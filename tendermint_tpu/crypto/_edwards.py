"""Pure-Python edwards25519 arithmetic and ZIP-215 ed25519 verification.

This module is the *semantics oracle* for the TPU verification engine
(tendermint_tpu.ops): slow, obviously-correct big-int math used for
differential testing and as the host fallback when no accelerator path
applies.

Semantics match the reference's curve25519-voi configuration
(crypto/ed25519/ed25519.go:23-31, VerifyOptionsZIP_215):
  - A (pubkey) and R (sig[:32]) decode per RFC 8032 §5.1.3 decompression
    *without* the canonical-y check (y is reduced mod p), i.e. non-canonical
    encodings are accepted;
  - small-order / mixed-order points are accepted;
  - s (sig[32:]) must be canonical: 0 <= s < L;
  - the verification equation is cofactored: [8]([s]B - R - [k]A) == O,
    with k = SHA512(R || A || msg) mod L.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

# Field and group parameters for edwards25519 (RFC 7748 / RFC 8032).
P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1), p ≡ 5 (mod 8)

# Extended homogeneous coordinates (X, Y, Z, T): x = X/Z, y = Y/Z, x*y = T/Z.
Point = Tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)

# Base point: y = 4/5, x recovered with even parity... sign bit 0 per RFC 8032.
_by = (4 * pow(5, P - 2, P)) % P


def _sqrt_ratio(u: int, v: int) -> Optional[int]:
    """Return r with v*r^2 == u (mod p), or None if u/v is not a square.

    Uses the p ≡ 5 (mod 8) trick: candidate r = u*v^3 * (u*v^7)^((p-5)/8);
    correct by sqrt(-1) if needed (RFC 8032 §5.1.3 step 3).
    """
    v3 = (v * v % P) * v % P
    v7 = (v3 * v3 % P) * v % P
    r = (u * v3 % P) * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    if check == u % P:
        return r
    if check == (P - u) % P:
        return r * SQRT_M1 % P
    return None


def decompress(s: bytes, allow_noncanonical: bool = True) -> Optional[Point]:
    """Decode a 32-byte point encoding -> extended point, or None.

    ZIP-215 mode (allow_noncanonical=True) follows curve25519-dalek's
    decompression (which ZIP 215 specifies and curve25519-voi implements):
    the y < p canonicity check is skipped AND the RFC 8032 §5.1.3 step-4
    rule ("x = 0 with sign bit 1 fails") is dropped — a conditional negate
    of x = 0 is a no-op, so "negative zero" encodings decode to x = 0.
    Strict mode applies both RFC 8032 checks.
    """
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little") & ((1 << 255) - 1)
    sign = s[31] >> 7
    if not allow_noncanonical and y >= P:
        return None
    y %= P
    yy = y * y % P
    u = (yy - 1) % P
    v = (D * yy + 1) % P
    x = _sqrt_ratio(u, v)
    if x is None:
        return None
    if x == 0 and sign and not allow_noncanonical:
        return None
    if (x & 1) != sign:
        x = P - x
    return (x, y, 1, x * y % P)


def compress(pt: Point) -> bytes:
    x, y, z, _ = pt
    zi = pow(z, P - 2, P)
    x = x * zi % P
    y = y * zi % P
    s = y | ((x & 1) << 255)
    return s.to_bytes(32, "little")


def point_add(p: Point, q: Point) -> Point:
    """Unified addition (add-2008-hwcd-3 with a=-1); complete for all inputs
    since a=-1 is square and d is non-square mod p."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = t1 * D2 % P * t2 % P
    d = 2 * z1 * z2 % P
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_double(p: Point) -> Point:
    """Dedicated doubling (dbl-2008-hwcd, a=-1)."""
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    d = (-a) % P
    e = ((x1 + y1) * (x1 + y1) - a - b) % P
    g = (d + b) % P
    f = (g - c) % P
    h = (d - b) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_neg(p: Point) -> Point:
    x, y, z, t = p
    return ((P - x) % P, y, z, (P - t) % P)


def scalar_mult(k: int, p: Point) -> Point:
    q = IDENTITY
    while k > 0:
        if k & 1:
            q = point_add(q, p)
        p = point_double(p)
        k >>= 1
    return q


def point_equal(p: Point, q: Point) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def is_identity(p: Point) -> bool:
    x, y, z, _ = p
    return x % P == 0 and (y - z) % P == 0


_bu = (_by * _by - 1) % P
_bv = (D * _by % P * _by + 1) % P
_bx = _sqrt_ratio(_bu, _bv)
assert _bx is not None
if _bx & 1:
    _bx = P - _bx
BASE: Point = (_bx, _by, 1, _bx * _by % P)


def mult_by_cofactor(p: Point) -> Point:
    return point_double(point_double(point_double(p)))


def challenge_scalar(r_enc: bytes, a_enc: bytes, msg: bytes) -> int:
    """k = SHA512(R || A || M) mod L (RFC 8032 verify step)."""
    h = hashlib.sha512(r_enc + a_enc + msg).digest()
    return int.from_bytes(h, "little") % L


def verify_zip215(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 single-signature verification (the oracle).

    Matches curve25519-voi VerifyWithOptions(..., VerifyOptionsZIP_215) as
    used at crypto/ed25519/ed25519.go:167.
    """
    if len(pub) != 32 or len(sig) != 64:
        return False
    a = decompress(pub)
    if a is None:
        return False
    r = decompress(sig[:32])
    if r is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = challenge_scalar(sig[:32], pub, msg)
    # [8]([s]B - R - [k]A) == O
    sb = scalar_mult(s, BASE)
    ka = scalar_mult(k, a)
    diff = point_add(sb, point_neg(point_add(r, ka)))
    return is_identity(mult_by_cofactor(diff))


def pubkey_from_seed(seed: bytes) -> bytes:
    """Derive the public key from a 32-byte seed (RFC 8032 §5.1.5)."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return compress(scalar_mult(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 §5.1.6 signing (pure-Python fallback; the package normally
    signs via the `cryptography` OpenSSL binding which is byte-identical)."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    prefix = h[32:]
    pub = compress(scalar_mult(a, BASE))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    r_enc = compress(scalar_mult(r, BASE))
    k = challenge_scalar(r_enc, pub, msg)
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")
