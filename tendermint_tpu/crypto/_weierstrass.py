"""Pure-Python secp256k1 (short Weierstrass) arithmetic and ECDSA.

This module is the *semantics oracle* for the batched secp256k1 device
lane (tendermint_tpu.ops.secp_verify), mirroring _edwards.py's role for
ed25519: slow, obviously-correct big-int math used for differential
testing and as the host fallback when the `cryptography` OpenSSL wheel
is absent (TM_TPU_PUREPY_CRYPTO=1 containers).

Semantics match the reference's btcec configuration
(crypto/secp256k1/secp256k1_nocgo.go:20-54):
  - signing is RFC 6979 deterministic (SHA-256 for both the message
    digest and the nonce HMAC), normalized to lower-S — byte-identical
    to the OpenSSL `deterministic_signing=True` path;
  - verification is plain ECDSA over SHA256(msg); the lower-S /
    range checks on (r, s) live in the caller (secp256k1.PubKey).

Points are affine (x, y) tuples; the identity is None. Modular
inversion via pow(x, -1, p) keeps every formula one line — this is an
oracle, not a hot path (the hot path is the device kernel).
"""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Tuple

# Field prime, curve order, and base point (SEC 2 v2, §2.4.1).
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
B = 7  # y^2 = x^3 + 7

Point = Optional[Tuple[int, int]]

G: Point = (GX, GY)


def point_add(p: Point, q: Point) -> Point:
    """Affine addition, complete over all inputs (identity = None)."""
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2:
        if (y1 + y2) % P == 0:  # q == -p (covers y == 0 doubling too)
            return None
        lam = (3 * x1 * x1) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def point_neg(p: Point) -> Point:
    if p is None:
        return None
    x, y = p
    return (x, (P - y) % P)


def scalar_mult(k: int, p: Point) -> Point:
    k %= N
    q: Point = None
    while k > 0:
        if k & 1:
            q = point_add(q, p)
        p = point_add(p, p)
        k >>= 1
    return q


def on_curve(p: Point) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - x * x * x - B) % P == 0


def decompress(pub: bytes) -> Point:
    """33-byte SEC1 compressed point -> affine point, or None if invalid.

    Matches OpenSSL's from_encoded_point acceptance: prefix 02/03,
    x < p, and x^3 + 7 must be a quadratic residue. p ≡ 3 (mod 4), so
    the candidate root is rhs^((p+1)/4) and one squaring checks it.
    """
    if len(pub) != 33 or pub[0] not in (2, 3):
        return None
    x = int.from_bytes(pub[1:], "big")
    if x >= P:
        return None
    rhs = (x * x * x + B) % P
    y = pow(rhs, (P + 1) // 4, P)
    if y * y % P != rhs:
        return None
    if (y & 1) != (pub[0] & 1):
        y = P - y
    return (x, y)


def compress(p: Point) -> bytes:
    assert p is not None
    x, y = p
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


def _rfc6979_nonce(x: bytes, h1: bytes, retry: int) -> int:
    """RFC 6979 §3.2 deterministic nonce (SHA-256; qlen == hlen == 256,
    so bits2int is the identity). `retry` extra K-update rounds handle
    the (astronomically rare) out-of-range / r==0 / s==0 candidates."""
    h2o = (int.from_bytes(h1, "big") % N).to_bytes(32, "big")  # bits2octets
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h2o, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h2o, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 0 < cand < N and retry == 0:
            return cand
        if 0 < cand < N:
            retry -= 1
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign_digest(d: int, digest: bytes) -> Tuple[int, int]:
    """ECDSA over a 32-byte digest with the RFC 6979 nonce; returns the
    raw (r, s) pair — lower-S normalization is the caller's concern."""
    e = int.from_bytes(digest, "big") % N
    x = d.to_bytes(32, "big")
    retry = 0
    while True:
        nonce = _rfc6979_nonce(x, digest, retry)
        pt = scalar_mult(nonce, G)
        assert pt is not None
        r = pt[0] % N
        if r != 0:
            s = (e + r * d) * pow(nonce, -1, N) % N
            if s != 0:
                return r, s
        retry += 1  # pragma: no cover


def verify_digest(pub_point: Point, digest: bytes, r: int, s: int) -> bool:
    """Plain ECDSA verify: R' = (e/s)G + (r/s)Q, accept iff R'.x ≡ r (mod n).
    Range checks on (r, s) are the caller's concern."""
    if pub_point is None or not on_curve(pub_point):
        return False
    e = int.from_bytes(digest, "big") % N
    w = pow(s, -1, N)
    rp = point_add(
        scalar_mult(e * w % N, G), scalar_mult(r * w % N, pub_point)
    )
    if rp is None:
        return False
    return rp[0] % N == r % N
