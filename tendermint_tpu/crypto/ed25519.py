"""Ed25519 keys with ZIP-215 verification semantics.

Reference parity: crypto/ed25519/ed25519.go —
  - PrivKey is 64 bytes: seed || pubkey (Go crypto/ed25519 format, :66-81)
  - PubKey.Address() = SHA256(pub)[:20] (:155-160)
  - VerifySignature uses ZIP-215 semantics (:23-31,167)
  - BatchVerifier seam (:192-227) — here, the device engine plugs in via
    crypto.batch (see tendermint_tpu/crypto/batch.py).

Verification strategy: try the OpenSSL (`cryptography`) verifier first — its
acceptance set (cofactorless + canonical encodings + s < L) is a strict
subset of ZIP-215's, so an OpenSSL accept is always a ZIP-215 accept and is
~100x faster than pure Python. Only on rejection do we run the exact ZIP-215
oracle to decide edge cases (non-canonical/small-order points).
"""

from __future__ import annotations

import os

try:  # OpenSSL fast path. With TM_TPU_PUREPY_CRYPTO=1 a container
    # without the wheel runs the pure-Python _edwards implementation
    # instead (identical bytes, ~3ms/op — far too slow for a validator,
    # useful for airgapped tooling and tests); without the opt-in a
    # missing wheel stays a hard import error rather than a silent
    # 1000x slowdown.
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    _HAVE_OPENSSL = True
except ModuleNotFoundError:
    if not os.environ.get("TM_TPU_PUREPY_CRYPTO"):
        raise
    _HAVE_OPENSSL = False

from . import PrivKey as _PrivKey, PubKey as _PubKey, address_hash, register_key_type
from . import _edwards

KEY_TYPE = "ed25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 64  # seed || pubkey
SIGNATURE_SIZE = 64
SEED_SIZE = 32

PUB_KEY_NAME = "tendermint/PubKeyEd25519"
PRIV_KEY_NAME = "tendermint/PrivKeyEd25519"


def verify_zip215_fast(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 verify with OpenSSL fast path (see module docstring)."""
    if len(sig) != SIGNATURE_SIZE or len(pub) != PUB_KEY_SIZE:
        return False
    if _HAVE_OPENSSL:
        try:
            Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            pass
    return _edwards.verify_zip215(pub, msg, sig)


class PubKey(_PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return address_hash(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        return verify_zip215_fast(self._bytes, msg, sig)

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(_PrivKey):
    __slots__ = ("_bytes", "_sk")

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"ed25519 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._sk = (
            Ed25519PrivateKey.from_private_bytes(self._bytes[:SEED_SIZE])
            if _HAVE_OPENSSL
            else None
        )

    def sign(self, msg: bytes) -> bytes:
        if self._sk is not None:
            return self._sk.sign(msg)
        return _edwards.sign(self._bytes[:SEED_SIZE], msg)

    def pub_key(self) -> PubKey:
        return PubKey(self._bytes[SEED_SIZE:])

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key(seed: bytes | None = None) -> PrivKey:
    """Generate a private key (crypto/ed25519/ed25519.go:113-137)."""
    if seed is None:
        seed = os.urandom(SEED_SIZE)
    if len(seed) != SEED_SIZE:
        raise ValueError(f"seed must be {SEED_SIZE} bytes")
    if _HAVE_OPENSSL:
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        pub = sk.public_key().public_bytes_raw()
    else:
        pub = _edwards.pubkey_from_seed(seed)
    return PrivKey(seed + pub)


register_key_type(KEY_TYPE, PubKey, PUB_KEY_SIZE)
