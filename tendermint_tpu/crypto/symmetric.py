"""Symmetric crypto utilities (SURVEY §1 L1).

Two self-contained constructions the reference ships next to the key
crypto:

- XChaCha20-Poly1305 AEAD (crypto/xchacha20poly1305/xchachapoly.go):
  HChaCha20 subkey from the first 16 nonce bytes, then standard
  ChaCha20-Poly1305 (via OpenSSL through `cryptography`) with a 12-byte
  subnonce of 4 zero bytes + the last 8 nonce bytes. 24-byte nonces are
  safe to pick at random.
- xsalsa20symmetric (crypto/xsalsa20symmetric/symmetric.go): NaCl
  secretbox (XSalsa20 + Poly1305) with a random 24-byte nonce prepended
  to the box. Salsa20 core and Poly1305 are implemented here from the
  public specifications (no nacl binding in this image); this is
  operator-tooling crypto (key files), not a hot path.
"""

from __future__ import annotations

import os
import struct

KEY_SIZE = 32
NONCE_SIZE = 24
TAG_SIZE = 16


# ---------------------------------------------------------------------------
# ChaCha20 / HChaCha20
# ---------------------------------------------------------------------------

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
_M32 = 0xFFFFFFFF


def _rotl32(v: int, n: int) -> int:
    return ((v << n) | (v >> (32 - n))) & _M32


def _chacha_quarter(s, a, b, c, d) -> None:
    s[a] = (s[a] + s[b]) & _M32
    s[d] = _rotl32(s[d] ^ s[a], 16)
    s[c] = (s[c] + s[d]) & _M32
    s[b] = _rotl32(s[b] ^ s[c], 12)
    s[a] = (s[a] + s[b]) & _M32
    s[d] = _rotl32(s[d] ^ s[a], 8)
    s[c] = (s[c] + s[d]) & _M32
    s[b] = _rotl32(s[b] ^ s[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """32-byte subkey from a 256-bit key and a 128-bit nonce (the XChaCha
    KDF; xchachapoly.go HChaCha20)."""
    if len(key) != KEY_SIZE:
        raise ValueError("hchacha20: key must be 32 bytes")
    if len(nonce16) < 16:
        raise ValueError("hchacha20: nonce must be at least 16 bytes")
    s = list(_SIGMA) + list(struct.unpack("<8L", key)) + list(
        struct.unpack("<4L", nonce16[:16])
    )
    for _ in range(10):
        _chacha_quarter(s, 0, 4, 8, 12)
        _chacha_quarter(s, 1, 5, 9, 13)
        _chacha_quarter(s, 2, 6, 10, 14)
        _chacha_quarter(s, 3, 7, 11, 15)
        _chacha_quarter(s, 0, 5, 10, 15)
        _chacha_quarter(s, 1, 6, 11, 12)
        _chacha_quarter(s, 2, 7, 8, 13)
        _chacha_quarter(s, 3, 4, 9, 14)
    return struct.pack("<4L", *s[0:4]) + struct.pack("<4L", *s[12:16])


class XChaCha20Poly1305:
    """crypto.AEAD parity with crypto/xchacha20poly1305 (24-byte nonces)."""

    def __init__(self, key: bytes):
        if len(key) != KEY_SIZE:
            raise ValueError("xchacha20poly1305: bad key length")
        self._key = bytes(key)

    @property
    def nonce_size(self) -> int:
        return NONCE_SIZE

    @property
    def overhead(self) -> int:
        return TAG_SIZE

    def _inner(self, nonce: bytes):
        from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

        if len(nonce) != NONCE_SIZE:
            raise ValueError("xchacha20poly1305: bad nonce length")
        subkey = hchacha20(self._key, nonce[:16])
        subnonce = b"\x00" * 4 + nonce[16:]
        return ChaCha20Poly1305(subkey), subnonce

    def seal(self, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
        aead, subnonce = self._inner(nonce)
        return aead.encrypt(subnonce, plaintext, aad or None)

    def open(self, nonce: bytes, ciphertext: bytes, aad: bytes = b"") -> bytes:
        from cryptography.exceptions import InvalidTag

        aead, subnonce = self._inner(nonce)
        try:
            return aead.decrypt(subnonce, ciphertext, aad or None)
        except InvalidTag:
            raise ValueError("xchacha20poly1305: message authentication failed")


# ---------------------------------------------------------------------------
# Salsa20 / XSalsa20 secretbox
# ---------------------------------------------------------------------------


def _salsa_quarter(s, a, b, c, d) -> None:
    s[b] ^= _rotl32((s[a] + s[d]) & _M32, 7)
    s[c] ^= _rotl32((s[b] + s[a]) & _M32, 9)
    s[d] ^= _rotl32((s[c] + s[b]) & _M32, 13)
    s[a] ^= _rotl32((s[d] + s[c]) & _M32, 18)


def _salsa20_rounds(state):
    s = list(state)
    for _ in range(10):
        _salsa_quarter(s, 0, 4, 8, 12)
        _salsa_quarter(s, 5, 9, 13, 1)
        _salsa_quarter(s, 10, 14, 2, 6)
        _salsa_quarter(s, 15, 3, 7, 11)
        _salsa_quarter(s, 0, 1, 2, 3)
        _salsa_quarter(s, 5, 6, 7, 4)
        _salsa_quarter(s, 10, 11, 8, 9)
        _salsa_quarter(s, 15, 12, 13, 14)
    return s


def _salsa20_block(key: bytes, nonce8: bytes, counter: int) -> bytes:
    k = struct.unpack("<8L", key)
    n = struct.unpack("<2L", nonce8)
    state = (
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        counter & _M32, (counter >> 32) & _M32, _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    )
    s = _salsa20_rounds(state)
    return struct.pack("<16L", *((a + b) & _M32 for a, b in zip(s, state)))


def hsalsa20(key: bytes, nonce16: bytes) -> bytes:
    """XSalsa20 KDF: 32 output bytes from key + 16-byte nonce."""
    k = struct.unpack("<8L", key)
    n = struct.unpack("<4L", nonce16)
    state = (
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        n[2], n[3], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    )
    s = _salsa20_rounds(state)
    out = [s[0], s[5], s[10], s[15], s[6], s[7], s[8], s[9]]
    return struct.pack("<8L", *out)


def _xsalsa20_stream(key: bytes, nonce24: bytes, length: int, first_block=b""):
    """Keystream bytes [0, length) of XSalsa20; first_block gives bytes
    0..63 already computed (block reuse between MAC key and payload)."""
    subkey = hsalsa20(key, nonce24[:16])
    out = bytearray(first_block)
    counter = len(first_block) // 64
    while len(out) < length:
        out += _salsa20_block(subkey, nonce24[16:], counter)
        counter += 1
    return bytes(out[:length])


_P1305 = (1 << 130) - 5


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        acc = (acc + int.from_bytes(block, "little") + (1 << (8 * len(block)))) * r % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def secretbox_seal(plaintext: bytes, key: bytes, nonce: bytes) -> bytes:
    """NaCl secretbox: returns poly1305 tag || xsalsa20-xor ciphertext."""
    stream = _xsalsa20_stream(key, nonce, 32 + len(plaintext))
    mac_key, pad = stream[:32], stream[32:64]
    # NaCl xors the plaintext against the stream starting at byte 32
    ct = bytes(p ^ k for p, k in zip(plaintext, stream[32:]))
    tag = _poly1305(mac_key, ct)
    return tag + ct


def secretbox_open(box: bytes, key: bytes, nonce: bytes) -> bytes:
    import hmac as _hmac

    if len(box) < TAG_SIZE:
        raise ValueError("ciphertext is too short")
    tag, ct = box[:TAG_SIZE], box[TAG_SIZE:]
    stream = _xsalsa20_stream(key, nonce, 32 + len(ct))
    mac_key = stream[:32]
    if not _hmac.compare_digest(tag, _poly1305(mac_key, ct)):
        raise ValueError("ciphertext decryption failed")
    return bytes(c ^ k for c, k in zip(ct, stream[32:]))


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """xsalsa20symmetric.EncryptSymmetric: random 24-byte nonce || box.
    Ciphertext is (16 + 24) bytes longer than the plaintext."""
    if len(secret) != KEY_SIZE:
        raise ValueError(f"secret must be 32 bytes long, got len {len(secret)}")
    nonce = os.urandom(NONCE_SIZE)
    return nonce + secretbox_seal(plaintext, secret, nonce)


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    """xsalsa20symmetric.DecryptSymmetric."""
    if len(secret) != KEY_SIZE:
        raise ValueError(f"secret must be 32 bytes long, got len {len(secret)}")
    if len(ciphertext) <= TAG_SIZE + NONCE_SIZE:
        raise ValueError("ciphertext is too short")
    nonce, box = ciphertext[:NONCE_SIZE], ciphertext[NONCE_SIZE:]
    return secretbox_open(box, secret, nonce)
