"""sr25519 — Schnorr signatures over ristretto255 (schnorrkel flavor).

Reference parity: crypto/sr25519/ backed by curve25519-voi's schnorrkel:
  - PrivKey is a 32-byte MiniSecretKey, expanded ExpandEd25519-style
    (SHA-512, ed25519 clamping, divide-by-cofactor) to (scalar, nonce)
  - signing context is "substrate" (crypto/sr25519/signature.go)
  - transcript protocol: merlin "SigningContext" / "Schnorr-sig" framing
  - signatures are R || s with the schnorrkel v1 marker bit (s[31] |= 0x80)
  - verification: R == [s]B - [k]A with k = transcript challenge
Batch verification is per-signature here (semantically identical to the
RLC batch, which falls back per-sig on failure anyway — mirrors the
ed25519 device-engine decision in ops/ed25519_verify.py).
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

from . import BatchVerifier as _BatchVerifier
from . import PrivKey as _PrivKey, PubKey as _PubKey, address_hash, register_key_type
from . import _merlin, _ristretto as R

KEY_TYPE = "sr25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 32  # MiniSecretKey
SIGNATURE_SIZE = 64

PUB_KEY_NAME = "tendermint/PubKeySr25519"
PRIV_KEY_NAME = "tendermint/PrivKeySr25519"

SIGNING_CTX = b"substrate"

L = R.L


def _expand_ed25519(mini: bytes) -> Tuple[int, bytes]:
    """MiniSecretKey.ExpandEd25519: (scalar, nonce)."""
    h = hashlib.sha512(mini).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    # divide by cofactor: right-shift the 256-bit LE integer by 3
    scalar = int.from_bytes(bytes(key), "little") >> 3
    return scalar % L, h[32:]


def _signing_transcript(msg: bytes) -> "_merlin.Transcript":
    t = _merlin.Transcript(b"SigningContext")
    t.append_message(b"", SIGNING_CTX)
    t.append_message(b"sign-bytes", msg)
    return t


def _challenge_scalar(t: "_merlin.Transcript", label: bytes) -> int:
    return int.from_bytes(t.challenge_bytes(label, 64), "little") % L


def sign(mini: bytes, msg: bytes) -> bytes:
    scalar, nonce = _expand_ed25519(mini)
    pub_pt = R.scalar_mult(scalar, R.BASE)
    pub = R.encode(pub_pt)
    t = _signing_transcript(msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    r = int.from_bytes(t.witness_bytes(b"signing", [nonce], 64), "little") % L
    r_enc = R.encode(R.scalar_mult(r, R.BASE))
    t.append_message(b"sign:R", r_enc)
    k = _challenge_scalar(t, b"sign:c")
    s = (k * scalar + r) % L
    sig = bytearray(r_enc + s.to_bytes(32, "little"))
    sig[63] |= 0x80  # schnorrkel v1 marker
    return bytes(sig)


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != SIGNATURE_SIZE or len(pub) != PUB_KEY_SIZE:
        return False
    if not (sig[63] & 0x80):
        return False  # not a schnorrkel v1 signature
    a_pt = R.decode(pub)
    if a_pt is None:
        return False
    r_bytes = sig[:32]
    r_pt = R.decode(r_bytes)
    if r_pt is None:
        return False
    s_bytes = bytearray(sig[32:])
    s_bytes[31] &= 0x7F
    s = int.from_bytes(bytes(s_bytes), "little")
    if s >= L:
        return False
    t = _signing_transcript(msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    t.append_message(b"sign:R", r_bytes)
    k = _challenge_scalar(t, b"sign:c")
    # R == [s]B - [k]A
    sb = R.scalar_mult(s, R.BASE)
    ka = R.scalar_mult(k, a_pt)
    expected = R.add(sb, R.neg(ka))
    return R.equals(expected, r_pt)


class PubKey(_PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"sr25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return address_hash(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        return verify(self._bytes, msg, sig)

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(_PrivKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"sr25519 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def sign(self, msg: bytes) -> bytes:
        return sign(self._bytes, msg)

    def pub_key(self) -> PubKey:
        scalar, _ = _expand_ed25519(self._bytes)
        return PubKey(R.encode(R.scalar_mult(scalar, R.BASE)))

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE


def verify_batch(entries: List[Tuple[bytes, bytes, bytes]]) -> List[bool]:
    """Per-signature verdicts for (pub, msg, sig) triples. Routes through
    the native C lane (tm_native.sr25519_verify_batch — full schnorrkel
    verify incl. merlin transcript, ~16x the pure-Python path) when built;
    the Python implementation is the fallback and differential oracle."""
    from ..native import load as _load_native

    native = _load_native()
    if native is not None and hasattr(native, "sr25519_verify_batch"):
        ok_shape = all(
            len(p) == PUB_KEY_SIZE and len(s) == SIGNATURE_SIZE
            for p, _, s in entries
        )
        if ok_shape:
            out = native.sr25519_verify_batch(
                SIGNING_CTX,
                b"".join(p for p, _, _ in entries),
                b"".join(s for _, _, s in entries),
                [m for _, m, _ in entries],
            )
            return [bool(b) for b in out]
    return [verify(p, m, s) for p, m, s in entries]


class BatchVerifier(_BatchVerifier):
    """crypto/sr25519/batch.go:13-19 semantics (per-sig evaluation)."""

    def __init__(self):
        self._entries: List[Tuple[bytes, bytes, bytes]] = []

    def add(self, key, msg: bytes, sig: bytes) -> None:
        if not isinstance(key, PubKey):
            raise TypeError("pubkey is not sr25519")
        if len(sig) != SIGNATURE_SIZE:
            raise ValueError("invalid signature length")
        self._entries.append((key.bytes(), msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        valid = verify_batch(self._entries)
        return all(valid) and len(valid) > 0, valid


def gen_priv_key(seed: bytes | None = None) -> PrivKey:
    return PrivKey(seed if seed is not None else os.urandom(PRIV_KEY_SIZE))


register_key_type(KEY_TYPE, PubKey, PUB_KEY_SIZE)
