"""sr25519 (Schnorr over ristretto255, schnorrkel flavor).

Reference parity: crypto/sr25519/ — pubkey/privkey/batch verifier backed by
curve25519-voi's schnorrkel implementation. Signing context is the
schnorrkel default "substrate" context used by the reference
(crypto/sr25519/signature.go).

Status: key container + address/type plumbing are complete (enough for
encoding, validator sets and config); sign/verify land with the
ristretto255 + merlin transcript implementation (tracked in README
roadmap). Verification raises rather than returning False so nothing can
silently treat unimplemented crypto as an invalid-signature result.
"""

from __future__ import annotations

import os

from . import PrivKey as _PrivKey, PubKey as _PubKey, address_hash, register_key_type

KEY_TYPE = "sr25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 32
SIGNATURE_SIZE = 64

PUB_KEY_NAME = "tendermint/PubKeySr25519"
PRIV_KEY_NAME = "tendermint/PrivKeySr25519"


class PubKey(_PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"sr25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return address_hash(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        raise NotImplementedError("sr25519 verification not yet implemented")

    def type(self) -> str:
        return KEY_TYPE


class PrivKey(_PrivKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"sr25519 privkey must be {PRIV_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def sign(self, msg: bytes) -> bytes:
        raise NotImplementedError("sr25519 signing not yet implemented")

    def pub_key(self) -> PubKey:
        raise NotImplementedError("sr25519 key derivation not yet implemented")

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key(seed: bytes | None = None) -> PrivKey:
    return PrivKey(seed if seed is not None else os.urandom(PRIV_KEY_SIZE))


register_key_type(KEY_TYPE, PubKey, PUB_KEY_SIZE)
