"""Low-overhead span tracer for the commit-verify hot path.

Design constraints (ISSUE 1):

- **~zero cost when disabled.** `span()` checks one module-global's
  `enabled` attribute and returns a shared null context manager before any
  clock read happens; no strings are formatted, no dicts are stored.
- **Thread-safe ring buffer.** Records are fixed-size tuples written under
  a lock into a preallocated ring; the buffer never grows, old spans are
  overwritten (wraparound), and recording is O(1) per span. Spans are
  recorded per *batch* (host prep, device dispatch, device wait), never
  per signature, so the lock is uncontended in practice.
- **Nested spans.** Nesting falls out of the `with` discipline: a child's
  [start, end) interval is contained in its parent's on the same thread,
  which is exactly how Chrome-trace/Perfetto reconstruct the flame graph
  from "X" (complete) events sharing a tid.
- **Chrome-trace export.** `export_chrome()` emits the Trace Event Format
  JSON (`{"traceEvents": [...]}`) loadable in chrome://tracing or
  https://ui.perfetto.dev; `dump(path)` writes it to disk (the node's
  OnStop flushes through this so a SIGTERM run leaves a complete file).

Causal cross-node tracing (ISSUE 10):

- **Flow events.** A span may carry a correlation id (`flow=` + a
  `flow_phase` of "s"/"t"/"f" — start/step/finish); `export_chrome()`
  emits matching Trace Event Format flow events bound to the slice, so a
  vote's journey (gossip send → deliver → verify dispatch) renders as a
  clickable arrow chain in Perfetto. `next_flow()` allocates process-wide
  ids (offset above 2^32 so they never collide with a simulation's own
  deterministic per-clock flow counters).
- **Per-node tracer instances.** `SpanTracer(node=..., now=..., epoch=...)`
  stamps every exported event with a per-node pid (+ a `process_name`
  metadata event) and reads time from an injected clock — simnet gives
  each simulated node a tracer on the shared virtual clock, so one merged
  trace aligns every node on the same (virtual) timebase.
- **Merging.** `merge_traces([doc, ...])` re-keys pids and concatenates
  event streams into ONE Chrome-trace document; flow ids are preserved
  verbatim so cross-document chains stay linked.

Enable via config (`[instrumentation] tracing = true`), env
(`TM_TPU_TRACE=1`), or `configure(enabled=True)`.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..libs import devcheck as _devcheck

_PID = os.getpid()

# A record is (name, start_s, end_s, tid, args_or_None); start/end are
# readings of the tracer's clock (perf_counter by default) against the
# tracer's epoch. Flow correlation rides INSIDE args under the reserved
# keys "flow" (int id) and "flow_phase" ("s"|"t"|"f") so the tuple shape
# — and every 5-tuple consumer — stays stable.
_Record = Tuple[str, float, float, int, Optional[dict]]

# Process-wide flow-id allocator for the wall-clock tracer. Offset far
# above any simulation's per-SimClock counter (which starts at 1) so a
# merged trace never aliases two unrelated chains onto one id.
_FLOW_BASE = 1 << 32
_flow_counter = itertools.count(_FLOW_BASE + 1)


def next_flow() -> int:
    """Allocate a process-unique flow (correlation) id."""
    return next(_flow_counter)


def set_flow_domain(domain: int) -> None:
    """Re-base this process's flow allocator into a disjoint id range.

    The verification fleet (ISSUE 18) merges traces from MANY processes
    — client nodes and the fleet host — into one flight-recorder view;
    each process calls this once at startup (TM_TPU_FLEET_FLOW_DOMAIN)
    with a distinct small integer so allocated flow ids can never alias
    across the merge. Domain 0 is the default base. Flows CONTINUED
    from a wire frame keep the originator's id — that is the point: the
    chain client-submit → fleet-recv → verdict shares one id, and this
    partition guarantees the fleet's own locally-started flows stay out
    of every client's range.
    """
    global _flow_counter
    base = _FLOW_BASE + (int(domain) & 0xFFFF) * (1 << 24)
    _flow_counter = itertools.count(base + 1)

# Per-node tracers get small deterministic pids well away from real OS
# pids; assignment order is the tracer construction order.
_node_pid_mtx = threading.Lock()
_node_pids: Dict[int, int] = {}  # id(tracer) -> pid
_NODE_PID_BASE = 10_000_000


class SpanTracer:
    """Ring-buffered span recorder. One process-wide wall-clock instance
    (TRACER); per-node instances (simnet) carry a node name and an
    injected clock."""

    def __init__(self, capacity: int = 16384, node: Optional[str] = None,
                 now: Optional[Callable[[], float]] = None,
                 epoch: Optional[float] = None):
        self.enabled = False
        self.node = node
        self._now = now if now is not None else time.perf_counter
        # inbound-flow register: a delivery driver parks the active flow
        # id here so downstream spans (consensus.verify_dispatch) can
        # continue the chain; single-threaded drivers only
        self.flow: Optional[int] = None
        self._cap = max(int(capacity), 16)
        self._buf: List[Optional[_Record]] = [None] * self._cap
        self._n = 0  # monotonic write index; wraps over _cap
        self._mtx = threading.Lock()
        self._epoch = float(epoch) if epoch is not None else self._now()

    # -- recording -----------------------------------------------------

    def record(self, name: str, start: float, end: float,
               args: Optional[dict] = None, flow: Optional[int] = None,
               flow_phase: Optional[str] = None) -> None:
        """Record one completed span (clock start/end). `flow`/`flow_phase`
        attach a correlation id under the reserved args keys."""
        if flow is not None:
            args = dict(args) if args else {}
            args["flow"] = int(flow)
            args["flow_phase"] = flow_phase or "t"
        rec = (name, start, end, threading.get_ident(), args)
        with self._mtx:
            self._buf[self._n % self._cap] = rec
            self._n += 1

    def span(self, name: str, flow: Optional[int] = None,
             flow_phase: Optional[str] = None, **args) -> object:
        """Context manager recording on THIS tracer (per-node instances);
        same disabled-path contract as the module-level span()."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None, flow, flow_phase)

    def flow_point(self, name: str, flow: Optional[int],
                   phase: str = "t", **args) -> None:
        """Record an instant (zero-duration) event carrying a flow id —
        how one coalesced batch fans a step/finish out to many chains."""
        if not self.enabled or flow is None:
            return
        t = self._now()
        self.record(name, t, t, args or None, flow=flow, flow_phase=phase)

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None) -> None:
        with self._mtx:
            if capacity is not None and int(capacity) != self._cap:
                self._cap = max(int(capacity), 16)
                self._buf = [None] * self._cap
                self._n = 0
        if enabled is not None:
            self.enabled = bool(enabled)

    def close(self) -> None:
        """Retire the tracer: under TM_TPU_DEVCHECK=1 assert every span
        opened on every thread was closed (the unbalanced-span canary —
        a leaked span skews every summary that trusts nesting)."""
        _devcheck.span_check(f"tracer.close({self.node or 'global'})")

    def clear(self) -> None:
        with self._mtx:
            self._buf = [None] * self._cap
            self._n = 0

    # -- reading -------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def recorded_total(self) -> int:
        """Total spans ever recorded (>= len(events()) after wraparound)."""
        return self._n

    def events(self) -> List[_Record]:
        """Retained records, oldest first."""
        with self._mtx:
            if self._n <= self._cap:
                return [r for r in self._buf[: self._n] if r is not None]
            head = self._n % self._cap
            return [r for r in self._buf[head:] + self._buf[:head]
                    if r is not None]

    def _pid(self) -> int:
        if self.node is None:
            return _PID
        with _node_pid_mtx:
            pid = _node_pids.get(id(self))
            if pid is None:
                pid = _NODE_PID_BASE + len(_node_pids) + 1
                _node_pids[id(self)] = pid
            return pid

    def export_chrome(self) -> dict:
        """Trace Event Format dict (chrome://tracing / Perfetto JSON).
        Spans carrying a flow id additionally emit the matching flow
        event ("s"/"t"/"f", binding-point "e" on finish) at the slice's
        start timestamp, so Perfetto draws the causal arrows."""
        evs = []
        epoch = self._epoch
        pid = self._pid()
        for name, start, end, tid, args in self.events():
            ts = (start - epoch) * 1e6   # microseconds
            ev = {
                "name": name,
                "cat": "tendermint_tpu",
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": ts,
                "dur": (end - start) * 1e6,
            }
            if args:
                ev["args"] = args
                fid = args.get("flow")
                if fid is not None:
                    ph = args.get("flow_phase", "t")
                    if ph not in ("s", "t", "f"):
                        ph = "t"
                    fev = {
                        "name": "flow", "cat": "flow", "ph": ph,
                        "id": int(fid), "pid": pid, "tid": tid, "ts": ts,
                    }
                    if ph == "f":
                        fev["bp"] = "e"  # bind to the enclosing slice
                    evs.append(fev)
            evs.append(ev)
        evs.sort(key=lambda e: e["ts"])
        if self.node is not None:
            evs.insert(0, {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": self.node},
            })
        return {"traceEvents": evs, "displayTimeUnit": "ms"}

    def dump(self, path: str) -> str:
        """Write the Chrome-trace JSON to `path` (returns the path)."""
        return dump_doc(self.export_chrome(), path)

    def summary(self) -> Dict[str, dict]:
        return summarize_events(self.export_chrome())


class _Span:
    """Active span: records on exit. Only built when tracing is enabled."""

    __slots__ = ("_tr", "_name", "_args", "_flow", "_phase", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Optional[dict],
                 flow: Optional[int] = None, phase: Optional[str] = None):
        self._tr = tracer
        self._name = name
        self._args = args
        self._flow = flow
        self._phase = phase

    def __enter__(self) -> "_Span":
        if _devcheck.enabled():
            _devcheck.span_opened(self._name)
        self._t0 = self._tr._now()
        return self

    def __exit__(self, *exc) -> bool:
        tr = self._tr
        tr.record(self._name, self._t0, tr._now(), self._args,
                  flow=self._flow, flow_phase=self._phase)
        # unconditional (like DevLock.release): devcheck disabled between
        # enter and exit must still pop the armed-time push. The inject
        # seam leaks ONLY this bookkeeping (the span still records) so
        # the close()-time canary demonstrably fires.
        if not _devcheck.inject_lintbug("span"):
            _devcheck.span_closed(self._name)
        return False


class _NullSpan:
    """Disabled-path context manager: shared, allocation-free."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()

TRACER = SpanTracer(int(os.environ.get("TM_TPU_TRACE_BUFFER", "16384")))
if os.environ.get("TM_TPU_TRACE", "0") not in ("", "0"):
    TRACER.enabled = True


def span(name: str, flow: Optional[int] = None,
         flow_phase: Optional[str] = None, **args) -> object:
    """Context manager recording `name` with optional args (and an
    optional flow correlation id) on the process-wide TRACER.

    The disabled path returns a shared null object after a single attribute
    check — hot-path call sites need no `if` of their own (though sites
    that build expensive kwargs should still guard on `TRACER.enabled`).
    """
    if not TRACER.enabled:
        return _NULL_SPAN
    return _Span(TRACER, name, args or None, flow, flow_phase)


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None) -> None:
    TRACER.configure(enabled=enabled, capacity=capacity)


# ---------------------------------------------------------------------------
# Summaries (shared by tools/trace_report.py, bench.py, and /dump_trace)
# ---------------------------------------------------------------------------


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = (len(sorted_vals) - 1) * q
    lo = int(k)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = k - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def summarize_events(trace_doc: dict) -> Dict[str, dict]:
    """Per-span-name stats over a Chrome-trace dict: count, total/p50/p95/
    p99 ms. The `_wall` pseudo-entry carries the trace's wall-clock extent
    and `device_utilization` (fraction of wall covered by spans whose name
    contains "device", merged across overlaps)."""
    evs = trace_doc.get("traceEvents", [])
    by_name: Dict[str, List[float]] = {}
    t_min, t_max = float("inf"), float("-inf")
    device_iv: List[Tuple[float, float]] = []
    for ev in evs:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        ts = float(ev.get("ts", 0.0))
        by_name.setdefault(ev["name"], []).append(dur)
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
        if "device" in ev["name"]:
            device_iv.append((ts, ts + dur))
    out: Dict[str, dict] = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "total_ms": sum(durs) / 1e3,
            "p50_ms": _percentile(durs, 0.50) / 1e3,
            "p95_ms": _percentile(durs, 0.95) / 1e3,
            "p99_ms": _percentile(durs, 0.99) / 1e3,
        }
    wall_us = (t_max - t_min) if evs and t_max > t_min else 0.0
    # merge overlapping device intervals so concurrent dispatches do not
    # count double against the wall clock
    device_us = 0.0
    last_e = float("-inf")
    for s, e in sorted(device_iv):
        if s < last_e:
            if e > last_e:
                device_us += e - last_e
                last_e = e
        else:
            device_us += e - s
            last_e = e
    out["_wall"] = {
        "wall_ms": wall_us / 1e3,
        "device_utilization": (device_us / wall_us) if wall_us else 0.0,
        "events": len(evs),
    }
    return out


def dump_doc(doc: dict, path: str) -> str:
    """Atomically write a trace document as JSON: tmp file + rename, so a
    SIGTERM mid-dump never leaves a truncated file at the advertised
    path. Shared by SpanTracer.dump, simnet_run --trace and trace_report
    --out."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)
    return path


def merge_traces(docs: Sequence[dict],
                 labels: Optional[Sequence[str]] = None) -> dict:
    """Merge several Chrome-trace documents into ONE (ISSUE 10): pids are
    re-keyed per source document (collision-proof), `process_name`
    metadata survives (or is synthesized from `labels`), and flow ids are
    preserved VERBATIM — a flow started in one document and finished in
    another stays a single causal chain. Documents must share a timebase
    for the timeline to be meaningful (simnet's per-node tracers all read
    the same virtual clock)."""
    merged: List[dict] = []
    meta: List[dict] = []
    next_pid = 1
    for i, doc in enumerate(docs):
        label = labels[i] if labels is not None and i < len(labels) else None
        evs = doc.get("traceEvents", [])
        named = {
            ev.get("pid")
            for ev in evs
            if ev.get("ph") == "M" and ev.get("name") == "process_name"
        }
        pid_map: Dict[object, int] = {}
        for ev in evs:
            old = ev.get("pid", 0)
            new = pid_map.get(old)
            if new is None:
                new = pid_map[old] = next_pid
                next_pid += 1
            ev2 = dict(ev)
            ev2["pid"] = new
            if ev2.get("ph") == "M":
                meta.append(ev2)
            else:
                merged.append(ev2)
        for old, new in sorted(pid_map.items(), key=lambda kv: kv[1]):
            if old not in named:
                meta.append({
                    "name": "process_name", "ph": "M", "pid": new, "tid": 0,
                    "args": {"name": label or f"proc{new}"},
                })
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta + merged, "displayTimeUnit": "ms"}


def flow_chains(trace_doc: dict) -> Dict[int, List[dict]]:
    """Group a document's flow-carrying slices by flow id, each chain
    ordered (phase-aware: "s" first, "f" last, ties by ts). The merged-
    trace acceptance check — and the tests — read chains through this
    instead of re-parsing the event soup."""
    order = {"s": 0, "t": 1, "f": 2}
    chains: Dict[int, List[dict]] = {}
    for ev in trace_doc.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        fid = args.get("flow")
        if fid is None:
            continue
        chains.setdefault(int(fid), []).append(ev)
    for evs in chains.values():
        evs.sort(key=lambda e: (order.get((e.get("args") or {}).get(
            "flow_phase", "t"), 1), e.get("ts", 0.0)))
    return chains
