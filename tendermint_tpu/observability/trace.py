"""Low-overhead span tracer for the commit-verify hot path.

Design constraints (ISSUE 1):

- **~zero cost when disabled.** `span()` checks one module-global's
  `enabled` attribute and returns a shared null context manager before any
  clock read happens; no strings are formatted, no dicts are stored.
- **Thread-safe ring buffer.** Records are fixed-size tuples written under
  a lock into a preallocated ring; the buffer never grows, old spans are
  overwritten (wraparound), and recording is O(1) per span. Spans are
  recorded per *batch* (host prep, device dispatch, device wait), never
  per signature, so the lock is uncontended in practice.
- **Nested spans.** Nesting falls out of the `with` discipline: a child's
  [start, end) interval is contained in its parent's on the same thread,
  which is exactly how Chrome-trace/Perfetto reconstruct the flame graph
  from "X" (complete) events sharing a tid.
- **Chrome-trace export.** `export_chrome()` emits the Trace Event Format
  JSON (`{"traceEvents": [...]}`) loadable in chrome://tracing or
  https://ui.perfetto.dev; `dump(path)` writes it to disk (the node's
  OnStop flushes through this so a SIGTERM run leaves a complete file).

Enable via config (`[instrumentation] tracing = true`), env
(`TM_TPU_TRACE=1`), or `configure(enabled=True)`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_PID = os.getpid()

# A record is (name, start_s, end_s, tid, args_or_None); start/end are
# time.perf_counter() readings against the tracer's epoch.
_Record = Tuple[str, float, float, int, Optional[dict]]


class SpanTracer:
    """Ring-buffered span recorder. One process-wide instance (TRACER)."""

    def __init__(self, capacity: int = 16384):
        self.enabled = False
        self._cap = max(int(capacity), 16)
        self._buf: List[Optional[_Record]] = [None] * self._cap
        self._n = 0  # monotonic write index; wraps over _cap
        self._mtx = threading.Lock()
        self._epoch = time.perf_counter()

    # -- recording -----------------------------------------------------

    def record(self, name: str, start: float, end: float,
               args: Optional[dict] = None) -> None:
        """Record one completed span (perf_counter start/end)."""
        rec = (name, start, end, threading.get_ident(), args)
        with self._mtx:
            self._buf[self._n % self._cap] = rec
            self._n += 1

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None) -> None:
        with self._mtx:
            if capacity is not None and int(capacity) != self._cap:
                self._cap = max(int(capacity), 16)
                self._buf = [None] * self._cap
                self._n = 0
        if enabled is not None:
            self.enabled = bool(enabled)

    def clear(self) -> None:
        with self._mtx:
            self._buf = [None] * self._cap
            self._n = 0

    # -- reading -------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def recorded_total(self) -> int:
        """Total spans ever recorded (>= len(events()) after wraparound)."""
        return self._n

    def events(self) -> List[_Record]:
        """Retained records, oldest first."""
        with self._mtx:
            if self._n <= self._cap:
                return [r for r in self._buf[: self._n] if r is not None]
            head = self._n % self._cap
            return [r for r in self._buf[head:] + self._buf[:head]
                    if r is not None]

    def export_chrome(self) -> dict:
        """Trace Event Format dict (chrome://tracing / Perfetto JSON)."""
        evs = []
        epoch = self._epoch
        for name, start, end, tid, args in self.events():
            ev = {
                "name": name,
                "cat": "tendermint_tpu",
                "ph": "X",
                "pid": _PID,
                "tid": tid,
                "ts": (start - epoch) * 1e6,   # microseconds
                "dur": (end - start) * 1e6,
            }
            if args:
                ev["args"] = args
            evs.append(ev)
        evs.sort(key=lambda e: e["ts"])
        return {"traceEvents": evs, "displayTimeUnit": "ms"}

    def dump(self, path: str) -> str:
        """Write the Chrome-trace JSON to `path` (returns the path)."""
        doc = self.export_chrome()
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)  # atomic: a SIGTERM mid-dump never leaves
        return path            # a truncated file at the advertised path

    def summary(self) -> Dict[str, dict]:
        return summarize_events(self.export_chrome())


class _Span:
    """Active span: records on exit. Only built when tracing is enabled."""

    __slots__ = ("_name", "_args", "_t0")

    def __init__(self, name: str, args: Optional[dict]):
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        TRACER.record(self._name, self._t0, time.perf_counter(), self._args)
        return False


class _NullSpan:
    """Disabled-path context manager: shared, allocation-free."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()

TRACER = SpanTracer(int(os.environ.get("TM_TPU_TRACE_BUFFER", "16384")))
if os.environ.get("TM_TPU_TRACE", "0") not in ("", "0"):
    TRACER.enabled = True


def span(name: str, **args) -> object:
    """Context manager recording `name` with optional args.

    The disabled path returns a shared null object after a single attribute
    check — hot-path call sites need no `if` of their own (though sites
    that build expensive kwargs should still guard on `TRACER.enabled`).
    """
    if not TRACER.enabled:
        return _NULL_SPAN
    return _Span(name, args or None)


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None) -> None:
    TRACER.configure(enabled=enabled, capacity=capacity)


# ---------------------------------------------------------------------------
# Summaries (shared by tools/trace_report.py, bench.py, and /dump_trace)
# ---------------------------------------------------------------------------


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    k = (len(sorted_vals) - 1) * q
    lo = int(k)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = k - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def summarize_events(trace_doc: dict) -> Dict[str, dict]:
    """Per-span-name stats over a Chrome-trace dict: count, total/p50/p95/
    p99 ms. The `_wall` pseudo-entry carries the trace's wall-clock extent
    and `device_utilization` (fraction of wall covered by spans whose name
    contains "device", merged across overlaps)."""
    evs = trace_doc.get("traceEvents", [])
    by_name: Dict[str, List[float]] = {}
    t_min, t_max = float("inf"), float("-inf")
    device_iv: List[Tuple[float, float]] = []
    for ev in evs:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0))
        ts = float(ev.get("ts", 0.0))
        by_name.setdefault(ev["name"], []).append(dur)
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
        if "device" in ev["name"]:
            device_iv.append((ts, ts + dur))
    out: Dict[str, dict] = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "total_ms": sum(durs) / 1e3,
            "p50_ms": _percentile(durs, 0.50) / 1e3,
            "p95_ms": _percentile(durs, 0.95) / 1e3,
            "p99_ms": _percentile(durs, 0.99) / 1e3,
        }
    wall_us = (t_max - t_min) if evs and t_max > t_min else 0.0
    # merge overlapping device intervals so concurrent dispatches do not
    # count double against the wall clock
    device_us = 0.0
    last_e = float("-inf")
    for s, e in sorted(device_iv):
        if s < last_e:
            if e > last_e:
                device_us += e - last_e
                last_e = e
        else:
            device_us += e - s
            last_e = e
    out["_wall"] = {
        "wall_ms": wall_us / 1e3,
        "device_utilization": (device_us / wall_us) if wall_us else 0.0,
        "events": len(evs),
    }
    return out
