"""Observability — hot-path span tracing and live introspection.

The commit-verify path (VoteSet.add_vote → VerifyCommit → the bucketed
device batch verifier) is the north-star workload; this package makes its
wall-clock visible: `trace` provides a low-overhead span tracer with a
thread-safe ring buffer and Chrome-trace (Perfetto) export, and
`libs.metrics` (re-exported here for convenience) carries the Prometheus
metric sets the node serves on the instrumentation scrape endpoint.

Tracing is off by default and costs ~nothing when off: every instrument
site guards on `trace.TRACER.enabled` (a plain attribute read) before any
clock read, dict build, or string work happens.
"""

from . import trace  # noqa: F401
from .trace import (  # noqa: F401
    TRACER,
    SpanTracer,
    configure,
    flow_chains,
    merge_traces,
    next_flow,
    span,
)
