"""Time-series telemetry + declarative SLO budgets (ISSUE 16).

The soak harness needs three instruments the repo already half-has:

- **TelemetrySampler** — snapshots the existing gauge/counter surfaces
  (`Registry.snapshot()`, no exposition-text parsing) on a SimClock
  cadence into bounded rings. Tick scheduling rides `clock.call_later`,
  so the tick count and timestamps are pure functions of the virtual
  duration and cadence — deterministic under replay even though some
  sampled VALUES (wall-clock-derived counters) are not.
- **LatencyRecorder** — per-lane latency samples stamped with the
  virtual submit time (for windowing/localization) and the wall time
  (for correlating a breach window with tracer spans).
- **SLOBudget / evaluate_slos** — declarative per-lane budgets
  (latency p99 ceilings, rate floors) evaluated over the recorder;
  a breach is localized to the worst time window and, when span data
  is available, to the dominating span category inside that window.

Per-workload latency attribution sources: `HeightTimeline` rings give
the consensus lane's per-height commit latency in VIRTUAL seconds
(deterministic); the wall-clock tracer's `pipeline.*` spans (mesh_pack,
transfer, dispatch, queue_wait, device.wait) attribute where a wall
latency breach actually went.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..libs import metrics as _metrics

# Metric surfaces the sampler tracks by default — the ISSUE 16 list:
# epoch-cache traffic, dispatch/pipeline depth, transfer overlap, pool
# recycling, CheckTx preemptions, mesh packing efficiency.
DEFAULT_SERIES = (
    "tendermint_ops_epoch_cache_hits_total",
    "tendermint_ops_epoch_cache_misses_total",
    "tendermint_ops_epoch_cache_evictions_total",
    "tendermint_ops_dispatch_queue_depth",
    "tendermint_ops_pipeline_queue_depth",
    "tendermint_ops_pipeline_inflight",
    "tendermint_ops_dispatch_busy_ratio",
    "tendermint_ops_transfer_overlap_ratio",
    "tendermint_ops_buffer_pool_hits_total",
    "tendermint_ops_buffer_pool_misses_total",
    "tendermint_mempool_checktx_preemptions",
    "tendermint_ops_mesh_lane_occupancy",
    "tendermint_ops_mesh_pad_waste_ratio",
)


def _scalar(sample: dict) -> float:
    """Collapse one Registry.snapshot() metric entry to a scalar: sum
    across labelsets for counters/gauges, observation count for
    histograms (their sums/percentiles have dedicated readers)."""
    if sample.get("type") == "histogram":
        return float(sum(s["count"] for s in sample.get("series", {}).values()))
    return float(sum(sample.get("values", {}).values()))


class TelemetrySampler:
    """Bounded-ring gauge sampler on an injected (virtual) clock.

    `start()` schedules the first tick one cadence out; every tick
    re-schedules itself until `stop()`. Ticks read `registry.snapshot()`
    plus any registered extra sources (callables returning a float —
    e.g. a lane_counts() split) and append `(virtual_t, value)` to each
    series' ring. Ring capacity bounds memory for arbitrarily long
    soaks; `ticks` counts every tick ever taken (cadence determinism is
    `ticks == floor(duration / cadence)` — the prep_bench gate).
    """

    def __init__(self, clock, *, cadence_s: float = 1.0,
                 capacity: int = 600, registry=None,
                 series: Sequence[str] = DEFAULT_SERIES,
                 extra_sources: Optional[Dict[str, Callable[[], float]]] = None):
        self._clock = clock
        self.cadence_s = float(cadence_s)
        self.capacity = int(capacity)
        self._registry = registry  # None -> global_registry() at tick time
        self._names = tuple(series)
        self._extra: Dict[str, Callable[[], float]] = dict(extra_sources or {})
        self._rings: Dict[str, collections.deque] = {}
        self.ticks = 0
        self._stopped = False

    def add_source(self, name: str, fn: Callable[[], float]) -> None:
        self._extra[name] = fn

    def start(self) -> None:
        self._clock.call_later(self.cadence_s, self._tick)

    def stop(self) -> None:
        self._stopped = True

    def _append(self, name: str, t: float, v: float) -> None:
        ring = self._rings.get(name)
        if ring is None:
            ring = self._rings[name] = collections.deque(maxlen=self.capacity)
        ring.append((t, v))

    def _tick(self) -> None:
        if self._stopped:
            return
        self.ticks += 1
        t = self._clock.time()
        reg = self._registry if self._registry is not None \
            else _metrics.global_registry()
        snap = reg.snapshot()
        for name in self._names:
            s = snap.get(name)
            if s is not None:
                self._append(name, t, _scalar(s))
        for name, fn in self._extra.items():
            try:
                v = float(fn())
            except Exception:  # noqa: BLE001 — a source must not kill ticks
                continue
            self._append(name, t, v)
        self._clock.call_later(self.cadence_s, self._tick)

    def series(self) -> Dict[str, List[Tuple[float, float]]]:
        return {name: list(ring) for name, ring in self._rings.items()}


# ---------------------------------------------------------------------------
# Latency samples + percentiles + windows
# ---------------------------------------------------------------------------


def percentile(vals: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile over an unsorted sample list."""
    if not vals:
        return 0.0
    sv = sorted(vals)
    k = (len(sv) - 1) * q
    lo = int(k)
    hi = min(lo + 1, len(sv) - 1)
    frac = k - lo
    return sv[lo] * (1 - frac) + sv[hi] * frac


class LatencyRecorder:
    """Per-lane latency samples: (t_virtual, latency_ms, t_wall).

    `t_virtual` places the sample on the run's deterministic timeline
    (windowing, breach localization); `t_wall` (the recording clock's
    perf_counter reading, 0.0 when not supplied) lets a breach window be
    correlated with wall-clock tracer spans. Bounded per lane.
    """

    def __init__(self, capacity_per_lane: int = 200_000):
        self._cap = int(capacity_per_lane)
        self._by_lane: Dict[str, collections.deque] = {}

    def record(self, lane: str, t_virtual: float, latency_ms: float,
               t_wall: float = 0.0) -> None:
        ring = self._by_lane.get(lane)
        if ring is None:
            ring = self._by_lane[lane] = collections.deque(maxlen=self._cap)
        ring.append((float(t_virtual), float(latency_ms), float(t_wall)))

    def lanes(self) -> List[str]:
        return list(self._by_lane)

    def samples(self, lane: str) -> List[Tuple[float, float, float]]:
        return list(self._by_lane.get(lane, ()))

    def latencies(self, lane: str) -> List[float]:
        return [ms for _, ms, _ in self._by_lane.get(lane, ())]


def window_stats(samples: Sequence[Tuple[float, float, float]],
                 window_s: float) -> List[dict]:
    """Bucket (t_virtual, ms, t_wall) samples into fixed windows aligned
    to the earliest sample; per-window count/p50/p99 plus the wall-time
    extent covered by the window's samples (for span correlation)."""
    if not samples:
        return []
    w = max(float(window_s), 1e-9)
    t_base = min(t for t, _, _ in samples)
    buckets: Dict[int, List[Tuple[float, float, float]]] = {}
    for t, ms, tw in samples:
        buckets.setdefault(int((t - t_base) / w), []).append((t, ms, tw))
    out = []
    for i in sorted(buckets):
        grp = buckets[i]
        lats = [ms for _, ms, _ in grp]
        walls = [tw for _, _, tw in grp if tw > 0.0]
        ends = [tw + ms / 1e3 for _, ms, tw in grp if tw > 0.0]
        out.append({
            "t0": t_base + i * w,
            "t1": t_base + (i + 1) * w,
            "count": len(grp),
            "p50_ms": percentile(lats, 0.50),
            "p99_ms": percentile(lats, 0.99),
            "max_ms": max(lats),
            "wall_range": [min(walls), max(ends)] if walls else None,
        })
    return out


def timeline_latencies(timelines: Sequence[dict]
                       ) -> List[Tuple[float, float, float]]:
    """LatencyRecorder-shaped samples from HeightTimeline dicts: one
    (t_applied_virtual, total_ms, 0.0) per fully-applied height — the
    consensus lane's commit latency, in deterministic virtual time."""
    out = []
    for tl in timelines:
        total = tl.get("total_s")
        t_applied = tl.get("t_applied")
        if total is None or t_applied is None:
            continue
        out.append((float(t_applied), float(total) * 1e3, 0.0))
    return out


def attribute_spans(events: Sequence[tuple],
                    wall_range: Optional[Sequence[float]] = None
                    ) -> Dict[str, dict]:
    """Aggregate SpanTracer records (5-tuples: name, start, end, tid,
    args) by span name — total/count ms, sorted nothing, plain dict.
    With `wall_range=[w0, w1]`, only spans overlapping that interval
    count: that is how a breach window names its dominating category."""
    agg: Dict[str, dict] = {}
    w0, w1 = (wall_range if wall_range else (None, None))
    for rec in events:
        name, start, end = rec[0], rec[1], rec[2]
        if w0 is not None and (end < w0 or start > w1):
            continue
        a = agg.get(name)
        if a is None:
            a = agg[name] = {"count": 0, "total_ms": 0.0}
        a["count"] += 1
        a["total_ms"] += (end - start) * 1e3
    return agg


def dominant_span(agg: Dict[str, dict]) -> Optional[str]:
    """The span category carrying the most total time (pipeline.* spans
    preferred — they name a stage of the verify engine, which is what a
    lane-latency breach wants attributed)."""
    if not agg:
        return None
    pipeline = {k: v for k, v in agg.items() if k.startswith("pipeline.")}
    pool = pipeline or agg
    return max(pool.items(), key=lambda kv: (kv[1]["total_ms"], kv[0]))[0]


# ---------------------------------------------------------------------------
# Declarative SLO budgets
# ---------------------------------------------------------------------------

KIND_P99_MS_MAX = "p99_ms_max"   # breach when observed p99 RISES past limit
KIND_RATE_MIN = "rate_min"       # breach when observed rate FALLS below limit


@dataclass
class SLOBudget:
    """One declarative budget: `lane` names a LatencyRecorder lane (for
    p99 kinds) or a key in the `rates` dict (for rate floors)."""

    name: str
    lane: str
    kind: str
    limit: float
    min_samples: int = 1  # p99 over fewer samples than this is a breach
    description: str = ""


def evaluate_slos(budgets: Sequence[SLOBudget], recorder: LatencyRecorder,
                  rates: Optional[Dict[str, float]] = None,
                  window_s: float = 5.0,
                  span_events: Optional[Sequence[tuple]] = None
                  ) -> List[dict]:
    """One verdict dict per budget. Latency breaches are localized to the
    worst window (max p99) and, when `span_events` is supplied, carry the
    dominating span category overlapping that window's wall extent."""
    rates = rates or {}
    out = []
    for b in budgets:
        v = {
            "slo": b.name, "lane": b.lane, "kind": b.kind,
            "limit": b.limit, "ok": True, "observed": None,
        }
        if b.kind == KIND_RATE_MIN:
            observed = rates.get(b.lane)
            v["observed"] = observed
            v["ok"] = observed is not None and observed >= b.limit
        elif b.kind == KIND_P99_MS_MAX:
            samples = recorder.samples(b.lane)
            v["samples"] = len(samples)
            if len(samples) < b.min_samples:
                v["ok"] = False
                v["reason"] = (f"only {len(samples)} samples "
                               f"(min {b.min_samples}) — lane starved or idle")
            else:
                observed = percentile([ms for _, ms, _ in samples], 0.99)
                v["observed"] = observed
                v["ok"] = observed <= b.limit
            if not v["ok"] and samples:
                wins = window_stats(samples, window_s)
                worst = max(wins, key=lambda wd: wd["p99_ms"])
                v["breach_window"] = {
                    "t0": worst["t0"], "t1": worst["t1"],
                    "count": worst["count"], "p99_ms": worst["p99_ms"],
                }
                if span_events is not None:
                    agg = attribute_spans(span_events, worst["wall_range"])
                    dom = dominant_span(agg)
                    if dom is not None:
                        v["breach_window"]["dominant_span"] = dom
                        v["breach_window"]["span_totals_ms"] = {
                            k: round(a["total_ms"], 3)
                            for k, a in sorted(agg.items())
                        }
        else:
            v["ok"] = False
            v["reason"] = f"unknown SLO kind {b.kind!r}"
        out.append(v)
    return out


def slo_verdict(results: Sequence[dict]) -> dict:
    breaches = [r for r in results if not r["ok"]]
    return {
        "ok": not breaches,
        "evaluated": len(results),
        "breaches": breaches,
        "results": list(results),
    }
