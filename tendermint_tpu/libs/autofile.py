"""Size-rotated file groups backing the WAL.

Reference parity: internal/libs/autofile/group.go — a Group is a head
file plus numbered rotated chunks (`wal`, `wal.000`, `wal.001`, ...).
When the head exceeds head_size_limit it is renamed to the next index and
a fresh head opened; when the group's total size exceeds total_size_limit
the oldest chunks are deleted. Readers iterate oldest chunk -> head.

Differences from the reference (deliberate): rotation is checked on write
rather than by a 10s ticker (no background goroutine needed — the check
is one integer compare), and minIndex/maxIndex are derived from the
directory listing at open.
"""

from __future__ import annotations

import os
import re
import threading
from typing import BinaryIO, List, Optional

DEFAULT_HEAD_SIZE_LIMIT = 10 * 1024 * 1024  # group.go:26 (10MB)
DEFAULT_TOTAL_SIZE_LIMIT = 1024 * 1024 * 1024  # group.go:27 (1GB)


class Group:
    """autofile.Group (write side + chunk enumeration)."""

    def __init__(
        self,
        head_path: str,
        head_size_limit: int = DEFAULT_HEAD_SIZE_LIMIT,
        total_size_limit: int = DEFAULT_TOTAL_SIZE_LIMIT,
    ):
        self._head_path = head_path
        self._head_size_limit = head_size_limit
        self._total_size_limit = total_size_limit
        self._mtx = threading.Lock()
        self._fh: Optional[BinaryIO] = None
        self._head_size = 0
        os.makedirs(os.path.dirname(head_path) or ".", exist_ok=True)

    # -- lifecycle -------------------------------------------------------

    def open(self) -> None:
        with self._mtx:
            self._fh = open(self._head_path, "ab")
            self._head_size = self._fh.tell()

    def close(self) -> None:
        with self._mtx:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None

    # -- chunk bookkeeping ----------------------------------------------

    def _indices(self) -> List[int]:
        d = os.path.dirname(self._head_path) or "."
        base = os.path.basename(self._head_path)
        pat = re.compile(re.escape(base) + r"\.(\d{3,})$")
        out = []
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _chunk_path(self, idx: int) -> str:
        return f"{self._head_path}.{idx:03d}"

    def files_oldest_first(self) -> List[str]:
        """All group files in log order (rotated chunks, then head)."""
        paths = [self._chunk_path(i) for i in self._indices()]
        if os.path.exists(self._head_path):
            paths.append(self._head_path)
        return paths

    # -- writes ----------------------------------------------------------

    def write(self, data: bytes) -> None:
        with self._mtx:
            if self._fh is None:
                raise ValueError("group is closed")
            self._fh.write(data)
            self._head_size += len(data)

    def flush_and_sync(self) -> None:
        with self._mtx:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())

    def maybe_rotate(self) -> bool:
        """group.go checkHeadSizeLimit/rotateFile: rename a full head to
        the next index and open a fresh one; then enforce the total-size
        cap by deleting the oldest chunks."""
        with self._mtx:
            if self._fh is None or self._head_size < self._head_size_limit:
                return False
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            indices = self._indices()
            nxt = (indices[-1] + 1) if indices else 0
            os.rename(self._head_path, self._chunk_path(nxt))
            self._fh = open(self._head_path, "ab")
            self._head_size = 0
            self._enforce_total_locked()
            return True

    def _enforce_total_locked(self) -> None:
        total = self._head_size
        chunks = [(i, self._chunk_path(i)) for i in self._indices()]
        sizes = {i: os.path.getsize(p) for i, p in chunks}
        total += sum(sizes.values())
        for i, p in chunks:  # oldest first
            if total <= self._total_size_limit:
                break
            os.remove(p)
            total -= sizes[i]
