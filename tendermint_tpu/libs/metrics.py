"""Metrics — Prometheus-style counters/gauges/histograms.

Reference parity: the go-kit metric sets wired in node/setup.go
defaultMetricsProvider (internal/consensus/metrics.go:8+, p2p/mempool/
state/proxy metric sets) and the Prometheus scrape endpoint from the
instrumentation config. Text exposition format, stdlib HTTP server.

Beyond the reference: `OpsMetrics` — the device verification engine's
metric set (sigs verified, batches by bucket, pad waste, host-prep vs
device-seconds histograms) — lives on a process-wide registry
(`global_registry()`), because the device engine is shared by every node
in the process; a node's MetricsServer serves both its own registry and
the global one.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from . import devcheck as _devcheck


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label value escaping: backslash, quote, LF."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(pairs: Tuple[Tuple[str, str], ...]) -> str:
    """('a','1'),('b','x') -> 'a="1",b="x"' (values escaped)."""
    return ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)


class _Metric:
    def __init__(self, name: str, help_: str, typ: str):
        self.name = name
        self.help = help_
        self.type = typ
        self._values: Dict[Tuple, float] = {}
        # devcheck-instrumented under TM_TPU_DEVCHECK=1 (plain Lock off):
        # metric locks sit at the BOTTOM of the lock-order graph — any
        # acquisition of another lock while holding one is a cycle risk
        self._mtx = _devcheck.lock("metrics.metric")

    def _key(self, labels: Dict[str, str]) -> Tuple:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.type}"]
        with self._mtx:
            for key in sorted(self._values):
                val = self._values[key]
                if key:
                    out.append(f"{self.name}{{{_fmt_labels(key)}}} {val}")
                else:
                    out.append(f"{self.name} {val}")
        return out

    # -- introspection (for /status verify-engine stats & tests) --------

    def value(self, **labels) -> float:
        with self._mtx:
            return self._values.get(self._key(labels), 0.0)

    def total(self) -> float:
        """Sum over every labelset (e.g. counter total across labels)."""
        with self._mtx:
            return sum(self._values.values())

    def by_label(self) -> Dict[Tuple, float]:
        with self._mtx:
            return dict(self._values)

    def sample(self) -> dict:
        """Plain-dict point-in-time read for Registry.snapshot(): label
        strings (exposition-format, e.g. 'lane="ingress"'; '' for the
        unlabeled series) -> current value. Lock-safe, no text parsing."""
        with self._mtx:
            return {
                "type": self.type,
                "values": {_fmt_labels(k): v for k, v in self._values.items()},
            }


class Counter(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_, "counter")

    def inc(self, delta: float = 1.0, **labels) -> None:
        with self._mtx:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + delta


class Gauge(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_, "gauge")

    def set(self, value: float, **labels) -> None:
        with self._mtx:
            self._values[self._key(labels)] = value

    def add(self, delta: float, **labels) -> None:
        with self._mtx:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + delta


class Histogram(_Metric):
    """Prometheus histogram with fixed buckets and label support.

    Each labelset gets its own (counts, sum, total) series; exposition
    merges the series labels with the cumulative `le` label per bucket
    line and always ends with the `+Inf` bucket equal to `_count` — the
    cumulative-bucket invariant scrapers check. The unlabeled series is
    pre-created so an unobserved histogram still exposes zeroed lines
    (go-kit/prometheus client behavior).
    """

    def __init__(self, name: str, help_: str = "", buckets=None,
                 labeled: bool = False):
        super().__init__(name, help_, "histogram")
        self.buckets = list(buckets or [0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10])
        # labelset key -> [counts list (len(buckets)+1), sum, total]
        self._series: Dict[Tuple, list] = {}
        if not labeled:
            self._series[()] = [[0] * (len(self.buckets) + 1), 0.0, 0]

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._mtx:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * (len(self.buckets) + 1), 0.0, 0]
            s[1] += value
            s[2] += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s[0][i] += 1
                    return
            s[0][-1] += 1

    @staticmethod
    def _fmt_le(b) -> str:
        return str(b)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._mtx:
            for key in sorted(self._series):
                counts, sum_, total = self._series[key]
                base = _fmt_labels(key)
                cumulative = 0
                for i, b in enumerate(self.buckets):
                    cumulative += counts[i]
                    lbl = (base + "," if base else "") + f'le="{self._fmt_le(b)}"'
                    out.append(f"{self.name}_bucket{{{lbl}}} {cumulative}")
                cumulative += counts[-1]
                lbl = (base + "," if base else "") + 'le="+Inf"'
                out.append(f"{self.name}_bucket{{{lbl}}} {cumulative}")
                suffix = f"{{{base}}}" if base else ""
                out.append(f"{self.name}_sum{suffix} {sum_}")
                out.append(f"{self.name}_count{suffix} {total}")
        return out

    # -- introspection --------------------------------------------------
    # _Metric.value()/by_label() read _values, which a histogram never
    # writes — override them onto _series so the Counter/Gauge-shaped API
    # returns observation counts instead of silent zeros.

    def value(self, **labels) -> float:
        """Observation count for the labelset (use snapshot() for sums)."""
        with self._mtx:
            s = self._series.get(self._key(labels))
            return float(s[2]) if s else 0.0

    def by_label(self) -> Dict[Tuple, float]:
        with self._mtx:
            return {k: float(s[2]) for k, s in self._series.items()}

    def snapshot(self) -> Dict[Tuple, Tuple[float, int]]:
        """labelset -> (sum, count)."""
        with self._mtx:
            return {k: (s[1], s[2]) for k, s in self._series.items()}

    def total(self) -> float:
        with self._mtx:
            return sum(s[2] for s in self._series.values())

    def sum_all(self) -> float:
        with self._mtx:
            return sum(s[1] for s in self._series.values())

    def sample(self) -> dict:
        """Histogram shape of _Metric.sample(): per-labelset sum/count
        plus raw (non-cumulative) bucket counts, keyed like sample()."""
        with self._mtx:
            return {
                "type": self.type,
                "buckets": list(self.buckets),
                "series": {
                    _fmt_labels(k): {
                        "sum": s[1], "count": s[2], "bucket_counts": list(s[0]),
                    }
                    for k, s in self._series.items()
                },
            }


class Registry:
    def __init__(self, namespace: str = "tendermint"):
        self.namespace = namespace
        self._metrics: List[_Metric] = []
        self._collect_hooks: List[Callable[[], None]] = []
        self._mtx = _devcheck.lock("metrics.registry")

    def counter(self, subsystem: str, name: str, help_: str = "") -> Counter:
        m = Counter(f"{self.namespace}_{subsystem}_{name}", help_)
        with self._mtx:
            self._metrics.append(m)
        return m

    def gauge(self, subsystem: str, name: str, help_: str = "") -> Gauge:
        m = Gauge(f"{self.namespace}_{subsystem}_{name}", help_)
        with self._mtx:
            self._metrics.append(m)
        return m

    def histogram(self, subsystem: str, name: str, help_: str = "",
                  buckets=None, labeled: bool = False) -> Histogram:
        m = Histogram(f"{self.namespace}_{subsystem}_{name}", help_, buckets,
                      labeled=labeled)
        with self._mtx:
            self._metrics.append(m)
        return m

    def add_collect_hook(self, fn: Callable[[], None]) -> None:
        """Run `fn` at the top of every expose() — for pull-style gauges
        (mempool size, connected peers, pipeline queue depth) that are
        cheaper to sample at scrape time than to push on every change."""
        with self._mtx:
            self._collect_hooks.append(fn)

    def expose(self) -> str:
        with self._mtx:
            hooks = list(self._collect_hooks)
            metrics = list(self._metrics)
        for fn in hooks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a scrape must never 500
                pass
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Lock-safe structured read of every metric: name -> sample()
        dict. Runs the collect hooks first (same contract as expose(), so
        pull-style gauges are fresh), then reads each metric under its
        own lock. The soak sampler and /status handlers consume this
        instead of re-parsing exposition text; expose() stays the only
        text path and its bytes are untouched."""
        with self._mtx:
            hooks = list(self._collect_hooks)
            metrics = list(self._metrics)
        for fn in hooks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a snapshot must never throw
                pass
        return {m.name: m.sample() for m in metrics}


class ConsensusMetrics:
    """internal/consensus/metrics.go:19+ — the consensus metric set."""

    def __init__(self, registry: Registry):
        self.height = registry.gauge("consensus", "height", "Height of the chain.")
        self.rounds = registry.gauge("consensus", "rounds", "Round of the chain.")
        self.validators = registry.gauge("consensus", "validators", "Number of validators.")
        self.validators_power = registry.gauge(
            "consensus", "validators_power", "Total power of all validators."
        )
        self.missing_validators = registry.gauge(
            "consensus", "missing_validators", "Validators missing from the last commit."
        )
        self.missing_validators_power = registry.gauge(
            "consensus", "missing_validators_power",
            "Voting power of the missing validators.",
        )
        self.byzantine_validators = registry.gauge(
            "consensus", "byzantine_validators", "Validators that equivocated."
        )
        self.block_interval_seconds = registry.histogram(
            "consensus", "block_interval_seconds", "Time between this and the last block."
        )
        self.num_txs = registry.gauge("consensus", "num_txs", "Txs in the latest block.")
        self.total_txs = registry.counter("consensus", "total_txs", "Total txs committed.")
        self.block_size_bytes = registry.gauge(
            "consensus", "block_size_bytes", "Size of the latest block."
        )
        # per-height latency attribution (ISSUE 10): the HeightTimeline
        # phase durations (propose / prevote / precommit / commit / apply)
        # as one labeled histogram — the 2302.00418-style per-phase
        # breakdown, scrapeable instead of paper-only
        self.phase_seconds = registry.histogram(
            "consensus", "phase_seconds",
            "Consensus phase durations per committed height, by phase "
            "label (propose|prevote|precommit|commit|apply).",
            buckets=[0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0],
            labeled=True,
        )


class VoteIngressMetrics:
    """Live-vote ingress (ISSUE 15): the consensus/vote_ingress.py
    accumulator's device-batching counters. A separate set (not
    ConsensusMetrics) because the accumulator is shared machinery like
    the mempool ingress — benches and multi-node sims use the
    process-wide instance."""

    def __init__(self, registry: Registry):
        self.batches = registry.counter(
            "consensus", "vote_ingress_batches",
            "Vote windows flushed to the device pipeline.",
        )
        self.batch_sigs = registry.counter(
            "consensus", "vote_ingress_sigs",
            "Vote signatures verified through ingress windows.",
        )
        self.batch_wait_ms = registry.histogram(
            "consensus", "vote_ingress_batch_wait_ms",
            "Milliseconds the oldest vote of each window waited before "
            "its flush.",
            buckets=[0.5, 1, 2.5, 5, 10, 25, 50, 100, 250],
        )
        self.memo_hits = registry.counter(
            "consensus", "vote_ingress_memo_hits",
            "Votes answered from the signature memo without re-dispatch "
            "(re-gossiped duplicates).",
        )
        self.sync_fallbacks = registry.counter(
            "consensus", "vote_ingress_sync_fallbacks",
            "Vote windows verified on the host (below "
            "BATCH_VERIFY_THRESHOLD, engine absent, or stepped mode).",
        )
        self.dispatch_errors = registry.counter(
            "consensus", "vote_ingress_dispatch_errors",
            "Vote windows poisoned by a DispatchError and re-driven "
            "through the per-vote fallback.",
        )


class MempoolMetrics:
    """internal/mempool/metrics.go — the mempool metric set. size/
    size_bytes are sampled by a registry collect hook at scrape time; the
    rest are pushed from TxMempool when a metrics set is attached."""

    def __init__(self, registry: Registry):
        self.size = registry.gauge("mempool", "size", "Number of uncommitted txs.")
        self.size_bytes = registry.gauge(
            "mempool", "size_bytes", "Total byte size of uncommitted txs."
        )
        self.tx_size_bytes = registry.histogram(
            "mempool", "tx_size_bytes", "Tx sizes in bytes.",
            buckets=[32, 128, 512, 2048, 8192, 32768, 131072, 1048576],
        )
        self.failed_txs = registry.counter(
            "mempool", "failed_txs", "Txs that failed CheckTx."
        )
        self.evicted_txs = registry.counter(
            "mempool", "evicted_txs", "Txs evicted to make room for higher priority."
        )
        self.recheck_times = registry.counter(
            "mempool", "recheck_times", "Txs rechecked after a block commit."
        )
        # device-batched ingress back-pressure (ISSUE 13): pushed by
        # mempool/ingress.py IngressAccumulator
        self.ingress_queue_depth = registry.gauge(
            "mempool", "ingress_queue_depth",
            "Tx signatures waiting in the ingress accumulator window.",
        )
        self.ingress_batch_wait_ms = registry.histogram(
            "mempool", "ingress_batch_wait_ms",
            "Milliseconds the oldest tx of each ingress batch waited "
            "before its window flushed to the device.",
            buckets=[0.5, 1, 2.5, 5, 10, 25, 50, 100, 250],
        )
        self.checktx_preemptions = registry.counter(
            "mempool", "checktx_preemptions",
            "Queued ingress CheckTx batches bypassed by a higher-priority "
            "consensus batch in the QoS dispatch queue.",
        )


class BlockSyncMetrics:
    """Blocksync catch-up metric set (ISSUE 14): speculation-cache
    accounting for the depth-1 pipelined path plus range-replay counters
    for the ReplayEngine. Pushed from blocksync; surfaced in /status."""

    def __init__(self, registry: Registry):
        self.speculation_hits = registry.counter(
            "blocksync", "speculation_hits",
            "Pre-verified next-height speculations whose device verdict "
            "was usable (height/valset/block hashes all matched).",
        )
        self.speculation_misses = registry.counter(
            "blocksync", "speculation_misses",
            "Heights applied with no speculation available (cold start, "
            "fetch gap, or below the device threshold).",
        )
        self.speculation_discards = registry.counter(
            "blocksync", "speculation_discards",
            "Speculations invalidated before use: height/valset/hash "
            "mismatch, dispatch error, or device timeout.",
        )
        self.replay_ranges = registry.counter(
            "blocksync", "replay_ranges",
            "Epoch ranges verified through the range-batched replay engine.",
        )
        self.replay_heights = registry.counter(
            "blocksync", "replay_heights",
            "Heights whose commit was verified as part of a replay range.",
        )
        self.replay_fallback_heights = registry.counter(
            "blocksync", "replay_fallback_heights",
            "Heights verified per-height (sequential fallback or "
            "sub-threshold range) during replay catch-up.",
        )
        self.replay_fallback_ranges = registry.counter(
            "blocksync", "replay_fallback_ranges",
            "Replay ranges that fell back to sequential verification "
            "(bad commit, prepare failure, or dispatch trouble).",
        )


class IngressMetrics:
    """One ingress fabric (ISSUE 17): the unified per-lane metric set
    pushed by ops/ingress.py IngressEngine. Every series carries a
    `lane` label (mempool|votes|light|replay) — the canonical names for
    what used to be four parallel sets. The old per-workload names
    (mempool_ingress_*, vote_ingress_*) are still written by the lane
    wrappers as ALIASES so /status, soak SLO evaluation, and existing
    dashboards keep working unchanged."""

    def __init__(self, registry: Registry):
        self.queue_depth = registry.gauge(
            "ingress", "queue_depth",
            "Signatures waiting in a lane's open windows, by lane label.",
        )
        self.batch_wait_ms = registry.histogram(
            "ingress", "batch_wait_ms",
            "Milliseconds the oldest item of each window waited before "
            "its flush, by lane label.",
            buckets=[0.5, 1, 2.5, 5, 10, 25, 50, 100, 250],
            labeled=True,
        )
        self.batches = registry.counter(
            "ingress", "batches",
            "Windows flushed through the fabric, by lane label.",
        )
        self.sigs = registry.counter(
            "ingress", "sigs",
            "Signatures flushed through the fabric (windowed + "
            "whole-block), by lane label.",
        )
        self.host_lane_sigs = registry.counter(
            "ingress", "host_lane_sigs",
            "Signatures route_fn-directed to the host lane (schemes "
            "without a device kernel), by lane label.",
        )
        self.sync_fallbacks = registry.counter(
            "ingress", "sync_fallbacks",
            "Windows host-verified as a fallback (sub-threshold, "
            "stepped mode, or engine absent), by lane label.",
        )
        self.dispatch_errors = registry.counter(
            "ingress", "dispatch_errors",
            "Windows poisoned by a DispatchError and handed back for "
            "per-item retry, by lane label.",
        )
        self.remote_fallbacks = registry.counter(
            "ingress", "remote_fallbacks",
            "Windows host-verified because a remote (fleet) verifier "
            "became unavailable after submit, by lane label (ISSUE 18).",
        )
        self.preemptions = registry.counter(
            "ingress", "preemptions",
            "Queued lane batches bypassed by a higher-priority batch in "
            "the QoS dispatch queue, by lane label.",
        )
        self.blocks = registry.counter(
            "ingress", "blocks",
            "Whole-block passthrough submissions (light stages, mempool "
            "recheck, replay fused chunks), by lane label.",
        )
        self.window_ms = registry.gauge(
            "ingress", "window_ms",
            "Current adaptive window length per lane (the controller's "
            "base trigger, before the SLO deadline bound).",
        )
        self.batch_target = registry.gauge(
            "ingress", "batch_target",
            "Current adaptive batch-size trigger per lane.",
        )
        self.deadline_flushes = registry.counter(
            "ingress", "deadline_flushes",
            "Flushes fired early by the SLO deadline bound (budget minus "
            "service-time headroom), by lane label.",
        )


class FleetMetrics:
    """The verification fleet (ISSUE 18): client- and server-side series
    for the network-facing EntryBlock verify service. Client series are
    labeled by `target` (the fleet address as the client knows it);
    server series by `lane` (the client-declared lane name riding the
    wire) or `reason` (frame-reject taxonomy). One labeled set serves
    any number of FleetClients/FleetServers in the process — benches and
    simnet runs host both ends."""

    def __init__(self, registry: Registry):
        # -- client side ------------------------------------------------
        self.client_connected = registry.gauge(
            "fleet", "client_connected",
            "1 while the client holds a live fleet connection, 0 while "
            "degraded to local fallback, by target label.",
        )
        self.client_rtt_ewma_ms = registry.gauge(
            "fleet", "client_rtt_ewma_ms",
            "EWMA of submit→verdict round-trip milliseconds, by target.",
        )
        self.client_requests = registry.counter(
            "fleet", "client_requests",
            "SUBMIT frames sent to the fleet, by target label.",
        )
        self.client_timeouts = registry.counter(
            "fleet", "client_timeouts",
            "Requests that hit the fleet deadline and were failed over, "
            "by target label.",
        )
        self.client_fallbacks = registry.counter(
            "fleet", "client_fallbacks",
            "Requests failed with FleetUnavailable (timeout, socket "
            "error, or fleet marked down), by target label.",
        )
        self.client_rejoins = registry.counter(
            "fleet", "client_rejoins",
            "Successful reconnects after a degraded interval, by target.",
        )
        # -- server side ------------------------------------------------
        self.server_connections = registry.gauge(
            "fleet", "server_connections",
            "Client connections currently held by the fleet server.",
        )
        self.server_frames_accepted = registry.counter(
            "fleet", "server_frames_accepted",
            "Well-formed SUBMIT frames accepted, by lane label.",
        )
        self.server_frames_rejected = registry.counter(
            "fleet", "server_frames_rejected",
            "Frames rejected, by reason label "
            "(malformed|version|oversize|closed).",
        )
        self.server_sigs = registry.counter(
            "fleet", "server_sigs",
            "Signatures received for verification, by lane label.",
        )
        self.server_verdicts_streamed = registry.counter(
            "fleet", "server_verdicts_streamed",
            "Verdict frames streamed back in completion order.",
        )
        self.server_dispatch_errors = registry.counter(
            "fleet", "server_dispatch_errors",
            "Requests answered with an ERROR frame because the verifier "
            "raised (DispatchError or submit failure).",
        )


class P2PMetrics:
    """p2p/metrics.go — the router metric set. peers is sampled by a
    registry collect hook at scrape time."""

    def __init__(self, registry: Registry):
        self.peers = registry.gauge("p2p", "peers", "Connected peers.")
        self.peer_receive_bytes_total = registry.counter(
            "p2p", "peer_receive_bytes_total", "Bytes received from peers."
        )
        self.peer_send_bytes_total = registry.counter(
            "p2p", "peer_send_bytes_total", "Bytes sent to peers."
        )


class OpsMetrics:
    """The device verification engine's metric set (ops/backend.py +
    ops/pipeline.py). Batch-labeled series carry a `bucket` label — the
    padded device batch size the batch compiled/dispatched as."""

    # seconds-scale buckets tuned to the measured path: host prep is
    # ~1-50 ms/batch, device batches ~10-300 ms through the relay
    _TIME_BUCKETS = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5]

    def __init__(self, registry: Registry):
        self.sigs_verified = registry.counter(
            "ops", "sigs_verified_total",
            "Signatures verified, by path label (device|host).",
        )
        self.batches = registry.counter(
            "ops", "batches_total", "Device batches dispatched, by bucket label."
        )
        self.padded_lanes = registry.counter(
            "ops", "padded_lanes_total",
            "Padding lanes dispatched (bucket size minus live signatures).",
        )
        self.pad_waste_ratio = registry.gauge(
            "ops", "pad_waste_ratio", "Pad fraction of the last device batch."
        )
        self.host_prep_seconds = registry.histogram(
            "ops", "host_prep_seconds",
            "Host-side batch prep (pack/hash/limb) seconds, by bucket label.",
            buckets=self._TIME_BUCKETS, labeled=True,
        )
        self.device_seconds = registry.histogram(
            "ops", "device_seconds",
            "Dispatch-to-materialized device seconds, by bucket label.",
            buckets=self._TIME_BUCKETS, labeled=True,
        )
        self.host_fallback = registry.counter(
            "ops", "host_fallback_total",
            "Batches below DEVICE_THRESHOLD verified on the host path.",
        )
        self.pipeline_queue_depth = registry.gauge(
            "ops", "pipeline_queue_depth", "Jobs waiting in the async verifier queue."
        )
        self.pipeline_inflight = registry.gauge(
            "ops", "pipeline_inflight", "Device batches in flight (dispatched, not resolved)."
        )
        self.pipeline_coalesced_jobs = registry.histogram(
            "ops", "pipeline_coalesced_jobs",
            "Jobs fused into one device batch by the coalescing worker.",
            buckets=[1, 2, 4, 8, 16, 32, 64],
        )
        self.dispatch_queue_depth = registry.gauge(
            "ops", "dispatch_queue_depth",
            "Prepared batches waiting for the dispatch-owner thread.",
        )
        self.dispatch_busy_ratio = registry.gauge(
            "ops", "dispatch_busy_ratio",
            "Dispatch-owner thread occupancy (launch time / wall time).",
        )
        # valset epoch cache (ops/epoch_cache.py): hits = warm epochs
        # (committee already device-resident), misses = cold epochs
        # (table registered, first commit rides the uncached path),
        # evictions = LRU pops past TM_TPU_EPOCH_CACHE depth
        self.epoch_cache_hits = registry.counter(
            "ops", "epoch_cache_hits_total",
            "Commit preps that found their validator set device-resident.",
        )
        self.epoch_cache_misses = registry.counter(
            "ops", "epoch_cache_misses_total",
            "Commit preps that registered a new validator-set epoch.",
        )
        self.epoch_cache_evictions = registry.counter(
            "ops", "epoch_cache_evictions_total",
            "Validator-set epochs evicted from the device cache (LRU).",
        )
        self.h2d_bytes_per_commit = registry.gauge(
            "ops", "h2d_bytes_per_commit",
            "Host bytes shipped to the device by the last dispatched "
            "batch, averaged over its coalesced commits.",
        )
        # overlapped relay (ops/pipeline.py dispatcher + ops/device_pool):
        # transfer_overlap_ratio = fraction of H2D transfer time issued
        # while a kernel was in flight (hidden behind compute); the pool
        # counters split slot acquires into recycled vs freshly minted —
        # steady state over one bucket shows misses == pool depth, then
        # hits only (allocations flat)
        self.transfer_overlap_ratio = registry.gauge(
            "ops", "transfer_overlap_ratio",
            "Fraction of recent H2D transfer time hidden behind device "
            "compute (windowed).",
        )
        self.buffer_pool_hits = registry.counter(
            "ops", "buffer_pool_hits_total",
            "Device input-buffer slot acquires served by a recycled slot.",
        )
        self.buffer_pool_misses = registry.counter(
            "ops", "buffer_pool_misses_total",
            "Device input-buffer slot acquires that minted a new slot.",
        )
        # mesh dispatcher (ops/mesh.py + ops/pipeline.py _worker_mesh):
        # lane packing efficiency of the last superbatch launch —
        # occupancy = live signatures / (lanes x lane_bucket), pad waste
        # = identity padding rows / total rows (occupancy + pad = 1; the
        # two gauges are published separately so dashboards can alert on
        # either without arithmetic)
        self.mesh_lane_occupancy = registry.gauge(
            "ops", "mesh_lane_occupancy",
            "Live-signature fraction of the last mesh superbatch's lanes.",
        )
        self.mesh_pad_waste_ratio = registry.gauge(
            "ops", "mesh_pad_waste_ratio",
            "Identity-padding fraction of the last mesh superbatch.",
        )
        # QoS lane queue wait (ISSUE 16): seconds a prepared batch sat in
        # the dispatch queue before winning its launch slot, by lane.
        # Before this, only the consensus lane's wait was observable (via
        # pipeline.queue_wait spans) — ingress starvation was invisible
        # to a scrape.
        self.queue_wait_seconds = registry.histogram(
            "ops", "queue_wait_seconds",
            "Dispatch-queue wait before launch, by QoS lane label "
            "(consensus|replay|ingress).",
            buckets=self._TIME_BUCKETS, labeled=True,
        )


# ---------------------------------------------------------------------------
# Process-wide registry: the device engine is shared by every node in the
# process, so its metrics live here; node MetricsServers serve this
# registry alongside their own.
# ---------------------------------------------------------------------------

# RLock: ops_metrics() calls global_registry() while holding it
_global_mtx = threading.RLock()
_global_registry: Optional[Registry] = None
_global_ops: Optional[OpsMetrics] = None


def global_registry() -> Registry:
    global _global_registry
    with _global_mtx:
        if _global_registry is None:
            _global_registry = Registry("tendermint")
        return _global_registry


def ops_metrics() -> OpsMetrics:
    global _global_ops
    with _global_mtx:
        if _global_ops is None:
            _global_ops = OpsMetrics(global_registry())
        return _global_ops


_global_mempool: Optional["MempoolMetrics"] = None


def mempool_metrics() -> "MempoolMetrics":
    """Process-wide MempoolMetrics for the ingress accumulator when no
    node-attached set exists (benches, tests, multi-node sims sharing one
    device engine). Nodes with instrumentation enabled still build their
    own per-node set; the accumulator uses whichever it was handed."""
    global _global_mempool
    with _global_mtx:
        if _global_mempool is None:
            _global_mempool = MempoolMetrics(global_registry())
        return _global_mempool


_global_vote_ingress: Optional["VoteIngressMetrics"] = None


def vote_ingress_metrics() -> "VoteIngressMetrics":
    """Process-wide VoteIngressMetrics — same sharing rationale as
    mempool_metrics(): many consensus states (simnet nodes, benches) can
    feed one shared device pipeline."""
    global _global_vote_ingress
    with _global_mtx:
        if _global_vote_ingress is None:
            _global_vote_ingress = VoteIngressMetrics(global_registry())
        return _global_vote_ingress


_global_ingress: Optional["IngressMetrics"] = None


def ingress_metrics() -> "IngressMetrics":
    """Process-wide IngressMetrics — the one labeled set behind every
    fabric lane (ops/ingress.py). Same sharing rationale as
    mempool_metrics(): the fabric's scheduler/completer are process
    infrastructure, so its counters live on the process registry."""
    global _global_ingress
    with _global_mtx:
        if _global_ingress is None:
            _global_ingress = IngressMetrics(global_registry())
        return _global_ingress


_global_fleet: Optional["FleetMetrics"] = None


def fleet_metrics() -> "FleetMetrics":
    """Process-wide FleetMetrics — same sharing rationale as
    ingress_metrics(): fleet clients hang off process-shared lanes and a
    fleet server fronts the process-shared verifier, so both ends push
    to the process registry."""
    global _global_fleet
    with _global_mtx:
        if _global_fleet is None:
            _global_fleet = FleetMetrics(global_registry())
        return _global_fleet


def fleet_stats() -> dict:
    """Fleet snapshot for /status — cheap counter reads, no fleet (or
    jax) import; safe to call whether or not a fleet exists (all-zero
    series then)."""
    m = fleet_metrics()

    def _by(metric, label):
        return {
            (dict(k).get(label, "") or "unlabeled"): int(v)
            for k, v in metric.by_label().items()
        }

    def _gauge_by(metric, label):
        return {
            (dict(k).get(label, "") or "unlabeled"): float(v)
            for k, v in metric.by_label().items()
        }

    return {
        "client": {
            "connected": _by(m.client_connected, "target"),
            "rtt_ewma_ms": _gauge_by(m.client_rtt_ewma_ms, "target"),
            "requests": _by(m.client_requests, "target"),
            "timeouts": _by(m.client_timeouts, "target"),
            "fallbacks": _by(m.client_fallbacks, "target"),
            "rejoins": _by(m.client_rejoins, "target"),
        },
        "server": {
            "connections": int(m.server_connections.value()),
            "frames_accepted": _by(m.server_frames_accepted, "lane"),
            "frames_rejected": _by(m.server_frames_rejected, "reason"),
            "sigs": _by(m.server_sigs, "lane"),
            "verdicts_streamed": int(m.server_verdicts_streamed.total()),
            "dispatch_errors": int(m.server_dispatch_errors.total()),
        },
    }


_global_blocksync: Optional["BlockSyncMetrics"] = None


def blocksync_metrics() -> "BlockSyncMetrics":
    """Process-wide BlockSyncMetrics — same sharing rationale as
    mempool_metrics(): the catch-up engine rides the shared device
    pipeline, so its counters live on the process registry."""
    global _global_blocksync
    with _global_mtx:
        if _global_blocksync is None:
            _global_blocksync = BlockSyncMetrics(global_registry())
        return _global_blocksync


def blocksync_stats() -> dict:
    """Blocksync catch-up snapshot for /status — cheap counter reads."""
    m = blocksync_metrics()
    hits = int(m.speculation_hits.total())
    misses = int(m.speculation_misses.total())
    discards = int(m.speculation_discards.total())
    rng = int(m.replay_heights.total())
    seq = int(m.replay_fallback_heights.total())
    return {
        "speculation_hits": hits,
        "speculation_misses": misses,
        "speculation_discards": discards,
        "replay_ranges": int(m.replay_ranges.total()),
        "replay_fallback_ranges": int(m.replay_fallback_ranges.total()),
        "replay_heights": rng,
        "replay_fallback_heights": seq,
        "replay_hit_rate": (rng / (rng + seq)) if (rng + seq) else 0.0,
    }


def ops_stats() -> dict:
    """Verify-engine snapshot for /status — no jax import, cheap reads."""
    m = ops_metrics()
    sigs_device = m.sigs_verified.value(path="device")
    sigs_host = m.sigs_verified.value(path="host")
    padded = m.padded_lanes.total()
    dispatched = sigs_device + padded
    prep_sum = m.host_prep_seconds.sum_all()
    prep_n = m.host_prep_seconds.total()
    dev_sum = m.device_seconds.sum_all()
    dev_n = m.device_seconds.total()
    return {
        "sigs_verified_device": int(sigs_device),
        "sigs_verified_host": int(sigs_host),
        "batches_by_bucket": {
            (dict(k).get("bucket", "") or "unbucketed"): int(v)
            for k, v in m.batches.by_label().items()
        },
        "pad_waste_ratio": (padded / dispatched) if dispatched else 0.0,
        "host_fallback_batches": int(m.host_fallback.total()),
        "host_prep_seconds_avg": (prep_sum / prep_n) if prep_n else 0.0,
        "device_seconds_avg": (dev_sum / dev_n) if dev_n else 0.0,
        "pipeline_queue_depth": int(m.pipeline_queue_depth.value()),
        "pipeline_inflight": int(m.pipeline_inflight.value()),
        "dispatch_queue_depth": int(m.dispatch_queue_depth.value()),
        "dispatch_busy_ratio": float(m.dispatch_busy_ratio.value()),
        "epoch_cache_hits": int(m.epoch_cache_hits.total()),
        "epoch_cache_misses": int(m.epoch_cache_misses.total()),
        "epoch_cache_evictions": int(m.epoch_cache_evictions.total()),
        "h2d_bytes_per_commit": float(m.h2d_bytes_per_commit.value()),
        "transfer_overlap_ratio": float(m.transfer_overlap_ratio.value()),
        "buffer_pool_hits": int(m.buffer_pool_hits.total()),
        "buffer_pool_misses": int(m.buffer_pool_misses.total()),
        "mesh_lane_occupancy": float(m.mesh_lane_occupancy.value()),
        "mesh_pad_waste_ratio": float(m.mesh_pad_waste_ratio.value()),
        # per-QoS-lane dispatch-queue wait (ISSUE 16) — sits next to the
        # lane_counts() intake split in /status verify_engine
        "queue_wait_by_lane": {
            (dict(k).get("lane", "") or "unlabeled"): {
                "count": int(c),
                "avg_ms": (s / c * 1000.0) if c else 0.0,
            }
            for k, (s, c) in m.queue_wait_seconds.snapshot().items()
        },
    }


class MetricsServer:
    """The instrumentation scrape endpoint (config.instrumentation).

    Accepts one registry or a list of registries (a node serves its own
    consensus/mempool/p2p registry plus the process-wide ops registry).
    """

    def __init__(self, registry, laddr: str):
        regs = list(registry) if isinstance(registry, (list, tuple)) else [registry]
        addr = laddr.replace("tcp://", "")
        host, _, port = addr.rpartition(":")

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: A003
                pass

            def do_GET(self):  # noqa: N802
                body = "".join(r.expose() for r in regs).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)

    @property
    def listen_addr(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"{h}:{p}"

    def start(self) -> None:
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
