"""Metrics — Prometheus-style counters/gauges/histograms.

Reference parity: the go-kit metric sets wired in node/setup.go
defaultMetricsProvider (internal/consensus/metrics.go:8+, p2p/mempool/
state/proxy metric sets) and the Prometheus scrape endpoint from the
instrumentation config. Text exposition format, stdlib HTTP server.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple


class _Metric:
    def __init__(self, name: str, help_: str, typ: str):
        self.name = name
        self.help = help_
        self.type = typ
        self._values: Dict[Tuple, float] = {}
        self._mtx = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple:
        return tuple(sorted(labels.items()))

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.type}"]
        with self._mtx:
            for key, val in self._values.items():
                if key:
                    lbl = ",".join(f'{k}="{v}"' for k, v in key)
                    out.append(f"{self.name}{{{lbl}}} {val}")
                else:
                    out.append(f"{self.name} {val}")
        return out


class Counter(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_, "counter")

    def inc(self, delta: float = 1.0, **labels) -> None:
        with self._mtx:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + delta


class Gauge(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_, "gauge")

    def set(self, value: float, **labels) -> None:
        with self._mtx:
            self._values[self._key(labels)] = value

    def add(self, delta: float, **labels) -> None:
        with self._mtx:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + delta


class Histogram(_Metric):
    """Prometheus histogram with fixed buckets."""

    def __init__(self, name: str, help_: str = "", buckets=None):
        super().__init__(name, help_, "histogram")
        self.buckets = buckets or [0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10]
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0

    def observe(self, value: float) -> None:
        with self._mtx:
            self._sum += value
            self._total += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._mtx:
            cumulative = 0
            for i, b in enumerate(self.buckets):
                cumulative += self._counts[i]
                out.append(f'{self.name}_bucket{{le="{b}"}} {cumulative}')
            cumulative += self._counts[-1]
            out.append(f'{self.name}_bucket{{le="+Inf"}} {cumulative}')
            out.append(f"{self.name}_sum {self._sum}")
            out.append(f"{self.name}_count {self._total}")
        return out


class Registry:
    def __init__(self, namespace: str = "tendermint"):
        self.namespace = namespace
        self._metrics: List[_Metric] = []
        self._mtx = threading.Lock()

    def counter(self, subsystem: str, name: str, help_: str = "") -> Counter:
        m = Counter(f"{self.namespace}_{subsystem}_{name}", help_)
        with self._mtx:
            self._metrics.append(m)
        return m

    def gauge(self, subsystem: str, name: str, help_: str = "") -> Gauge:
        m = Gauge(f"{self.namespace}_{subsystem}_{name}", help_)
        with self._mtx:
            self._metrics.append(m)
        return m

    def histogram(self, subsystem: str, name: str, help_: str = "", buckets=None) -> Histogram:
        m = Histogram(f"{self.namespace}_{subsystem}_{name}", help_, buckets)
        with self._mtx:
            self._metrics.append(m)
        return m

    def expose(self) -> str:
        with self._mtx:
            lines: List[str] = []
            for m in self._metrics:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class ConsensusMetrics:
    """internal/consensus/metrics.go:19+ — the consensus metric set."""

    def __init__(self, registry: Registry):
        self.height = registry.gauge("consensus", "height", "Height of the chain.")
        self.rounds = registry.gauge("consensus", "rounds", "Round of the chain.")
        self.validators = registry.gauge("consensus", "validators", "Number of validators.")
        self.validators_power = registry.gauge(
            "consensus", "validators_power", "Total power of all validators."
        )
        self.missing_validators = registry.gauge(
            "consensus", "missing_validators", "Validators missing from the last commit."
        )
        self.byzantine_validators = registry.gauge(
            "consensus", "byzantine_validators", "Validators that equivocated."
        )
        self.block_interval_seconds = registry.histogram(
            "consensus", "block_interval_seconds", "Time between this and the last block."
        )
        self.num_txs = registry.gauge("consensus", "num_txs", "Txs in the latest block.")
        self.total_txs = registry.counter("consensus", "total_txs", "Total txs committed.")
        self.block_size_bytes = registry.gauge(
            "consensus", "block_size_bytes", "Size of the latest block."
        )


class MetricsServer:
    """The instrumentation scrape endpoint (config.instrumentation)."""

    def __init__(self, registry: Registry, laddr: str):
        addr = laddr.replace("tcp://", "")
        host, _, port = addr.rpartition(":")

        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: A003
                pass

            def do_GET(self):  # noqa: N802
                body = reg.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)

    @property
    def listen_addr(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"{h}:{p}"

    def start(self) -> None:
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
