"""Service lifecycle — the BaseService pattern every component embeds.

Reference parity: libs/service/service.go — Start/Stop/Reset with
on_start/on_stop hooks, idempotence errors, and is_running checks
(embedded by consensus state, reactors, mempool, etc., e.g.
internal/consensus/state.go:81).
"""

from __future__ import annotations

import threading


class AlreadyStartedError(RuntimeError):
    pass


class AlreadyStoppedError(RuntimeError):
    pass


class BaseService:
    def __init__(self, name: str = ""):
        self._name = name or type(self).__name__
        self._started = False
        self._stopped = False
        self._quit = threading.Event()
        self._svc_mtx = threading.Lock()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        with self._svc_mtx:
            if self._started:
                raise AlreadyStartedError(f"{self._name} already started")
            if self._stopped:
                raise AlreadyStoppedError(f"{self._name} already stopped")
            self.on_start()
            self._started = True

    def stop(self) -> None:
        with self._svc_mtx:
            if not self._started or self._stopped:
                return
            self._stopped = True
            self._quit.set()
            self.on_stop()

    def reset(self) -> None:
        with self._svc_mtx:
            if not self._stopped:
                raise RuntimeError(f"cannot reset running service {self._name}")
            self._started = False
            self._stopped = False
            self._quit = threading.Event()
            self.on_reset()

    # -- hooks ----------------------------------------------------------

    def on_start(self) -> None: ...

    def on_stop(self) -> None: ...

    def on_reset(self) -> None: ...

    # -- state ----------------------------------------------------------

    def is_running(self) -> bool:
        return self._started and not self._stopped

    def wait(self, timeout: float | None = None) -> None:
        self._quit.wait(timeout)

    @property
    def quit_event(self) -> threading.Event:
        return self._quit

    @property
    def name(self) -> str:
        return self._name
