"""Flow-rate monitoring and limiting.

Reference parity: internal/libs/flowrate/ (Monitor with EWMA rate tracking
and Limit(want, rate, block)); used by MConnection for per-connection
send/recv rate caps (internal/p2p/conn/connection.go:103-104) and exposed
in net_info peer status.
"""

from __future__ import annotations

import threading
import time


class Monitor:
    """flowrate.Monitor: tracks transfer rate with an exponentially
    weighted moving average over `window` seconds."""

    def __init__(self, sample_period: float = 0.1, window: float = 1.0):
        self._mtx = threading.Lock()
        self._sample = max(sample_period, 0.01)
        self._window = max(window, self._sample)
        self._start = time.monotonic()
        self._last = self._start
        self._acc = 0  # bytes since last sample
        self._rate = 0.0  # EWMA bytes/s
        self._total = 0

    def update(self, n: int) -> None:
        with self._mtx:
            self._total += n
            self._acc += n
            self._tick_locked()

    def _tick_locked(self) -> None:
        now = time.monotonic()
        dt = now - self._last
        if dt >= self._sample:
            alpha = 1.0 - pow(2.7182818, -dt / self._window)
            self._rate += alpha * (self._acc / dt - self._rate)
            self._acc = 0
            self._last = now

    def rate(self) -> float:
        with self._mtx:
            self._tick_locked()
            return self._rate

    def total(self) -> int:
        with self._mtx:
            return self._total

    def status(self) -> dict:
        with self._mtx:
            self._tick_locked()
            now = time.monotonic()
            return {
                "duration": now - self._start,
                "bytes": self._total,
                "cur_rate": self._rate,
                "avg_rate": self._total / max(now - self._start, 1e-9),
            }


class Limiter:
    """Token-bucket byte-rate limiter: `wait(n)` blocks just long enough to
    keep throughput at or below `rate` bytes/s (burst of one bucket).
    flowrate.Monitor.Limit analog shaped for blocking writers."""

    def __init__(self, rate: float, burst: float | None = None):
        if rate <= 0:
            raise ValueError("rate must be positive")
        self._rate = float(rate)
        self._burst = float(burst if burst is not None else rate / 10)
        self._tokens = self._burst
        self._last = time.monotonic()
        self._mtx = threading.Lock()

    def wait(self, n: int) -> None:
        delay = 0.0
        with self._mtx:
            now = time.monotonic()
            self._tokens = min(
                self._burst, self._tokens + (now - self._last) * self._rate
            )
            self._last = now
            self._tokens -= n
            if self._tokens < 0:
                delay = -self._tokens / self._rate
        if delay > 0:
            time.sleep(delay)
