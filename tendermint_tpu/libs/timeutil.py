"""Wall-clock Timestamp source — the sanctioned wall-clock read for
light-client code.

tmlint's simnet-determinism pass covers `tendermint_tpu/light/`
(ISSUE 11): simnet-driven light clients and the batched verification
service must read time through an injected clock, so the wall-clock
DEFAULT lives here (libs/ is outside the deterministic scope) and rides
in via the `now_fn` seams on light.client.Client and
light.service.LightVerifyService.
"""

from __future__ import annotations

import time


def now_ts():
    """Current wall clock as a wire.canonical.Timestamp."""
    from ..wire.canonical import Timestamp

    t = time.time()
    return Timestamp(seconds=int(t), nanos=int((t % 1) * 1e9))
