"""BitArray — thread-safe bit vector used for vote/part tracking.

Reference parity: libs/bits/bit_array.go. Stored as a Python int bitmask
(arbitrary precision beats a []uint64 here); the wire form is the proto
tendermint.libs.bits.BitArray {1 bits(int64) 2 elems(repeated uint64)}.
"""

from __future__ import annotations

import random as _random
import threading
from typing import List, Optional

from ..wire.proto import ProtoWriter, decode_message, field_int


class BitArray:
    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bit count")
        self._bits = bits
        self._mask = 0
        self._mtx = threading.Lock()

    # -- core ----------------------------------------------------------

    def size(self) -> int:
        return self._bits

    def get_index(self, i: int) -> bool:
        with self._mtx:
            if i >= self._bits or i < 0:
                return False
            return bool((self._mask >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        with self._mtx:
            if i >= self._bits or i < 0:
                return False
            if v:
                self._mask |= 1 << i
            else:
                self._mask &= ~(1 << i)
            return True

    def copy(self) -> "BitArray":
        out = BitArray(self._bits)
        out._mask = self._mask
        return out

    # -- set algebra (bit_array.go Or/And/Not/Sub) ----------------------

    def or_(self, other: Optional["BitArray"]) -> "BitArray":
        if other is None:
            return self.copy()
        out = BitArray(max(self._bits, other._bits))
        out._mask = self._mask | other._mask
        return out

    def and_(self, other: Optional["BitArray"]) -> "BitArray":
        if other is None:
            return BitArray(self._bits)
        out = BitArray(min(self._bits, other._bits))
        out._mask = self._mask & other._mask & ((1 << out._bits) - 1)
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self._bits)
        out._mask = ~self._mask & ((1 << self._bits) - 1)
        return out

    def sub(self, other: Optional["BitArray"]) -> "BitArray":
        """Bits in self but not in other (within self's length)."""
        if other is None:
            return self.copy()
        out = BitArray(self._bits)
        out._mask = self._mask & ~(other._mask & ((1 << self._bits) - 1))
        return out

    def is_empty(self) -> bool:
        return self._mask == 0

    def is_full(self) -> bool:
        return self._mask == (1 << self._bits) - 1 and self._bits > 0

    def pick_random(self) -> tuple:
        """(index, ok): a uniformly random true bit (bit_array.go:253-265)."""
        with self._mtx:
            idxs = [i for i in range(self._bits) if (self._mask >> i) & 1]
        if not idxs:
            return 0, False
        return _random.choice(idxs), True

    def get_true_indices(self) -> List[int]:
        with self._mtx:
            return [i for i in range(self._bits) if (self._mask >> i) & 1]

    def num_true_bits(self) -> int:
        return bin(self._mask).count("1")

    # -- wire ----------------------------------------------------------

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self._bits)
        elems = (self._bits + 63) // 64
        for i in range(elems):
            w.write_varint(2, (self._mask >> (64 * i)) & ((1 << 64) - 1), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "BitArray":
        f = decode_message(data)
        bits = field_int(f, 1)
        out = cls(bits)
        mask = 0
        for i, (_, v) in enumerate(f.get(2, [])):
            mask |= int(v) << (64 * i)
        out._mask = mask & ((1 << bits) - 1) if bits else 0
        return out

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, BitArray)
            and self._bits == other._bits
            and self._mask == other._mask
        )

    def __repr__(self) -> str:
        s = "".join("x" if (self._mask >> i) & 1 else "_" for i in range(self._bits))
        return f"BA{{{self._bits}:{s}}}"
