"""Per-machine persistent JAX compilation cache.

XLA:CPU AOT results are compiled for the build machine's exact CPU
feature flags; loading them on a host with a different CPU risks SIGILL
(observed as loader warnings when an external driver ran a cache warmed
on different hardware). Every cache-enabling site (tests/conftest,
bench, tools, the driver entry) routes through here so each machine
warms its own subdirectory of `.jax_cache/`.
"""

from __future__ import annotations

import hashlib
import os


def machine_tag() -> str:
    """Short tag identifying this host's CPU feature set."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 reports "flags", ARM reports "Features"
                if line.startswith(("flags", "Features")):
                    return hashlib.sha256(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform

    # No readable cpuinfo (non-Linux / hardened container): there is no
    # feature list to key on, so fall back to machine|processor|version.
    # processor is often "" there, and version (kernel build) churns on
    # kernel upgrades — accepted: a cold recompile on upgrade beats two
    # different-featured hosts silently sharing AOT executables.
    u = platform.uname()
    return hashlib.sha256(
        f"{u.machine}|{u.processor}|{u.version}".encode()
    ).hexdigest()[:12]


_MIN_COMPILE_SECS = "1.0"


def cache_dir(repo_root: str) -> str:
    return os.path.join(repo_root, ".jax_cache", machine_tag())


def enable(jax, repo_root: str) -> None:
    jax.config.update("jax_compilation_cache_dir", cache_dir(repo_root))
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(_MIN_COMPILE_SECS)
    )


def set_env(env: dict, repo_root: str) -> dict:
    """setdefault the cache env vars for a subprocess environment."""
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir(repo_root))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", _MIN_COMPILE_SECS)
    return env
