"""Crash-point injection for crash-consistency testing.

Reference parity: internal/libs/fail/fail.go:28 — the FAIL_TEST_INDEX env
var names a numbered crash point; when execution reaches it the process
dies, so tests can assert WAL/handshake recovery from every interleaving
(used inside BlockExecutor.apply_block like execution.go:171-218).
"""

from __future__ import annotations

import os
import sys

_ENV = "FAIL_TEST_INDEX"

_counter = 0


def _target() -> int:
    v = os.environ.get(_ENV)
    return int(v) if v else -1


def fail_point(_ignored_index: int = 0) -> None:
    """Die if the global call counter has reached FAIL_TEST_INDEX.
    Counting is call-order based like the reference (fail.go:19-34)."""
    global _counter
    t = _target()
    if t < 0:
        return
    if _counter == t:
        sys.stderr.write(f"*** fail-test {t} ***\n")
        sys.stderr.flush()
        os._exit(1)
    _counter += 1


def reset() -> None:
    global _counter
    _counter = 0
