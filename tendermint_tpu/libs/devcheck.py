"""devcheck — runtime invariant checkers for the device pipeline (ISSUE 8).

The runtime twin of tools/tmlint: where tmlint flags call SITES, devcheck
asserts the invariants while the pipeline actually runs. Env-gated —
``TM_TPU_DEVCHECK=1`` (or ``devcheck.enable()`` from a test) turns it on;
off (the default) every hook is a single boolean check, no allocation, no
locking, so production paths pay nothing.

Three checkers:

1. **Relay-thread assertions** — the dispatch-owner thread (the ONLY
   thread allowed to touch the relay, PERF_r05 §2) claims ownership via
   ``claim_relay()``; the launch/transfer/table-upload entry points call
   ``note_relay_touch()``, which raises (and records) when any OTHER
   thread reaches them. ``exempt()`` marks the sanctioned direct paths
   (oversized-batch fallback, warmup) so they do not false-positive.

2. **Lock-order cycle detector** — ``devcheck.lock(name)`` /
   ``rlock(name)`` wrap the coalescer/dispatcher/resolver/metrics locks
   when devcheck is on at CREATION time (plain ``threading.Lock`` when
   off — zero overhead). Each acquisition records an edge held→acquired
   in a process-wide lock-ORDER graph keyed by lock *name* (order classes,
   not instances); the first edge that closes a cycle raises with the
   offending path. A cycle in the order graph is a deadlock waiting for
   the right interleaving, even if this run never hit it.

3. **Write-after-resolve canary** — the resolver registers every verdict
   array it delivers (``canary_register``) with a byte snapshot;
   subsequent sweeps (next resolve, pool-slot release, pipeline close)
   verify the delivered bytes are still identical. A future resolved with
   a zero-copy view of a donated XLA buffer — the PR-7 bug — trips the
   canary the moment a later launch recycles the page. On slot release
   the checker also best-effort poisons the slot's device buffers
   (backends that expose writable host views get 0xAB scribbles, making
   any lingering alias detectable immediately; backends that do not still
   get the byte-stability verification).

Violations are recorded in a process-wide list (``violations()``) and —
for the relay and lock checkers, where the failing stack IS the bug —
also raised as ``DevcheckViolation`` at the offending call site. The
canary records without raising (the mutation is detected asynchronously,
on a thread that did nothing wrong); drive ``check()`` from tests.

Test seams: ``TM_TPU_INJECT_LINTBUG=alias|owner`` re-introduces the PR-7
readback aliasing / a resolver-thread relay touch inside ops/pipeline.py
(mirroring simnet's ``--inject-bug``), so tier-1 proves each checker
actually fires (tests/test_devcheck.py).

Stdlib + numpy only; importable without jax.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Set

import numpy as np

_ON = os.environ.get("TM_TPU_DEVCHECK", "") == "1"

_mtx = threading.Lock()  # guards all devcheck global state below
_violations: List[dict] = []
_counts: Dict[str, int] = {"relay_touches": 0, "lock_acquires": 0,
                           "canary_checks": 0, "canary_registered": 0,
                           "span_opens": 0}
_relay_owners: Set[int] = set()
_lock_edges: Dict[str, Set[str]] = {}
_tls = threading.local()  # .held: list of lock names; .exempt: int depth
# unbalanced-span canary (ISSUE 10): thread ident -> open span names, in
# nesting order. Fed by observability.trace._Span when devcheck is armed;
# span_check() asserts every stack drained (tracer close, pipeline close).
_open_spans: Dict[int, List[str]] = {}

_CANARY_RING = 64
_canaries: "OrderedDict[int, tuple]" = OrderedDict()  # id -> (tag, arr, snap)


class DevcheckViolation(RuntimeError):
    """A devcheck invariant failed; the message carries the context."""


# ---------------------------------------------------------------------------
# enable / disable / reporting


def enabled() -> bool:
    return _ON


def enable(reset: bool = False) -> None:
    """Turn the checkers on (tests; production uses TM_TPU_DEVCHECK=1 so
    import-time lock creation is instrumented too)."""
    global _ON
    if reset:
        reset_state()
    _ON = True


def disable() -> None:
    global _ON
    _ON = False


def reset_state() -> None:
    with _mtx:
        _violations.clear()
        _relay_owners.clear()
        _lock_edges.clear()
        _canaries.clear()
        _open_spans.clear()
        for k in _counts:
            _counts[k] = 0


def _violate(kind: str, message: str) -> dict:
    rec = {
        "kind": kind,
        "message": message,
        "thread": threading.current_thread().name,
    }
    with _mtx:
        _violations.append(rec)
    return rec


def violations() -> List[dict]:
    with _mtx:
        return list(_violations)


def check() -> None:
    """Raise if any violation has been recorded (test teardown hook)."""
    v = violations()
    if v:
        lines = "\n".join(f"  [{r['kind']}] {r['message']} "
                          f"(thread {r['thread']})" for r in v)
        raise DevcheckViolation(f"{len(v)} devcheck violation(s):\n{lines}")


def report() -> dict:
    """JSON-embeddable snapshot (tools/simnet_run.py --devcheck)."""
    with _mtx:
        return {
            "enabled": _ON,
            "violations": list(_violations),
            "counts": dict(_counts),
            "lock_order_edges": int(sum(len(v) for v in _lock_edges.values())),
            "open_spans": int(sum(len(s) for s in _open_spans.values())),
        }


def _bump(key: str) -> None:
    with _mtx:
        _counts[key] += 1


# ---------------------------------------------------------------------------
# 1) relay-thread assertions


def claim_relay(name: str = "") -> None:
    """The dispatch-owner thread claims the relay. Multiple verifiers may
    each claim (one dispatcher per instance); any NON-claimed thread
    reaching a relay entry point afterwards is a violation."""
    if not _ON:
        return
    with _mtx:
        _relay_owners.add(threading.get_ident())


def clear_relay() -> None:
    with _mtx:
        _relay_owners.clear()


def unclaim_relay(idents) -> None:
    """Drop specific thread idents from the owner set — a closing
    verifier retires its dispatcher's claim so (a) later standalone
    direct use stays legal and (b) OS thread-ident reuse cannot hand a
    dead owner's pass to an arbitrary new thread. Safe with devcheck
    off (the set is empty)."""
    with _mtx:
        _relay_owners.difference_update(idents)


class _Exempt:
    def __enter__(self):
        _tls.exempt = getattr(_tls, "exempt", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.exempt -= 1
        return False


def exempt() -> _Exempt:
    """Context manager marking a sanctioned direct relay path (oversized
    fallback, warmup) on the current thread."""
    return _Exempt()


def note_relay_touch(what: str) -> None:
    """Assert the current thread may touch the relay. No-op until a
    dispatcher has claimed ownership (standalone/direct use stays legal);
    afterwards only owner threads and exempt() scopes pass."""
    if not _ON:
        return
    _bump("relay_touches")
    if getattr(_tls, "exempt", 0):
        return
    with _mtx:
        owners = set(_relay_owners)
    if not owners:
        return
    ident = threading.get_ident()
    if ident not in owners:
        rec = _violate(
            "relay-ownership",
            f"{what}: relay touched from thread "
            f"{threading.current_thread().name!r} (ident {ident}) but the "
            f"relay is owned by dispatcher ident(s) {sorted(owners)} — "
            f"exactly ONE dispatch-owner thread may launch/transfer",
        )
        raise DevcheckViolation(rec["message"])


# ---------------------------------------------------------------------------
# 1b) unbalanced-span canary (ISSUE 10 satellite)
#
# observability.trace._Span reports every enter/exit here when devcheck is
# armed; span_check() (tracer close, pipeline close) asserts that every
# thread's stack drained. A span left open — an early return or exception
# path that dodged the `with` discipline, or a hand-called __enter__ —
# corrupts the flame-graph nesting every summary trusts, silently.


def span_opened(name: str) -> None:
    if not _ON:
        return
    ident = threading.get_ident()
    with _mtx:
        _counts["span_opens"] += 1
        _open_spans.setdefault(ident, []).append(name)


def span_closed(name: str) -> None:
    """Pop the most recent matching open span. Unconditional on the live
    flag like DevLock.release: disabling devcheck mid-span must not leave
    a stale entry that later reads as a leak."""
    if not _open_spans:
        # nothing was ever pushed (devcheck never armed): skip the lock —
        # this keeps the tracing-enabled/devcheck-off path allocation- and
        # contention-free (the racy read only ever skips when empty)
        return
    ident = threading.get_ident()
    with _mtx:
        stack = _open_spans.get(ident)
        if not stack:
            return
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                break
        if not stack:
            _open_spans.pop(ident, None)


def span_check(where: str, only_exited: bool = False) -> None:
    """Assert no span is left open (tracer `close()`, verifier close).
    `only_exited=True` restricts the check to threads that are no longer
    alive — the right scope for a component close() racing unrelated
    live threads legitimately mid-span (a span on a DEAD thread can
    never be closed, so it is always a leak). Raises with the per-thread
    leftovers; only the REPORTED entries are cleared, so a live thread's
    in-progress bookkeeping is never corrupted and one leak does not
    re-report at every subsequent checkpoint."""
    if not _ON:
        return
    names = {t.ident: t.name for t in threading.enumerate()}
    with _mtx:
        leftover = {
            i: list(s)
            for i, s in _open_spans.items()
            if s and not (only_exited and i in names)
        }
        for i in leftover:
            _open_spans.pop(i, None)
    if not leftover:
        return
    detail = "; ".join(
        f"{names.get(i, 'exited-thread')}({i}): {s}"
        for i, s in sorted(leftover.items())
    )
    rec = _violate(
        "unbalanced-span",
        f"{sum(len(s) for s in leftover.values())} span(s) left open at "
        f"{where} — every span must close on the thread that opened it "
        f"({detail})",
    )
    raise DevcheckViolation(rec["message"])


# ---------------------------------------------------------------------------
# 2) lock-order cycle detector


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _reaches(src: str, dst: str, edges: Dict[str, Set[str]]) -> Optional[list]:
    """DFS path src -> dst in the order graph, or None."""
    stack = [(src, [src])]
    seen = set()
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        if node in seen:
            continue
        seen.add(node)
        for nxt in edges.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


def _note_intent(name: str) -> Optional[list]:
    """Record the prospective order edge BEFORE the blocking acquire and
    return the cycle path if this edge closes one (None otherwise).
    Intent-time recording is what lets a CONTESTED inversion be reported
    instead of hanging: edge insertion + cycle check serialize under
    _mtx, so of two threads deadlocking each other at first contact, the
    second one's check must see the first one's edge and raise before
    ever blocking."""
    _bump("lock_acquires")
    held = _held()
    if not held or held[-1] == name:
        return None
    holder = held[-1]
    with _mtx:
        fwd = _lock_edges.setdefault(holder, set())
        new_edge = name not in fwd
        fwd.add(name)
        return _reaches(name, holder, _lock_edges) if new_edge else None


def _note_released(name: str) -> None:
    held = _held()
    # release order may differ from acquire order (handoffs); remove the
    # most recent matching entry
    for i in range(len(held) - 1, -1, -1):
        if held[i] == name:
            del held[i]
            return


def _redepth() -> dict:
    d = getattr(_tls, "redepth", None)
    if d is None:
        d = _tls.redepth = {}
    return d


class DevLock:
    """A named threading.Lock/RLock wrapper feeding the order graph.
    Supports the full lock protocol (with-statement, Condition wrapping,
    timeout/blocking acquire). Reentrant acquisitions of the same RLock
    do not re-record (per-thread depth counter, so the stack pop pairs
    with the OUTERMOST acquire).

    Stack bookkeeping is deliberately NOT gated on the live _ON flag at
    release time: a test disabling devcheck between an acquire and its
    release must still pop the armed-time push, or the stale entry
    manufactures phantom order edges (and false cycles) for every later
    acquisition on that thread."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._l = threading.RLock() if reentrant else threading.Lock()
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        """The order edge is recorded (and the cycle check runs) BEFORE
        the blocking acquire — a contested AB/BA inversion raises on one
        of the two threads instead of wedging both with no diagnostic.

        On a detected cycle: try a NON-blocking acquire first. If it
        succeeds the violation raises with the lock HELD (what both a
        bare acquire() caller and Condition._acquire_restore — cv.wait's
        re-acquire, whose enclosing `with cv:` later releases — expect);
        if the lock is contended, that IS the live deadlock, and the
        violation raises WITHOUT the lock (hanging is the alternative).
        The exception's `lock_held` attribute says which happened;
        __enter__ uses it to release only what was taken."""
        if _ON:
            if self._reentrant and _redepth().get(self.name, 0) > 0:
                ok = self._l.acquire(blocking, timeout)
                if ok:
                    _redepth()[self.name] += 1
                return ok  # re-entry: no new order edge, no push
            back = _note_intent(self.name)
            if back is not None:
                got = self._l.acquire(False)
                rec = _violate(
                    "lock-order",
                    f"acquiring {self.name!r} while holding {back[-1]!r} "
                    f"closes a cycle in the lock-order graph: "
                    f"{' -> '.join(back)} -> {self.name} — a deadlock "
                    f"under the right interleaving"
                    + ("" if got else " (lock contended: a LIVE deadlock "
                                      "was avoided; lock NOT acquired)"),
                )
                e = DevcheckViolation(rec["message"])
                e.lock_held = bool(got)
                raise e
        ok = self._l.acquire(blocking, timeout)
        if ok and _ON:
            if self._reentrant:
                _redepth()[self.name] = 1
            _held().append(self.name)
        return ok

    def release(self) -> None:
        if self._reentrant:
            d = _redepth()
            n = d.get(self.name, 0)
            if n > 1:
                d[self.name] = n - 1
                self._l.release()
                return
            d.pop(self.name, None)
        _note_released(self.name)  # unconditional: pairs any armed push
        self._l.release()

    def __enter__(self):
        try:
            self.acquire()  # tmlint: disable=lock-discipline — this IS the context manager
        except DevcheckViolation as e:
            # __exit__ never runs when __enter__ raises — release here
            # (when the violation path actually took the lock) or the
            # reported POTENTIAL deadlock becomes a real one
            if getattr(e, "lock_held", True):
                self._l.release()
            raise
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:  # Lock protocol completeness
        locked = getattr(self._l, "locked", None)
        return locked() if locked is not None else False


def lock(name: str):
    """A lock for `name`: instrumented when devcheck is on at creation
    time, a plain threading.Lock otherwise (zero overhead off)."""
    return DevLock(name) if _ON else threading.Lock()


def rlock(name: str):
    return DevLock(name, reentrant=True) if _ON else threading.RLock()


# ---------------------------------------------------------------------------
# 3) write-after-resolve canary


def canary_register(arr, tag: str = "") -> None:
    """Snapshot a delivered verdict array; later sweeps verify the bytes
    never change. Ring-bounded (the last _CANARY_RING resolutions)."""
    if not _ON or not isinstance(arr, np.ndarray):
        return
    snap = arr.tobytes()
    with _mtx:
        _counts["canary_registered"] += 1
        _canaries[id(arr)] = (tag, arr, snap)
        while len(_canaries) > _CANARY_RING:
            _canaries.popitem(last=False)


def canary_sweep(where: str) -> int:
    """Verify every registered verdict array is byte-stable. Returns the
    number of violations found (each registered once, then dropped).
    Records without raising — the sweeping thread is not the culprit."""
    if not _ON:
        return 0
    with _mtx:
        items = list(_canaries.items())
    bad = []
    for key, (tag, arr, snap) in items:
        _bump("canary_checks")
        try:
            now = arr.tobytes()
        except Exception:  # noqa: BLE001 — a freed buffer IS the finding
            now = None
        if now != snap:
            bad.append(key)
            _violate(
                "write-after-resolve",
                f"verdict array ({tag}) mutated AFTER resolution "
                f"(detected at {where}) — a future was resolved with a "
                f"non-owning view of a recycled device buffer (the PR-7 "
                f"donation-aliasing class); resolve with np.array/.copy()",
            )
    if bad:
        with _mtx:
            for k in bad:
                _canaries.pop(k, None)
    return len(bad)


def canary_clear() -> None:
    with _mtx:
        _canaries.clear()


def on_slot_release(arrays) -> None:
    """Pool-slot return hook: sweep the canaries, then poison the slot's
    buffers where the backend exposes writable host views (0xAB scribble)
    so any alias still pointing at them fails the NEXT sweep loudly."""
    if not _ON:
        return
    canary_sweep("pool.release")
    if not arrays:
        return
    for a in arrays:
        if isinstance(a, np.ndarray):
            continue  # host array passthrough — may be shared, never poison
        try:
            v = np.asarray(a)
            if v.flags.writeable:
                v.fill(0xAB)
        except Exception:  # noqa: BLE001 — poisoning is best-effort
            pass


# ---------------------------------------------------------------------------
# injected-bug seams (tests only; mirrors simnet's --inject-bug pattern)


def inject_lintbug(kind: str) -> bool:
    """True when TM_TPU_INJECT_LINTBUG names this seam AND devcheck is
    armed. The devcheck gate is load-bearing: the seams deliberately
    corrupt verdicts / touch the relay cross-thread, so a stale env
    export with the checkers off must stay inert. Read per call so tests
    can flip it via monkeypatch.setenv without reimporting."""
    return _ON and os.environ.get("TM_TPU_INJECT_LINTBUG", "") == kind
