"""tendermint_tpu.libs — utility libraries (reference libs/, SURVEY.md L0)."""
