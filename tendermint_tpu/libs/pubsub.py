"""Pubsub with query filtering.

Reference parity: libs/pubsub/pubsub.go (Server with per-subscriber
buffered channels) + libs/pubsub/query (the event query language:
`tm.event='NewBlock' AND tx.height>5`). The query grammar here covers the
operators the reference's PEG grammar defines: =, <, <=, >, >=, CONTAINS,
EXISTS, AND (the reference has no OR — parity).
"""

from __future__ import annotations

import queue
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Message:
    data: object
    events: Dict[str, List[str]] = field(default_factory=dict)


class Query:
    """Parsed event query (libs/pubsub/query/query.go)."""

    _COND_RE = re.compile(
        r"\s*([\w.\-/]+)\s*(=|<=|>=|<|>|CONTAINS|EXISTS)\s*"
        r"('(?:[^']*)'|\"(?:[^\"]*)\"|[\w.\-+]+)?\s*",
    )

    def __init__(self, s: str):
        self._source = s
        self.conditions: List[Tuple[str, str, Optional[str]]] = []
        if not s.strip():
            return
        for part in re.split(r"\bAND\b", s):
            part = part.strip()
            if not part:
                continue
            m = self._COND_RE.fullmatch(part)
            if not m:
                raise ValueError(f"invalid query condition {part!r}")
            key, op, val = m.group(1), m.group(2), m.group(3)
            if op != "EXISTS":
                if val is None:
                    raise ValueError(f"operator {op} needs a value in {part!r}")
                if val[0] in "'\"":
                    val = val[1:-1]
            self.conditions.append((key, op, val))

    def matches(self, events: Dict[str, List[str]]) -> bool:
        for key, op, want in self.conditions:
            values = events.get(key)
            if values is None:
                return False
            if op == "EXISTS":
                continue
            if not any(self._match_one(op, got, want) for got in values):
                return False
        return True

    @staticmethod
    def _match_one(op: str, got: str, want: str) -> bool:
        if op == "=":
            return got == want
        if op == "CONTAINS":
            return want in got
        try:
            g, w = float(got), float(want)
        except ValueError:
            return False
        return {"<": g < w, "<=": g <= w, ">": g > w, ">=": g >= w}[op]

    def __str__(self) -> str:
        return self._source

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self._source == other._source

    def __hash__(self) -> int:
        return hash(self._source)


ALL = Query("")  # matches everything (query.Empty)


class Subscription:
    def __init__(self, q: Query, capacity: int = 100):
        self.query = q
        self._q: "queue.Queue[Message]" = queue.Queue(maxsize=capacity if capacity else 0)
        self.canceled = threading.Event()
        self.cancel_reason: str = ""

    def put(self, msg: Message, block: bool) -> bool:
        try:
            self._q.put(msg, block=block, timeout=None if block else 0)
            return True
        except queue.Full:
            return False

    def next(self, timeout: Optional[float] = None) -> Message:
        return self._q.get(timeout=timeout)

    def try_next(self) -> Optional[Message]:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def cancel(self, reason: str = "") -> None:
        self.cancel_reason = reason
        self.canceled.set()


class Server:
    """libs/pubsub/pubsub.go:104 Server."""

    def __init__(self):
        self._subs: Dict[Tuple[str, str], Subscription] = {}
        self._mtx = threading.RLock()

    def subscribe(
        self, subscriber: str, q: Query, capacity: int = 100
    ) -> Subscription:
        with self._mtx:
            key = (subscriber, str(q))
            if key in self._subs:
                raise ValueError(f"already subscribed: {key}")
            sub = Subscription(q, capacity)
            self._subs[key] = sub
            return sub

    def unsubscribe(self, subscriber: str, q: Query) -> None:
        with self._mtx:
            key = (subscriber, str(q))
            sub = self._subs.pop(key, None)
            if sub is None:
                raise KeyError(f"not subscribed: {key}")
            sub.cancel("unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            keys = [k for k in self._subs if k[0] == subscriber]
            if not keys:
                raise KeyError(f"not subscribed: {subscriber}")
            for k in keys:
                self._subs.pop(k).cancel("unsubscribed")

    def publish(self, data: object, events: Optional[Dict[str, List[str]]] = None) -> None:
        events = events or {}
        msg = Message(data=data, events=events)
        with self._mtx:
            subs = list(self._subs.items())
        for (name, _), sub in subs:
            if sub.query.matches(events):
                if not sub.put(msg, block=False):
                    # slow subscriber: cancel like the reference's
                    # ErrOutOfCapacity eviction
                    sub.cancel("out of capacity")

    def num_clients(self) -> int:
        with self._mtx:
            return len({k[0] for k in self._subs})

    def num_client_subscriptions(self, subscriber: str) -> int:
        with self._mtx:
            return sum(1 for k in self._subs if k[0] == subscriber)
