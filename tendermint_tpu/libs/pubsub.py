"""Pubsub with query filtering.

Reference parity: libs/pubsub/pubsub.go (Server with per-subscriber
buffered channels) + libs/pubsub/query (the event query language:
`tm.event='NewBlock' AND tx.height>5`). The parser below is a
recursive-descent implementation of the reference's PEG grammar
(libs/pubsub/query/query.peg) with its typed operand semantics
(libs/pubsub/query/query.go:140-200, matchValue :396-503): quoted
strings, int64/float64 numbers, `TIME <RFC3339>` and `DATE <ISO-date>`
literals, operators =, <, <=, >, >=, CONTAINS, EXISTS joined by AND (the
reference has no OR — parity). Quoted values are tokenized, so a literal
containing ` AND ` parses; event values matched against numeric operands
are filtered through the reference's numRegex first (`8.045stake` > 7.0
matches), and float values compared to int operands truncate exactly as
strconv-then-int64 does.
"""

from __future__ import annotations

import queue
import re
import threading
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Dict, List, NamedTuple, Optional, Union


@dataclass
class Message:
    data: object
    events: Dict[str, List[str]] = field(default_factory=dict)


Operand = Union[str, int, float, datetime]


class Condition(NamedTuple):
    """query.Condition: (CompositeKey, Op, Operand) with a TYPED operand:
    str (quoted value), int, float, or tz-aware datetime (TIME/DATE)."""

    key: str
    op: str
    operand: Optional[Operand]


# tag <- (![ \t\n\r\\()"'=><] .)+
_TAG_STOP = set(" \t\n\r\\()\"'=><")
_NUM_RE = re.compile(r"(0|[1-9][0-9]*)(\.[0-9]*)?")
# numRegex in query.go:23 — the value-side number filter
_VAL_NUM_RE = re.compile(r"[0-9\.]+")
_TIME_RE = re.compile(
    r"[12][0-9]{3}-[01][0-9]-[0-3][0-9]T[0-9]{2}:[0-9]{2}:[0-9]{2}"
    r"(?:\.[0-9]+)?(?:[-+][0-9]{2}:[0-9]{2}|Z)"  # RFC3339 incl. fractions
)
_DATE_RE = re.compile(r"[12][0-9]{3}-[01][0-9]-[0-3][0-9]")


def _parse_time(s: str) -> datetime:
    dt = datetime.fromisoformat(s.replace("Z", "+00:00"))
    return dt if dt.tzinfo else dt.replace(tzinfo=timezone.utc)


def _parse_date(s: str) -> datetime:
    return datetime.fromisoformat(s).replace(tzinfo=timezone.utc)


class _Parser:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def error(self, what: str) -> ValueError:
        return ValueError(f"invalid query: expected {what} at offset {self.i} in {self.s!r}")

    def spaces(self) -> None:
        while self.i < len(self.s) and self.s[self.i] in " \t\n\r":
            self.i += 1

    def literal(self, lit: str) -> bool:
        if self.s.startswith(lit, self.i):
            self.i += len(lit)
            return True
        return False

    def regex(self, rx: "re.Pattern") -> Optional[str]:
        m = rx.match(self.s, self.i)
        if m is None:
            return None
        self.i = m.end()
        return m.group(0)

    def tag(self) -> str:
        j = self.i
        while j < len(self.s) and self.s[j] not in _TAG_STOP:
            j += 1
        if j == self.i:
            raise self.error("tag")
        out = self.s[self.i : j]
        self.i = j
        return out

    def quoted(self) -> str:
        if not self.literal("'"):
            raise self.error("quoted value")
        j = self.s.find("'", self.i)
        if j < 0:
            raise self.error("closing quote")
        out = self.s[self.i : j]
        self.i = j + 1
        return out

    def number(self) -> Optional[Union[int, float]]:
        text = self.regex(_NUM_RE)
        if text is None:
            return None
        # number must end the operand (no trailing junk like `7stake`)
        if self.i < len(self.s) and self.s[self.i] not in " \t\n\r":
            raise self.error("end of number")
        return float(text) if "." in text else int(text)

    def operand(self, allow_string: bool) -> Operand:
        if self.literal("TIME "):
            self.spaces()
            text = self.regex(_TIME_RE)
            if text is None:
                raise self.error("RFC3339 time after TIME")
            return _parse_time(text)
        if self.literal("DATE "):
            self.spaces()
            text = self.regex(_DATE_RE)
            if text is None:
                raise self.error("date after DATE")
            return _parse_date(text)
        num = self.number()
        if num is not None:
            return num
        if allow_string and self.i < len(self.s) and self.s[self.i] == "'":
            return self.quoted()
        raise self.error("operand")

    def condition(self) -> Condition:
        key = self.tag()
        self.spaces()
        for op in ("<=", ">=", "<", ">", "="):
            if self.literal(op):
                self.spaces()
                # inequalities take number/time/date only; = also strings
                return Condition(key, op, self.operand(allow_string=op == "="))
        if self.literal("CONTAINS"):
            self.spaces()
            return Condition(key, "CONTAINS", self.quoted())
        if self.literal("EXISTS"):
            return Condition(key, "EXISTS", None)
        raise self.error("operator")

    def parse(self) -> List[Condition]:
        out = [self.condition()]
        while True:
            self.spaces()
            if self.i >= len(self.s):
                return out
            if not self.literal("AND"):
                raise self.error("AND")
            self.spaces()
            out.append(self.condition())


class Query:
    """Parsed event query (libs/pubsub/query/query.go)."""

    def __init__(self, s: str):
        self._source = s
        self.conditions: List[Condition] = []
        if not s.strip():
            return
        self.conditions = _Parser(s.strip()).parse()

    def matches(self, events: Dict[str, List[str]]) -> bool:
        return self.match_conditions(events, self.conditions)

    @staticmethod
    def match_conditions(events: Dict[str, List[str]], conditions) -> bool:
        """AND-match a condition list against flattened events (shared by
        pubsub matching and the indexer's search post-filters)."""
        for key, op, want in conditions:
            if op == "EXISTS":
                # query.go:246-262: composite "type.attr" tags look up
                # exactly; bare tags PREFIX-match ("sl" matches "slash.*")
                if "." in key:
                    if key not in events:
                        return False
                elif not any(k.startswith(key) for k in events):
                    return False
                continue
            values = events.get(key)
            if values is None:
                return False
            if not any(Query._match_one(op, got, want) for got in values):
                return False
        return True

    @staticmethod
    def _match_one(op: str, got: str, want: Operand) -> bool:
        """matchValue (query.go:396-503): the event value `got` is coerced
        toward the OPERAND's type; coercion failure is no-match."""
        if isinstance(want, str):
            if op == "=":
                return got == want
            if op == "CONTAINS":
                return want in got
            return False
        if isinstance(want, datetime):
            try:
                g = _parse_time(got) if "T" in got else _parse_date(got)
            except ValueError:
                return False
        else:
            m = _VAL_NUM_RE.search(got)
            if m is None:
                return False
            try:
                g = float(m.group(0))
            except ValueError:
                return False
            if isinstance(want, int):
                g = int(g) if "." in m.group(0) else int(m.group(0))
        return {
            "=": g == want, "<": g < want, "<=": g <= want,
            ">": g > want, ">=": g >= want,
        }[op]

    def __str__(self) -> str:
        return self._source

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self._source == other._source

    def __hash__(self) -> int:
        return hash(self._source)


ALL = Query("")  # matches everything (query.Empty)


class Subscription:
    def __init__(self, q: Query, capacity: int = 100):
        self.query = q
        self._q: "queue.Queue[Message]" = queue.Queue(maxsize=capacity if capacity else 0)
        self.canceled = threading.Event()
        self.cancel_reason: str = ""

    def put(self, msg: Message, block: bool) -> bool:
        try:
            self._q.put(msg, block=block, timeout=None if block else 0)
            return True
        except queue.Full:
            return False

    def next(self, timeout: Optional[float] = None) -> Message:
        return self._q.get(timeout=timeout)

    def try_next(self) -> Optional[Message]:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def cancel(self, reason: str = "") -> None:
        self.cancel_reason = reason
        self.canceled.set()


class Server:
    """libs/pubsub/pubsub.go:104 Server."""

    def __init__(self):
        self._subs: Dict[Tuple[str, str], Subscription] = {}
        self._mtx = threading.RLock()

    def subscribe(
        self, subscriber: str, q: Query, capacity: int = 100
    ) -> Subscription:
        with self._mtx:
            key = (subscriber, str(q))
            if key in self._subs:
                raise ValueError(f"already subscribed: {key}")
            sub = Subscription(q, capacity)
            self._subs[key] = sub
            return sub

    def unsubscribe(self, subscriber: str, q: Query) -> None:
        with self._mtx:
            key = (subscriber, str(q))
            sub = self._subs.pop(key, None)
            if sub is None:
                raise KeyError(f"not subscribed: {key}")
            sub.cancel("unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._mtx:
            keys = [k for k in self._subs if k[0] == subscriber]
            if not keys:
                raise KeyError(f"not subscribed: {subscriber}")
            for k in keys:
                self._subs.pop(k).cancel("unsubscribed")

    def publish(self, data: object, events: Optional[Dict[str, List[str]]] = None) -> None:
        events = events or {}
        msg = Message(data=data, events=events)
        with self._mtx:
            subs = list(self._subs.items())
        for (name, _), sub in subs:
            if sub.query.matches(events):
                if not sub.put(msg, block=False):
                    # slow subscriber: cancel like the reference's
                    # ErrOutOfCapacity eviction
                    sub.cancel("out of capacity")

    def num_clients(self) -> int:
        with self._mtx:
            return len({k[0] for k in self._subs})

    def num_client_subscriptions(self, subscriber: str) -> int:
        with self._mtx:
            return sum(1 for k in self._subs if k[0] == subscriber)
