"""Inspect — read-only RPC over the data directories of a stopped node.

Reference parity: internal/inspect/inspect.go — serves the store-backed
subset of the RPC surface (status/block/commit/validators/...) without
starting consensus or p2p, for post-mortem debugging.
"""

from __future__ import annotations

from typing import Optional

from ..rpc.core import Environment
from ..rpc.server import RPCServer


class _StubConsensus:
    """Just enough surface for the store-backed Environment methods."""

    _priv_validator_pub_key = None

    def __init__(self, state):
        self._state = state
        from ..consensus.types import RoundState

        self.rs = RoundState()

    @property
    def committed_state(self):
        return self._state


class _InspectNode:
    def __init__(self, config, genesis, state_store, block_store,
                 tx_index_sink=None):
        self.config = config
        self.genesis = genesis
        self.state_store = state_store
        self.block_store = block_store
        self.router = None
        self.mempool = None
        self.mempool_reactor = None
        self.evidence_pool = None
        self.proxy_app = None
        self.tx_index_sink = tx_index_sink
        state = state_store.load()
        self.consensus = _StubConsensus(state)
        self.node_key = None

    @property
    def node_id(self) -> str:
        return ""


# routes the inspect server exposes (inspect.go:60-90 + the indexer-backed
# routes the reference inspect serves, internal/inspect/rpc/rpc.go:48-66)
INSPECT_ROUTES = [
    "status", "health", "genesis", "block", "block_by_hash", "blockchain",
    "commit", "block_results", "validators", "consensus_params",
    "tx", "tx_search", "block_search",
]


def _open_index_sink(config):
    """Open the stopped node's tx_index KV sink read-only-ish — the same
    data dir the live node's IndexerService wrote
    (internal/inspect/inspect.go NewFromConfig -> sink setup)."""
    if "kv" not in getattr(config.tx_index, "indexer", ""):
        return None
    home = config.base.home
    if not home or config.base.db_backend in ("memdb", "mem"):
        return None
    from ..db import backend as db_backend
    from ..indexer import KVSink

    return KVSink(db_backend(config.base.db_backend, config.base.db_path("tx_index")))


class Inspector:
    """inspect.go Inspector."""

    def __init__(self, config, genesis, state_store, block_store,
                 laddr: Optional[str] = None, tx_index_sink=None):
        if tx_index_sink is None:
            tx_index_sink = _open_index_sink(config)
        node = _InspectNode(
            config, genesis, state_store, block_store, tx_index_sink
        )
        self._env = Environment(node)
        self._server = RPCServer(
            laddr or config.rpc.laddr, self._env, routes=INSPECT_ROUTES
        )

    @property
    def env(self) -> Environment:
        return self._env

    @property
    def listen_addr(self) -> str:
        return self._server.listen_addr

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()
