"""Batched light-client verification plans (ISSUE 11).

One client request is a (trusted, untrusted) header pair plus trust
parameters. `prepare_request` runs every host-side check (trust level,
expiry, hash chaining, clock drift — through the light/verifier.py
prepare seam, so the checks are the SAME code the sequential path runs)
and captures the request's sig work as EntryBlocks with epoch metadata
attached. The service ships those blocks through the shared
AsyncBatchVerifier, where same-epoch work from MANY requests coalesces
into one device batch (mesh lanes when enabled); `conclude_request`
applies the device verdict rows back in sequential stage order so error
precedence — and every error string — matches light/verifier.py exactly.

Error-precedence contract (what makes verdicts byte-identical to the
sequential path): verify_non_adjacent raises the trusting-stage error
before the +2/3 stage runs at all, so

  * a host-side failure while preparing stage k is recorded ON stage k
    and later stages are not prepared (sequential never reached them);
  * verdicts are applied in stage order — stage k's sig failure masks
    anything recorded for stage k+1.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..types import Fraction
from ..wire.canonical import Timestamp
from . import verifier

# light/client.go:56 (mirrors client.DEFAULT_MAX_CLOCK_DRIFT without
# pulling the provider/store stack into this module's import graph)
DEFAULT_MAX_CLOCK_DRIFT = 10.0


@dataclass
class HeaderRequest:
    """One light-client verification request: skip-verify
    `untrusted_header` from `trusted_header` (light/verifier.go Verify).
    `now` is optional — the service resolves one clock reading per RPC
    batch when omitted, which is also what lets identical requests from
    different clients share a verification."""

    trusted_header: object  # SignedHeader
    trusted_vals: object  # ValidatorSet
    untrusted_header: object  # SignedHeader
    untrusted_vals: object  # ValidatorSet
    trusting_period: float
    max_clock_drift: float = DEFAULT_MAX_CLOCK_DRIFT
    trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL
    now: Optional[Timestamp] = None


def fingerprint(req: HeaderRequest, now: Timestamp) -> Optional[tuple]:
    """Memo / single-flight key: fully identifies the verification's
    inputs. Header hashes pin every header field, the untrusted COMMIT
    hash pins the signatures (a forged commit under a genuine header
    must never alias a clean request), valset hashes pin keys+powers,
    and every trust parameter — including the resolved `now`, because
    expiry and clock-drift verdicts depend on it — rides along.

    Returns None when the request is NOT fingerprintable: an incomplete
    header hashes to b"" (Header.hash's nil convention), which would
    alias every such request onto one memo slot — those verify uniquely
    instead of risking a wrong cached verdict."""
    th = req.trusted_header.header.hash()
    uh = req.untrusted_header.header.hash()
    if not th or not uh:
        return None
    return (
        th,
        uh,
        req.untrusted_header.commit.hash(),
        req.trusted_vals.hash(),
        req.untrusted_vals.hash(),
        float(req.trusting_period),
        float(req.max_clock_drift),
        req.trust_level.numerator,
        req.trust_level.denominator,
        now.seconds,
        now.nanos,
    )


@dataclass
class StagePlan:
    """One prepared sig-check stage: exactly one of {entries+conclude,
    error, neither} — `neither` means the stage completed synchronously
    at prepare time (sub-threshold commit) and passed."""

    kind: str
    entries: object = None
    conclude: Optional[Callable] = None
    error: Optional[BaseException] = None


@dataclass
class RequestPlan:
    stages: List[StagePlan] = field(default_factory=list)
    error: Optional[BaseException] = None  # host-check failure (pre-sig)

    def entry_stages(self) -> List[StagePlan]:
        return [s for s in self.stages if s.entries is not None]


def prepare_request(req: HeaderRequest, now: Timestamp) -> RequestPlan:
    """Host half of one request: non-sig checks + sig-work extraction.
    Never raises — failures land in the plan so the service turns them
    into streamed verdicts."""
    try:
        checks = verifier.prepare_verify(
            req.trusted_header, req.trusted_vals,
            req.untrusted_header, req.untrusted_vals,
            req.trusting_period, now, req.max_clock_drift, req.trust_level,
        )
    except Exception as e:  # noqa: BLE001 — any host-check error is the verdict
        return RequestPlan(error=e)
    plan = RequestPlan()
    for chk in checks:
        try:
            entries, conclude = chk.prepare()
        except Exception as e:  # noqa: BLE001
            plan.stages.append(StagePlan(chk.kind, error=e))
            break  # sequential surfaces this before later stages run
        plan.stages.append(
            StagePlan(chk.kind, entries=entries, conclude=conclude)
        )
    return plan


def conclude_request(plan: RequestPlan, verdicts) -> Optional[BaseException]:
    """Apply device verdicts in SEQUENTIAL stage order. `verdicts` has
    one item per entry_stages() entry, in that order — each a bool
    validity row or the exception its pipeline future resolved with.
    Returns the request's error (byte-identical to the sequential
    path's) or None on acceptance."""
    if plan.error is not None:
        return plan.error
    vi = 0
    for st in plan.stages:
        if st.error is not None:
            return st.error
        if st.entries is None:
            continue  # verified synchronously at prepare time
        v = verdicts[vi]
        vi += 1
        if isinstance(v, BaseException):
            return v  # pipeline-level failure (DispatchError): not parity
        try:
            st.conclude(v)
        except Exception as e:  # noqa: BLE001 — the wrapped stage error
            return e
    return None


def group_stats(plans) -> Dict[Optional[bytes], int]:
    """Per-epoch stage-block counts across a batch of plans — the
    epoch-grouping shape the service reports (the actual coalescing is
    the shared pipeline's; this is its observable input)."""
    groups: Dict[Optional[bytes], int] = {}
    for p in plans:
        for st in p.entry_stages():
            k = getattr(st.entries, "epoch_key", None)
            groups[k] = groups.get(k, 0) + 1
    return groups
