"""Light-block providers.

Reference parity: light/provider/ — the Provider interface (LightBlock,
ReportEvidence) and concrete implementations. The reference's primary
implementation fetches over RPC (provider/http); here the equivalent
node-backed provider reads another node's stores directly (the in-process
analog used by tests and statesync) and the RPC-backed provider lands with
the RPC client.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Optional

from ..types import Commit, Header, SignedHeader, ValidatorSet


@dataclass
class LightBlock:
    """types.LightBlock: SignedHeader + its validator set."""

    signed_header: SignedHeader
    validators: ValidatorSet

    @property
    def height(self) -> int:
        return self.signed_header.header.height

    def hash(self) -> bytes:
        return self.signed_header.header.hash()


class ErrLightBlockNotFound(KeyError):
    pass


class Provider(abc.ABC):
    @abc.abstractmethod
    def light_block(self, height: int) -> LightBlock:
        """Fetch the light block at height (0 = latest). Raises
        ErrLightBlockNotFound when unavailable."""

    def report_evidence(self, ev) -> None:  # noqa: B027 — optional hook
        pass


class NodeBackedProvider(Provider):
    """Reads block store + state store of a (local) node."""

    def __init__(self, block_store, state_store):
        self._bs = block_store
        self._ss = state_store

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self._bs.height()
        meta = self._bs.load_block_meta(height)
        commit = self._bs.load_block_commit(height)
        if commit is None and height == self._bs.height():
            # at the tip only the seen commit exists (core/blocks.go Commit)
            seen = self._bs.load_seen_commit()
            if seen is not None and seen.height == height:
                commit = seen
        if meta is None or commit is None:
            raise ErrLightBlockNotFound(height)
        try:
            vals = self._ss.load_validators(height)
        except KeyError as e:
            raise ErrLightBlockNotFound(height) from e
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validators=vals,
        )
