"""Light-block providers.

Reference parity: light/provider/ — the Provider interface (LightBlock,
ReportEvidence) and concrete implementations. The reference's primary
implementation fetches over RPC (provider/http); here the equivalent
node-backed provider reads another node's stores directly (the in-process
analog used by tests and statesync) and the RPC-backed provider lands with
the RPC client.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Optional

from ..types import Commit, Header, SignedHeader, ValidatorSet


@dataclass
class LightBlock:
    """types.LightBlock: SignedHeader + its validator set."""

    signed_header: SignedHeader
    validators: ValidatorSet

    @property
    def height(self) -> int:
        return self.signed_header.header.height

    def hash(self) -> bytes:
        return self.signed_header.header.hash()


class ErrLightBlockNotFound(KeyError):
    pass


class Provider(abc.ABC):
    @abc.abstractmethod
    def light_block(self, height: int) -> LightBlock:
        """Fetch the light block at height (0 = latest). Raises
        ErrLightBlockNotFound when unavailable."""

    def report_evidence(self, ev) -> None:  # noqa: B027 — optional hook
        pass


class HTTPProvider(Provider):
    """light/provider/http: fetches signed headers + validator sets from a
    node's JSON-RPC endpoint (/commit, /validators with pagination)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self._url = base_url.rstrip("/")
        for prefix in ("tcp://",):
            if self._url.startswith(prefix):
                self._url = "http://" + self._url[len(prefix):]
        if not self._url.startswith("http"):
            self._url = "http://" + self._url
        self._timeout = timeout

    def _get(self, path: str) -> dict:
        import json as _json
        import urllib.request

        with urllib.request.urlopen(
            f"{self._url}/{path}", timeout=self._timeout
        ) as r:
            res = _json.loads(r.read())
        if "error" in res and res["error"]:
            raise ErrLightBlockNotFound(res["error"])
        return res["result"]

    def light_block(self, height: int) -> LightBlock:
        from ..wire.json_types import parse_signed_header, parse_validator_set

        try:
            q = f"?height={height}" if height else ""
            com = self._get(f"commit{q}")
            sh = parse_signed_header(com["signed_header"])
            h = sh.header.height
            vals = []
            page = 1
            while True:
                res = self._get(f"validators?height={h}&page={page}&per_page=100")
                got = res["validators"]
                if not got:
                    # a byzantine primary could promise total=N forever;
                    # an empty page means it cannot deliver — stop
                    raise ErrLightBlockNotFound(f"empty validator page {page}")
                vals.extend(got)
                if len(vals) >= int(res["total"]) or page >= 100:
                    break
                page += 1
            vset = parse_validator_set({"validators": vals})
        except (OSError, ValueError, KeyError) as e:
            raise ErrLightBlockNotFound(str(e)) from e
        return LightBlock(signed_header=sh, validators=vset)

    def report_evidence(self, ev) -> None:
        import base64 as _b64
        import urllib.parse
        import urllib.request

        from ..types.evidence import encode_evidence

        # percent-encode: raw base64 '+' would decode as a space in the
        # server's query parser and silently corrupt the evidence
        data = urllib.parse.quote(_b64.b64encode(encode_evidence(ev)).decode())
        try:
            urllib.request.urlopen(
                f"{self._url}/broadcast_evidence?evidence=%22{data}%22",
                timeout=self._timeout,
            ).read()
        except OSError:
            pass  # best effort (detector.go sendEvidence)


class NodeBackedProvider(Provider):
    """Reads block store + state store of a (local) node."""

    def __init__(self, block_store, state_store):
        self._bs = block_store
        self._ss = state_store

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self._bs.height()
        meta = self._bs.load_block_meta(height)
        commit = self._bs.load_block_commit(height)
        if commit is None and height == self._bs.height():
            # at the tip only the seen commit exists (core/blocks.go Commit)
            seen = self._bs.load_seen_commit()
            if seen is not None and seen.height == height:
                commit = seen
        if meta is None or commit is None:
            raise ErrLightBlockNotFound(height)
        try:
            vals = self._ss.load_validators(height)
        except KeyError as e:
            raise ErrLightBlockNotFound(height) from e
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validators=vals,
        )
