"""Light client — stateful header verification with bisection.

Reference parity: light/client.go — trust options bootstrap (:370),
VerifyLightBlockAtHeight (:406), sequential verification (:546), skipping
verification with the 9/16 bisection pivot (:639, :44-45), backwards
verification (:878), primary/witness management (:935-1035), and the
divergence detector (detector.go) comparing the primary's headers against
witnesses.
"""

from __future__ import annotations

import http.client as _http
from dataclasses import dataclass
from typing import Callable, List, Optional

from ..libs.timeutil import now_ts as _now_ts
from ..types import Fraction
from ..wire.canonical import Timestamp
from . import verifier
from .provider import ErrLightBlockNotFound, LightBlock, Provider
from .store import LightStore

DEFAULT_PRUNING_SIZE = 1000
DEFAULT_MAX_CLOCK_DRIFT = 10.0  # seconds (light/client.go:56)

# bisection pivot: 9/16 (light/client.go:44-45)
_BISECT_NUM = 9
_BISECT_DEN = 16


@dataclass
class TrustOptions:
    """light/client.go TrustOptions: period + (height, hash) root of trust."""

    period: float  # seconds
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period <= 0:
            raise ValueError("trusting period must be greater than zero")
        if self.height <= 0:
            raise ValueError("trust option height must be greater than zero")
        if len(self.hash) != 32:
            raise ValueError(f"expected hash size to be 32 bytes, got {len(self.hash)}")


class ErrLightClientAttack(RuntimeError):
    """detector.go: divergence between primary and witness."""


class ErrNoWitnesses(RuntimeError):
    """light/errors.go ErrNoWitnesses."""


class ErrFailedHeaderCrossReferencing(RuntimeError):
    """light/errors.go: no witness could confirm the primary's header."""


def make_attack_evidence(conflicted: LightBlock, trusted: LightBlock, common: LightBlock):
    """detector.go:406-423 newLightClientAttackEvidence. The common height
    encodes the attack form: lunatic (forged state hashes) points at the
    last common header; equivocation/amnesia at the conflicting height."""
    from ..types.evidence import LightBlockData, LightClientAttackEvidence

    ev = LightClientAttackEvidence(
        conflicting_block=LightBlockData.from_parts(
            conflicted.signed_header, conflicted.validators
        ),
        common_height=0,
    )
    if ev.conflicting_header_is_invalid(trusted.signed_header.header):
        ev.common_height = common.height
        ev.timestamp = common.signed_header.header.time
        ev.total_voting_power = common.validators.total_voting_power()
    else:
        ev.common_height = trusted.height
        ev.timestamp = trusted.signed_header.header.time
        ev.total_voting_power = trusted.validators.total_voting_power()
    ev.byzantine_validators = ev.get_byzantine_validators(
        common.validators, trusted.signed_header
    )
    return ev


class Client:
    """light/client.go:130-1100."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: List[Provider],
        store: LightStore,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        max_clock_drift: float = DEFAULT_MAX_CLOCK_DRIFT,
        sequential: bool = False,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        now_fn: Optional[Callable[[], Timestamp]] = None,
    ):
        trust_options.validate()
        verifier.validate_trust_level(trust_level)
        # injected clock (ISSUE 11 satellite): simnet-driven light
        # clients read virtual time through here; the wall-clock default
        # lives in libs/timeutil, outside tmlint's deterministic scope
        self._now_ts = now_fn or _now_ts
        self._chain_id = chain_id
        self._trusting_period = trust_options.period
        self._trust_level = trust_level
        self._max_clock_drift = max_clock_drift
        self._primary = primary
        self._witnesses = list(witnesses)
        self._store = store
        self._sequential = sequential
        self._pruning_size = pruning_size
        self._initialize(trust_options)

    # -- bootstrap (client.go:370-404) -----------------------------------

    def _initialize(self, opts: TrustOptions) -> None:
        existing = self._store.latest_light_block()
        if existing is not None:
            return  # already bootstrapped (checkTrustedHeaderUsingOptions simplified)
        lb = self._primary.light_block(opts.height)
        if lb.hash() != opts.hash:
            raise ValueError(
                f"expected header's hash {opts.hash.hex()}, but got {lb.hash().hex()}"
            )
        lb.signed_header.validate_basic(self._chain_id)
        if lb.signed_header.header.validators_hash != lb.validators.hash():
            raise ValueError("expected header's validators to match those supplied")
        # verify the commit against its own validator set (1/1 trust at root)
        from ..types.validation import verify_commit_light

        verify_commit_light(
            self._chain_id,
            lb.validators,
            lb.signed_header.commit.block_id,
            lb.height,
            lb.signed_header.commit,
        )
        # cross-check BEFORE persisting: a failed construction must not
        # leave the store bootstrapped (a retry would skip this check)
        self._compare_first_header_with_witnesses(lb)
        self._store.save_light_block(lb)

    def _compare_first_header_with_witnesses(self, root: LightBlock) -> None:
        """client.go:1086 compareFirstHeaderWithWitnesses: every reachable
        witness must agree with the primary's root header. A witness that
        cannot serve the height (unreachable / missing block) is ignored —
        the reference keeps such witnesses too; one that serves a
        DIFFERENT header is a conflict the operator must resolve (raise).
        No witnesses at all is ErrNoWitnesses (light/errors.go): a client
        with nothing to cross-check against must not bootstrap silently."""
        if not self._witnesses:
            raise ErrNoWitnesses(
                "no witnesses configured; cannot cross-check the root header"
            )
        compared = 0
        for i, w in enumerate(self._witnesses):
            try:
                wlb = w.light_block(root.height)
            except (OSError, ValueError, KeyError, TimeoutError,
                    ConnectionError, RuntimeError, _http.HTTPException):
                continue  # unreachable / missing block: ignore this witness
            compared += 1
            if wlb.hash() != root.hash():
                # compareNewHeaderWithWitness: hash mismatch at the root is
                # errConflictingHeaders — the operator must pick a side
                raise ErrLightClientAttack(
                    f"witness {i} has a different header at the root height "
                    f"{root.height}: {wlb.hash().hex()} vs {root.hash().hex()}"
                )
        if compared == 0:
            raise ErrFailedHeaderCrossReferencing(
                f"none of the {len(self._witnesses)} configured witnesses "
                f"could serve the root header at height {root.height}"
            )

    # -- public API -------------------------------------------------------

    def verify_header(self, new_header, now: Optional[Timestamp] = None) -> None:
        """client.go:456 VerifyHeader: verify an externally obtained
        header — already-trusted headers must match byte-for-byte; fresh
        ones are fetched from the primary (with vals) and must hash-match
        before the normal verification path runs."""
        if new_header is None:
            raise ValueError("nil header")
        if new_header.height <= 0:
            raise ValueError("negative or zero height")
        existing = self._store.light_block(new_header.height)
        if existing is not None:
            if existing.hash() != new_header.hash():
                raise ValueError(
                    f"existing trusted header {existing.hash().hex()} does not "
                    f"match newHeader {new_header.hash().hex()}"
                )
            return
        # compare the primary's header BEFORE any verification/storage
        # (client.go:482): a mismatch must not pin the primary's fork into
        # the trusted store
        probe = self._light_block_from_primary(new_header.height)
        if probe.hash() != new_header.hash():
            raise ValueError(
                f"header from primary {probe.hash().hex()} does not match "
                f"newHeader {new_header.hash().hex()}"
            )
        # then verify through the normal dispatch (forward bisection or
        # the backwards hash-link walk for heights below trust) — a height
        # below the pruning window must never be stored unverified
        lb = self.verify_light_block_at_height(new_header.height, now)
        if lb.hash() != new_header.hash():
            raise ValueError(
                f"verified header {lb.hash().hex()} does not match "
                f"newHeader {new_header.hash().hex()}"
            )

    def last_trusted_height(self) -> int:
        """client.go:801 (-1 when empty)."""
        lb = self._store.latest_light_block()
        return lb.height if lb is not None else -1

    def first_trusted_height(self) -> int:
        """client.go:809 (-1 when empty)."""
        return self._store.first_light_block_height()

    def chain_id(self) -> str:
        return self._chain_id

    def primary(self) -> Provider:
        return self._primary

    def witnesses(self) -> List[Provider]:
        return list(self._witnesses)

    def add_provider(self, p: Provider) -> None:
        """client.go:841."""
        self._witnesses.append(p)

    def remove_witnesses(self, indexes: List[int]) -> None:
        """client.go:975: drop misbehaving witnesses (descending order so
        earlier removals do not shift later indexes)."""
        uniq = sorted(set(indexes), reverse=True)
        if any(i < 0 or i >= len(self._witnesses) for i in uniq):
            raise IndexError(f"witness index out of range: {indexes}")
        if len(self._witnesses) <= len(uniq):
            raise RuntimeError("cannot remove all witnesses")
        for i in uniq:
            self._witnesses.pop(i)

    def cleanup(self) -> None:
        """client.go:849: remove all stored light blocks."""
        self._store.prune(0)

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        if height == 0:
            return self._store.latest_light_block()
        return self._store.light_block(height)

    def update(self, now: Optional[Timestamp] = None) -> Optional[LightBlock]:
        """client.go Update: verify the primary's latest header."""
        latest = self._primary.light_block(0)
        trusted = self._store.latest_light_block()
        if trusted is not None and latest.height <= trusted.height:
            return None
        return self.verify_light_block_at_height(latest.height, now)

    def verify_light_block_at_height(
        self, height: int, now: Optional[Timestamp] = None
    ) -> LightBlock:
        """client.go:406-487."""
        if height <= 0:
            raise ValueError("height must be positive")
        now = now or self._now_ts()
        existing = self._store.light_block(height)
        if existing is not None:
            return existing
        latest_trusted = self._store.latest_light_block()
        if latest_trusted is None:
            raise RuntimeError("no trusted state — client not initialized")
        if height < latest_trusted.height:
            return self._backwards(latest_trusted, height, now)
        new_block = self._light_block_from_primary(height)
        self._verify_light_block(new_block, now)
        return new_block

    # -- verification strategies -----------------------------------------

    def _verify_light_block(self, new_block: LightBlock, now: Timestamp) -> None:
        closest = self._store.light_block_before(new_block.height) or \
            self._store.latest_light_block()
        if self._sequential:
            self._verify_sequential(closest, new_block, now)
        else:
            self._verify_skipping_against_witnesses(closest, new_block, now)
        self._store.save_light_block(new_block)
        self._store.prune(self._pruning_size)

    def _verify_sequential(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """client.go:546-637: fetch and verify every intermediate header."""
        current = trusted
        for h in range(trusted.height + 1, new_block.height + 1):
            if h == new_block.height:
                interim = new_block
            else:
                interim = self._light_block_from_primary(h)
            verifier.verify_adjacent(
                current.signed_header,
                interim.signed_header,
                interim.validators,
                self._trusting_period,
                now,
                self._max_clock_drift,
            )
            self._store.save_light_block(interim)
            current = interim

    def _verify_skipping(
        self, source: Provider, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> List[LightBlock]:
        """client.go:639-720 verifySkipping: bisection with 9/16 pivot."""
        blocks_to_verify = [new_block]
        depth = 0
        verified = [trusted]
        current = trusted
        while True:
            target = blocks_to_verify[depth]
            try:
                verifier.verify(
                    current.signed_header,
                    current.validators,
                    target.signed_header,
                    target.validators,
                    self._trusting_period,
                    now,
                    self._max_clock_drift,
                    self._trust_level,
                )
                verified.append(target)
                if depth == 0:
                    return verified
                current = target
                depth -= 1
            except verifier.ErrNotEnoughTrust:
                # bisect: pivot at 9/16 between current and target
                pivot = (
                    current.height
                    + (target.height - current.height) * _BISECT_NUM // _BISECT_DEN
                )
                if pivot <= current.height:
                    pivot = current.height + 1
                if pivot >= target.height:
                    raise
                interim = self._light_block_from(source, pivot)
                blocks_to_verify.append(interim)
                depth += 1

    def _verify_skipping_against_witnesses(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """client.go:722-780 + detector.go: verify against the primary,
        then cross-check the verified trace with every witness."""
        trace = self._verify_skipping(self._primary, trusted, new_block, now)
        self._detect_divergence(trace, now)

    # -- divergence detector (detector.go) --------------------------------

    def _detect_divergence(self, primary_trace: List[LightBlock], now: Timestamp) -> None:
        """detector.go:28-118 detectDivergence: compare the end of the
        verified trace with each witness; on a conflicting header, examine
        it against the trace, build LightClientAttackEvidence for both
        sides, submit, and halt. Witnesses that conflict but cannot sustain
        their own header are removed; if no witness matches, verification
        fails with ErrFailedHeaderCrossReferencing."""
        if not primary_trace or len(primary_trace) < 2:
            return  # nothing beyond the root of trust to cross-examine
        if not self._witnesses:
            raise ErrNoWitnesses("no witnesses connected. falling back to primary")
        last = primary_trace[-1]
        header_matched = False
        to_remove: List[int] = []
        for i, witness in enumerate(self._witnesses):
            try:
                w_block = witness.light_block(last.height)
            except (ErrLightBlockNotFound, ConnectionError):
                continue  # witness doesn't have it (yet) — tolerated
            if w_block.hash() != last.hash():
                # raises ErrLightClientAttack when the conflict is real;
                # returns normally when the witness can't sustain it
                self._handle_conflicting_headers(primary_trace, w_block, i, now)
                to_remove.append(i)
            else:
                header_matched = True
        for i in reversed(to_remove):
            del self._witnesses[i]
        if not header_matched:
            raise ErrFailedHeaderCrossReferencing(
                "all witnesses have either not responded, don't have the "
                "block or sent invalid blocks"
            )

    def _handle_conflicting_headers(
        self,
        primary_trace: List[LightBlock],
        challenging_block: LightBlock,
        witness_index: int,
        now: Timestamp,
    ) -> None:
        """detector.go:228-290 handleConflictingHeaders: hold the witness
        as source of truth -> evidence against the primary; then reverse
        roles -> evidence against the witness; always halt with
        ErrLightClientAttack."""
        witness = self._witnesses[witness_index]
        try:
            witness_trace, primary_block = self._examine_conflicting_header_against_trace(
                primary_trace, challenging_block, witness, now
            )
        except (ValueError, RuntimeError, ErrLightBlockNotFound, ConnectionError):
            # witness couldn't sustain its own header — not an attack proof
            return
        common, trusted_block = witness_trace[0], witness_trace[-1]
        ev_against_primary = make_attack_evidence(primary_block, trusted_block, common)
        self._send_evidence(ev_against_primary, witness)

        # Reverse: examine the witness's trace holding the primary as the
        # source of truth (best effort — we halt either way).
        try:
            primary_trace2, witness_block = self._examine_conflicting_header_against_trace(
                witness_trace, primary_block, self._primary, now
            )
            common2, trusted2 = primary_trace2[0], primary_trace2[-1]
            ev_against_witness = make_attack_evidence(witness_block, trusted2, common2)
            self._send_evidence(ev_against_witness, self._primary)
        except (ValueError, RuntimeError, ErrLightBlockNotFound, ConnectionError):
            pass
        raise ErrLightClientAttack(
            f"conflicting header at height {challenging_block.height}: "
            f"witness #{witness_index} {challenging_block.hash().hex()} vs "
            f"primary {primary_trace[-1].hash().hex()}"
        )

    def _examine_conflicting_header_against_trace(
        self,
        trace: List[LightBlock],
        target_block: LightBlock,
        source: Provider,
        now: Timestamp,
    ) -> tuple:
        """detector.go:289-374 examineConflictingHeaderAgainstTrace: walk
        the trace verifying the source's chain at each intermediate height
        until the bifurcation point. Returns (source_trace,
        divergent_trace_block)."""
        if target_block.height < trace[0].height:
            raise ValueError(
                f"target block height {target_block.height} below trusted "
                f"height {trace[0].height}"
            )
        previously_verified: Optional[LightBlock] = None
        source_trace: List[LightBlock] = []
        for idx, trace_block in enumerate(trace):
            # forward lunatic: the trace extends beyond the target
            if trace_block.height > target_block.height:
                tb_t = trace_block.signed_header.header.time
                tg_t = target_block.signed_header.header.time
                if (tb_t.seconds, tb_t.nanos) > (tg_t.seconds, tg_t.nanos):
                    raise RuntimeError(
                        "sanity: trace block after target must not be newer"
                    )
                if previously_verified.height != target_block.height:
                    source_trace = self._verify_skipping(
                        source, previously_verified, target_block, now
                    )
                return source_trace, trace_block
            if trace_block.height == target_block.height:
                source_block = target_block
            else:
                source_block = source.light_block(trace_block.height)
            if idx == 0:
                if source_block.hash() != trace_block.hash():
                    raise ValueError(
                        "trusted block differs from the source's first block"
                    )
                previously_verified = source_block
                continue
            source_trace = self._verify_skipping(
                source, previously_verified, source_block, now
            )
            if source_block.hash() != trace_block.hash():
                return source_trace, trace_block  # bifurcation point
            previously_verified = source_block
        raise RuntimeError("no divergence found along the trace")

    def _send_evidence(self, ev, receiver: Provider) -> None:
        """detector.go:220-226 sendEvidence (best effort)."""
        try:
            receiver.report_evidence(ev)
        except Exception:  # noqa: BLE001 — provider failure must not mask the halt
            pass

    def _backwards(
        self, trusted: LightBlock, height: int, now: Timestamp
    ) -> LightBlock:
        """client.go:878-933: hash-linked walk to an older header."""
        current = trusted
        for h in range(trusted.height - 1, height - 1, -1):
            interim = self._light_block_from_primary(h)
            verifier.verify_backwards(interim.signed_header, current.signed_header)
            self._store.save_light_block(interim)
            current = interim
        return current

    # -- provider plumbing (client.go:935-1035) ---------------------------

    def _light_block_from_primary(self, height: int) -> LightBlock:
        try:
            lb = self._primary.light_block(height)
        except (ErrLightBlockNotFound, ConnectionError):
            # primary failed: promote a witness (client.go findNewPrimary)
            for i, w in enumerate(self._witnesses):
                try:
                    lb = w.light_block(height)
                except (ErrLightBlockNotFound, ConnectionError):
                    continue
                self._witnesses.pop(i)
                self._witnesses.append(self._primary)
                self._primary = w
                return lb
            raise
        return lb

    def _light_block_from(self, source: Provider, height: int) -> LightBlock:
        if source is self._primary:
            return self._light_block_from_primary(height)
        return source.light_block(height)
