"""Light client — stateful header verification with bisection.

Reference parity: light/client.go — trust options bootstrap (:370),
VerifyLightBlockAtHeight (:406), sequential verification (:546), skipping
verification with the 9/16 bisection pivot (:639, :44-45), backwards
verification (:878), primary/witness management (:935-1035), and the
divergence detector (detector.go) comparing the primary's headers against
witnesses.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from typing import List, Optional

from ..types import Fraction
from ..wire.canonical import Timestamp
from . import verifier
from .provider import ErrLightBlockNotFound, LightBlock, Provider
from .store import LightStore

DEFAULT_PRUNING_SIZE = 1000
DEFAULT_MAX_CLOCK_DRIFT = 10.0  # seconds (light/client.go:56)

# bisection pivot: 9/16 (light/client.go:44-45)
_BISECT_NUM = 9
_BISECT_DEN = 16


@dataclass
class TrustOptions:
    """light/client.go TrustOptions: period + (height, hash) root of trust."""

    period: float  # seconds
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period <= 0:
            raise ValueError("trusting period must be greater than zero")
        if self.height <= 0:
            raise ValueError("trust option height must be greater than zero")
        if len(self.hash) != 32:
            raise ValueError(f"expected hash size to be 32 bytes, got {len(self.hash)}")


class ErrLightClientAttack(RuntimeError):
    """detector.go: divergence between primary and witness."""


def _now_ts() -> Timestamp:
    t = _time.time()
    return Timestamp(seconds=int(t), nanos=int((t % 1) * 1e9))


class Client:
    """light/client.go:130-1100."""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: List[Provider],
        store: LightStore,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        max_clock_drift: float = DEFAULT_MAX_CLOCK_DRIFT,
        sequential: bool = False,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
    ):
        trust_options.validate()
        verifier.validate_trust_level(trust_level)
        self._chain_id = chain_id
        self._trusting_period = trust_options.period
        self._trust_level = trust_level
        self._max_clock_drift = max_clock_drift
        self._primary = primary
        self._witnesses = list(witnesses)
        self._store = store
        self._sequential = sequential
        self._pruning_size = pruning_size
        self._initialize(trust_options)

    # -- bootstrap (client.go:370-404) -----------------------------------

    def _initialize(self, opts: TrustOptions) -> None:
        existing = self._store.latest_light_block()
        if existing is not None:
            return  # already bootstrapped (checkTrustedHeaderUsingOptions simplified)
        lb = self._primary.light_block(opts.height)
        if lb.hash() != opts.hash:
            raise ValueError(
                f"expected header's hash {opts.hash.hex()}, but got {lb.hash().hex()}"
            )
        lb.signed_header.validate_basic(self._chain_id)
        if lb.signed_header.header.validators_hash != lb.validators.hash():
            raise ValueError("expected header's validators to match those supplied")
        # verify the commit against its own validator set (1/1 trust at root)
        from ..types.validation import verify_commit_light

        verify_commit_light(
            self._chain_id,
            lb.validators,
            lb.signed_header.commit.block_id,
            lb.height,
            lb.signed_header.commit,
        )
        self._store.save_light_block(lb)

    # -- public API -------------------------------------------------------

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        if height == 0:
            return self._store.latest_light_block()
        return self._store.light_block(height)

    def update(self, now: Optional[Timestamp] = None) -> Optional[LightBlock]:
        """client.go Update: verify the primary's latest header."""
        latest = self._primary.light_block(0)
        trusted = self._store.latest_light_block()
        if trusted is not None and latest.height <= trusted.height:
            return None
        return self.verify_light_block_at_height(latest.height, now)

    def verify_light_block_at_height(
        self, height: int, now: Optional[Timestamp] = None
    ) -> LightBlock:
        """client.go:406-487."""
        if height <= 0:
            raise ValueError("height must be positive")
        now = now or _now_ts()
        existing = self._store.light_block(height)
        if existing is not None:
            return existing
        latest_trusted = self._store.latest_light_block()
        if latest_trusted is None:
            raise RuntimeError("no trusted state — client not initialized")
        if height < latest_trusted.height:
            return self._backwards(latest_trusted, height, now)
        new_block = self._light_block_from_primary(height)
        self._verify_light_block(new_block, now)
        return new_block

    # -- verification strategies -----------------------------------------

    def _verify_light_block(self, new_block: LightBlock, now: Timestamp) -> None:
        closest = self._store.light_block_before(new_block.height) or \
            self._store.latest_light_block()
        if self._sequential:
            self._verify_sequential(closest, new_block, now)
        else:
            self._verify_skipping_against_witnesses(closest, new_block, now)
        self._store.save_light_block(new_block)
        self._store.prune(self._pruning_size)

    def _verify_sequential(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """client.go:546-637: fetch and verify every intermediate header."""
        current = trusted
        for h in range(trusted.height + 1, new_block.height + 1):
            if h == new_block.height:
                interim = new_block
            else:
                interim = self._light_block_from_primary(h)
            verifier.verify_adjacent(
                current.signed_header,
                interim.signed_header,
                interim.validators,
                self._trusting_period,
                now,
                self._max_clock_drift,
            )
            self._store.save_light_block(interim)
            current = interim

    def _verify_skipping(
        self, source: Provider, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> List[LightBlock]:
        """client.go:639-720 verifySkipping: bisection with 9/16 pivot."""
        blocks_to_verify = [new_block]
        depth = 0
        verified = [trusted]
        current = trusted
        while True:
            target = blocks_to_verify[depth]
            try:
                verifier.verify(
                    current.signed_header,
                    current.validators,
                    target.signed_header,
                    target.validators,
                    self._trusting_period,
                    now,
                    self._max_clock_drift,
                    self._trust_level,
                )
                verified.append(target)
                if depth == 0:
                    return verified
                current = target
                depth -= 1
            except verifier.ErrNotEnoughTrust:
                # bisect: pivot at 9/16 between current and target
                pivot = (
                    current.height
                    + (target.height - current.height) * _BISECT_NUM // _BISECT_DEN
                )
                if pivot <= current.height:
                    pivot = current.height + 1
                if pivot >= target.height:
                    raise
                interim = self._light_block_from(source, pivot)
                blocks_to_verify.append(interim)
                depth += 1

    def _verify_skipping_against_witnesses(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> None:
        """client.go:722-780 + detector.go: verify against the primary,
        then cross-check the final header with every witness."""
        self._verify_skipping(self._primary, trusted, new_block, now)
        self._detect_divergence(new_block, now)

    def _detect_divergence(self, new_block: LightBlock, now: Timestamp) -> None:
        """detector.go:40-120 (comparison phase; evidence construction is
        handled by the evidence pool when running in a full node)."""
        for i, witness in enumerate(self._witnesses):
            try:
                w_block = witness.light_block(new_block.height)
            except (ErrLightBlockNotFound, ConnectionError):
                continue  # witness doesn't have it (yet) — tolerated
            if w_block.hash() != new_block.hash():
                raise ErrLightClientAttack(
                    f"witness #{i} has a different header "
                    f"{w_block.hash().hex()} != {new_block.hash().hex()} "
                    f"at height {new_block.height}"
                )

    def _backwards(
        self, trusted: LightBlock, height: int, now: Timestamp
    ) -> LightBlock:
        """client.go:878-933: hash-linked walk to an older header."""
        current = trusted
        for h in range(trusted.height - 1, height - 1, -1):
            interim = self._light_block_from_primary(h)
            verifier.verify_backwards(interim.signed_header, current.signed_header)
            self._store.save_light_block(interim)
            current = interim
        return current

    # -- provider plumbing (client.go:935-1035) ---------------------------

    def _light_block_from_primary(self, height: int) -> LightBlock:
        try:
            lb = self._primary.light_block(height)
        except (ErrLightBlockNotFound, ConnectionError):
            # primary failed: promote a witness (client.go findNewPrimary)
            for i, w in enumerate(self._witnesses):
                try:
                    lb = w.light_block(height)
                except (ErrLightBlockNotFound, ConnectionError):
                    continue
                self._witnesses.pop(i)
                self._witnesses.append(self._primary)
                self._primary = w
                return lb
            raise
        return lb

    def _light_block_from(self, source: Provider, height: int) -> LightBlock:
        if source is self._primary:
            return self._light_block_from_primary(height)
        return source.light_block(height)
