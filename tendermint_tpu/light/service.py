"""Light-client verification as a service (ISSUE 11 tentpole).

The first multi-request serving surface in the repo: many clients'
(trusted-header, target-header) requests ride ONE shared device
pipeline. Per request, the non-sig checks (trust level, expiry, hash
chaining, clock drift) run host-side through the light/verifier.py
prepare seam — bit-identical to the sequential path — and the sig-check
work is emitted as EntryBlocks (epoch_key/val_idx attached) into the
shared AsyncBatchVerifier, where requests across clients group by valset
epoch and cross-request coalesce into device batches (mesh lanes when
TM_TPU_MESH is on). Verdicts stream back per request as device batches
resolve, in COMPLETION order.

Why this turns ~1.2k headers/s into a serving workload ("Practical Light
Clients for Committee-Based Blockchains", arxiv 2410.03347; "A
Tendermint Light Client", arxiv 2010.07031): clients within one trust
period re-verify the SAME validator sets — exactly the shape the PR-5
epoch cache amortizes (tables device-resident once per epoch) and the
PR-9 mesh dispatcher bin-packs (many small same-epoch jobs → lanes of
one superbatch). On top of the device-side amortization the service
adds request-level amortization: byte-identical in-flight requests
single-flight onto one verification, and resolved verdicts memoize in a
bounded LRU (the PR-6 _SigMemo idiom lifted to the request level — keyed
on the FULL input fingerprint including the resolved `now`, so a forged
commit or a different clock can never alias a clean verdict).

Flow instrumentation (ISSUE 10 machinery): every unique verification
carries one flow id — `light.rpc_arrival` (s) → `light.prepare` →
`light.epoch_group` per stage → `pipeline.submit`/`pipeline.dispatch`
(and `pipeline.mesh_pack` when mesh lanes are on) → `light.verdict` (f)
— so one Perfetto chain spans RPC arrival to verdict delivery.

Since ISSUE 17 stage submission rides the `light` lane of the shared
ingress fabric (ops/ingress.py) — a whole-block passthrough at
CONSENSUS priority with per-lane labeled metrics; the single-flight,
memo, and plan machinery here IS the lane's host stage.

Knobs: TM_TPU_INGRESS_LIGHT_INFLIGHT (max unresolved unique
verifications, 256) and TM_TPU_INGRESS_LIGHT_MEMO (verdict memo
entries, 4096; 0 disables); legacy TM_TPU_LIGHT_INFLIGHT /
TM_TPU_LIGHT_MEMO still honored with a DeprecationWarning.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
from collections import OrderedDict
from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..libs.timeutil import now_ts as _now_ts
from ..observability import trace as _trace
from ..wire.canonical import Timestamp
from . import batch as _lb

DEFAULT_MAX_INFLIGHT = 256
DEFAULT_MEMO_SIZE = 4096


class VerdictBatch:
    """Streaming handle for one submit_many(): verdicts arrive in
    COMPLETION order, each `{"index", "height", "ok", "error",
    "error_type"}` with `index` the request's position in the submitted
    list. Iterate for the stream; results() collects and re-orders by
    index."""

    def __init__(self, n: int):
        self._n = n
        self._q: "_queue.Queue[dict]" = _queue.Queue()

    def __len__(self) -> int:
        return self._n

    def _push(self, verdict: dict) -> None:
        self._q.put(verdict)

    def stream(self, timeout: Optional[float] = None) -> Iterator[dict]:
        """Yield verdicts as they complete. `timeout` is an overall
        DEADLINE for the whole batch (not per verdict); expiry raises
        TimeoutError naming how many verdicts are still pending."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for i in range(self._n):
            wait = None
            if deadline is not None:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    wait = 0.0
            try:
                yield self._q.get(timeout=wait)
            except _queue.Empty:
                raise TimeoutError(
                    f"timed out with {self._n - i} of {self._n} light "
                    f"verdicts still pending"
                ) from None

    def __iter__(self) -> Iterator[dict]:
        return self.stream()

    def results(self, timeout: Optional[float] = None) -> List[dict]:
        return sorted(self.stream(timeout=timeout), key=lambda v: v["index"])


class _Pending:
    """One unique in-flight verification and the requests attached to
    it (single-flight). `infra` marks a pipeline-infrastructure failure
    (submit refused, dispatch died) as opposed to a parity verdict — the
    memo must never cache those."""

    __slots__ = ("fp", "height", "waiters", "futs", "acquired", "infra")

    def __init__(self, fp: Optional[tuple], height: int):
        self.fp = fp
        self.height = height
        self.waiters: List[tuple] = []  # (index, VerdictBatch)
        self.futs: List = []
        self.acquired = False
        self.infra = False


class LightVerifyService:
    """Batched light-client verification over the shared device
    pipeline. Thread-safe; submit_many() may be called from any thread
    (the RPC server's handler threads included) and blocks only on the
    in-flight bound."""

    def __init__(self, verifier=None, now_fn=None,
                 max_inflight: Optional[int] = None,
                 memo_size: Optional[int] = None):
        from ..ops import ingress as _fabric

        if verifier is None:
            from ..ops import pipeline as _pl

            verifier = _pl.shared_verifier()
        self._v = verifier
        # the `light` lane: whole-block passthrough on the shared fabric
        # (stepped — the service has no windows; stages submit directly)
        self._lane = _fabric.shared_engine().register(_fabric.LaneSpec(
            name="light",
            priority=_fabric.PRIORITY_CONSENSUS,
            stepped=True,
            closed_msg="light verify service is closed",
            verifier=verifier,
        ))
        # injected clock (the light/ determinism contract): simnet
        # drives a virtual clock through here; wall clock is the default
        self._now_fn = now_fn or _now_ts
        if max_inflight is None:
            v = _fabric.env_setting("TM_TPU_INGRESS_LIGHT_INFLIGHT",
                                    "TM_TPU_LIGHT_INFLIGHT")
            max_inflight = int(v) if v is not None else DEFAULT_MAX_INFLIGHT
        if memo_size is None:
            v = _fabric.env_setting("TM_TPU_INGRESS_LIGHT_MEMO",
                                    "TM_TPU_LIGHT_MEMO")
            memo_size = int(v) if v is not None else DEFAULT_MEMO_SIZE
        self._sem = threading.Semaphore(max(int(max_inflight), 1))
        self._memo_cap = max(int(memo_size), 0)
        self._memo: "OrderedDict[tuple, dict]" = OrderedDict()
        self._mtx = threading.Lock()
        self._inflight: dict = {}  # fingerprint -> _Pending
        self._closed = False
        self._stats = {
            "requests": 0,
            "memo_hits": 0,
            "inflight_joins": 0,
            "unique": 0,
            "rejected": 0,
        }

    # -- submission ------------------------------------------------------

    def submit(self, req: _lb.HeaderRequest,
               now: Optional[Timestamp] = None) -> dict:
        """One request, blocking: returns its verdict dict."""
        return next(iter(self.submit_many([req], now=now).stream()))

    def submit_many(self, requests: Sequence[_lb.HeaderRequest],
                    now: Optional[Timestamp] = None) -> VerdictBatch:
        """Submit a batch; returns the VerdictBatch stream immediately.
        `now` (or one service-clock reading, resolved ONCE per call like
        the reference resolves once per Verify) applies to every request
        that did not pin its own."""
        reqs = list(requests)
        out = VerdictBatch(len(reqs))
        if not reqs:
            return out
        if self._closed:
            raise RuntimeError("light verify service is closed")
        batch_now = now or self._resolved_now()
        for i, req in enumerate(reqs):
            self._submit_one(req, i, out, batch_now)
        return out

    def _resolved_now(self) -> Timestamp:
        """One service-clock reading per submit_many, truncated to WHOLE
        seconds: the fingerprint includes `now` (expiry/drift depend on
        it), so a nanosecond-resolution clock would make identical
        requests from different RPC calls never share a memo slot —
        request-level amortization would exist only for clients pinning
        an explicit `now`. Truncation is applied to the now used for
        VERIFICATION too, so memo key and verdict always agree; sub-
        second clock coarseness is immaterial against trusting periods
        and matches the reference's once-per-Verify clock read. Callers
        that pin `now` (or per-request req.now) get it verbatim."""
        ts = self._now_fn()
        return ts if ts.nanos == 0 else Timestamp(seconds=ts.seconds, nanos=0)

    def _submit_one(self, req, index: int, out: VerdictBatch,
                    batch_now: Timestamp) -> None:
        rnow = req.now or batch_now
        try:
            fp = _lb.fingerprint(req, rnow)
        except Exception as e:  # noqa: BLE001 — unhashable garbage request
            out._push({
                "index": index, "height": "0", "ok": False,
                "error": f"malformed request: {e}",
                "error_type": type(e).__name__,
            })
            return
        with self._mtx:
            self._stats["requests"] += 1
            # fp is None for non-fingerprintable requests (incomplete
            # headers hash to b"" and would alias): no memo, no
            # single-flight — each verifies uniquely
            hit = self._memo.get(fp) if fp is not None else None
            if hit is not None:
                self._memo.move_to_end(fp)
                self._stats["memo_hits"] += 1
                out._push(dict(hit, index=index))
                return
            pend = self._inflight.get(fp) if fp is not None else None
            if pend is not None:
                # single-flight: identical request already verifying —
                # attach and share its verdict
                self._stats["inflight_joins"] += 1
                pend.waiters.append((index, out))
                return
            pend = _Pending(fp, req.untrusted_header.header.height)
            pend.waiters.append((index, out))
            if fp is not None:
                self._inflight[fp] = pend
        self._verify_unique(req, rnow, pend)

    # -- the unique-verification path ------------------------------------

    def _verify_unique(self, req, rnow: Timestamp, pend: _Pending) -> None:
        tr = _trace.TRACER
        fid = _trace.next_flow() if tr.enabled else None
        if fid is not None:
            tr.flow_point("light.rpc_arrival", fid, "s", height=pend.height)
        with _trace.span("light.prepare", height=pend.height):
            plan = _lb.prepare_request(req, rnow)
        entry_stages = plan.entry_stages()
        if fid is not None:
            for st in entry_stages:
                ek = getattr(st.entries, "epoch_key", None)
                tr.flow_point(
                    "light.epoch_group", fid, "t", kind=st.kind,
                    epoch=ek.hex()[:16] if ek else "uncached",
                    n=len(st.entries),
                )
        if not entry_stages:
            self._finish(pend, plan, [], fid)
            return
        # bound unresolved unique verifications (device memory + futures)
        self._sem.acquire()
        pend.acquired = True
        try:
            futs = [
                self._lane.submit_block(st.entries, flow=fid)
                for st in entry_stages
            ]
        except Exception as e:  # noqa: BLE001 — closed/overloaded verifier
            pend.infra = True  # transient: a retry may succeed — no memo
            for st in entry_stages:
                st.entries, st.error = None, e
            self._finish(pend, plan, [], fid)
            return
        pend.futs = futs
        remaining = [len(futs)]
        done_mtx = threading.Lock()

        def _on_done(_f) -> None:
            with done_mtx:
                remaining[0] -= 1
                if remaining[0]:
                    return
            verdicts: List[object] = []
            for f in futs:
                try:
                    # futures resolve to host-owned rows (the PR-7
                    # owndata contract); copy anyway before fanning one
                    # row out to many waiters' conclude closures
                    verdicts.append(np.array(f.result(), dtype=bool))
                except Exception as e:  # noqa: BLE001
                    verdicts.append(e)
            self._finish(pend, plan, verdicts, fid)

        for f in futs:
            f.add_done_callback(_on_done)

    def _finish(self, pend: _Pending, plan, verdicts, fid) -> None:
        err = _lb.conclude_request(plan, verdicts)
        # provenance, not name-matching: an error that IS one of the
        # pipeline futures' exceptions (DispatchError, a raw resolver
        # failure, ...) is infrastructure — a retry may succeed, so it
        # must never be served from the memo. Parity errors come from
        # the prepare/conclude path and are deterministic.
        infra = pend.infra or any(
            isinstance(v, BaseException) and v is err for v in verdicts
        )
        verdict = {
            "height": str(pend.height),
            "ok": err is None,
            "error": None if err is None else str(err),
            "error_type": None if err is None else type(err).__name__,
        }
        if fid is not None and _trace.TRACER.enabled:
            _trace.TRACER.flow_point(
                "light.verdict", fid, "f", ok=int(err is None)
            )
        with self._mtx:
            if pend.fp is not None:
                self._inflight.pop(pend.fp, None)
            self._stats["unique"] += 1
            if err is not None:
                self._stats["rejected"] += 1
            # memoize verdicts AND parity rejections — but never an
            # infrastructure failure or a non-fingerprintable request
            if self._memo_cap and pend.fp is not None and not infra:
                self._memo[pend.fp] = verdict
                while len(self._memo) > self._memo_cap:
                    self._memo.popitem(last=False)
            waiters, pend.waiters = pend.waiters, []
        if pend.acquired:
            self._sem.release()
        for index, out in waiters:
            out._push(dict(verdict, index=index))

    # -- introspection / lifecycle ---------------------------------------

    def stats(self) -> dict:
        with self._mtx:
            s = dict(self._stats)
            s["memo_entries"] = len(self._memo)
            s["inflight"] = len(self._inflight)
        return s

    def close(self) -> None:
        """Retire the service. The underlying verifier is SHARED (the
        node's consensus path uses it too) and is not closed here; the
        fabric lane unregisters so /status stops counting it."""
        self._closed = True
        self._lane.close(timeout=0.0)


# ---------------------------------------------------------------------------
# JSON wire forms (the /light_verify RPC endpoint; shapes mirror the
# existing /commit + /validators result conventions so a provider can
# round-trip its fetched blocks straight into a request)
# ---------------------------------------------------------------------------


def request_from_json(d: dict) -> _lb.HeaderRequest:
    """Parse one /light_verify request object. Headers/valsets use the
    same JSON shapes /commit and /validators serve (parsed by
    wire.json_types); trust parameters are plain numbers."""
    from ..types import Fraction
    from ..wire.json_types import (
        parse_signed_header,
        parse_time,
        parse_validator_set,
    )

    tl = d.get("trust_level") or {}
    now = d.get("now")
    return _lb.HeaderRequest(
        trusted_header=parse_signed_header(d["trusted_header"]),
        trusted_vals=parse_validator_set(d["trusted_validators"]),
        untrusted_header=parse_signed_header(d["untrusted_header"]),
        untrusted_vals=parse_validator_set(d["untrusted_validators"]),
        trusting_period=float(d["trusting_period"]),
        max_clock_drift=float(
            d.get("max_clock_drift", _lb.DEFAULT_MAX_CLOCK_DRIFT)
        ),
        trust_level=Fraction(
            int(tl.get("numerator", 1)), int(tl.get("denominator", 3))
        ),
        now=parse_time(now) if now else None,
    )


def request_to_json(req: _lb.HeaderRequest) -> dict:
    """Serialize a HeaderRequest for the /light_verify endpoint."""
    from ..wire.json_types import (
        signed_header_to_json,
        time_to_json,
        validator_set_to_json,
    )

    out = {
        "trusted_header": signed_header_to_json(req.trusted_header),
        "trusted_validators": validator_set_to_json(req.trusted_vals),
        "untrusted_header": signed_header_to_json(req.untrusted_header),
        "untrusted_validators": validator_set_to_json(req.untrusted_vals),
        "trusting_period": req.trusting_period,
        "max_clock_drift": req.max_clock_drift,
        "trust_level": {
            "numerator": req.trust_level.numerator,
            "denominator": req.trust_level.denominator,
        },
    }
    if req.now is not None:
        out["now"] = time_to_json(req.now)
    return out
