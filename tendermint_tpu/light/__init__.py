"""tendermint_tpu.light — light client (reference light/, L12)."""

from .verifier import (  # noqa: F401
    DEFAULT_TRUST_LEVEL,
    ErrInvalidHeader,
    ErrNotEnoughTrust,
    ErrOldHeaderExpired,
    header_expired,
    prepare_adjacent,
    prepare_non_adjacent,
    prepare_verify,
    validate_trust_level,
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)
from .batch import HeaderRequest  # noqa: F401
from .client import Client, LightBlock, TrustOptions  # noqa: F401
from .provider import Provider, NodeBackedProvider  # noqa: F401
from .service import LightVerifyService  # noqa: F401
from .store import LightStore  # noqa: F401
