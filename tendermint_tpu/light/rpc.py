"""Proof-verifying RPC client + light proxy.

Reference parity: light/rpc/client.go — an RPC client that cross-checks
every response against light-client-verified headers: blocks by header
hash, commits by verification, txs by merkle proof against the verified
data hash, validators against the verified validators hash; and
light/proxy/proxy.go — the RPC server exposing the verified surface.
"""

from __future__ import annotations

import base64
from typing import List, Optional

from ..crypto import merkle
from ..rpc.core import RPCError
from ..types.tx import tx_hash
from .client import Client
from .provider import LightBlock


class VerificationFailed(RuntimeError):
    pass


class VerifyingClient:
    """light/rpc/client.go Client."""

    def __init__(self, rpc, light_client: Client):
        self._rpc = rpc  # an HTTPClient-like transport to the full node
        self._lc = light_client

    # -- verified reads --------------------------------------------------

    def _trusted(self, height: int) -> LightBlock:
        return self._lc.verify_light_block_at_height(height)

    def block(self, height: int) -> dict:
        res = self._rpc.block(height)
        lb = self._trusted(height)
        got = bytes.fromhex(res["block_id"]["hash"])
        if got != lb.hash():
            raise VerificationFailed(
                f"block at {height}: hash {got.hex()} != verified {lb.hash().hex()}"
            )
        return res

    def commit(self, height: int) -> dict:
        res = self._rpc.commit(height)
        lb = self._trusted(height)
        hdr_height = int(res["signed_header"]["header"]["height"])
        if hdr_height != height:
            raise VerificationFailed("commit height mismatch")
        want = lb.signed_header.header.validators_hash.hex().upper()
        if res["signed_header"]["header"]["validators_hash"] != want:
            raise VerificationFailed("commit validators hash mismatch")
        return res

    def validators(self, height: int) -> dict:
        res = self._rpc.validators(height)
        lb = self._trusted(height)
        # reconstruct the validator-set hash from the response
        from ..crypto import ed25519
        from ..types import Validator, ValidatorSet

        vals = []
        for v in res["validators"]:
            pk = ed25519.PubKey(base64.b64decode(v["pub_key"]["value"]))
            vals.append(Validator.new(pk, int(v["voting_power"])))
        got = ValidatorSet(validators=vals).hash()
        if got != lb.signed_header.header.validators_hash:
            raise VerificationFailed("validator set does not match verified header")
        return res

    def tx(self, tx_hash_bytes: bytes) -> dict:
        res = self._rpc.tx(tx_hash_bytes, prove=True)
        height = int(res["height"])
        lb = self._trusted(height)
        proof = res.get("proof")
        if proof is None:
            raise VerificationFailed("node did not return a tx proof")
        p = merkle.Proof(
            total=int(proof["proof"]["total"]),
            index=int(proof["proof"]["index"]),
            leaf_hash_=base64.b64decode(proof["proof"]["leaf_hash"]),
            aunts=[base64.b64decode(a) for a in proof["proof"]["aunts"]],
        )
        data = base64.b64decode(proof["data"])
        root = bytes.fromhex(proof["root_hash"])
        if root != lb.signed_header.header.data_hash:
            raise VerificationFailed("tx proof root does not match verified data hash")
        try:
            p.verify(root, data)
        except ValueError as e:
            raise VerificationFailed(f"tx proof invalid: {e}") from e
        if tx_hash(data) != tx_hash_bytes:
            raise VerificationFailed("tx bytes do not match requested hash")
        return res

    # -- pass-throughs (unverifiable surface) -----------------------------

    def status(self) -> dict:
        return self._rpc.status()

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        return self._rpc.broadcast_tx_sync(tx)

    def abci_query(self, path: str, data: bytes, height: int = 0, prove: bool = True) -> dict:
        """abci_query returns app-level proofs (crypto.ProofOps); apps that
        don't produce proofs (like the kvstore example) can't be verified —
        surfaced to the caller rather than silently trusted."""
        res = self._rpc.abci_query(path, data, height=height, prove=prove)
        res["verified"] = False
        return res


class LightProxy:
    """light/proxy/proxy.go: an RPC server exposing the verifying client."""

    def __init__(self, verifying_client: VerifyingClient, laddr: str):
        from ..rpc.server import RPCServer

        class _Env:
            def __init__(self, vc):
                self._vc = vc

            def status(self):
                return self._vc.status()

            def block(self, height=None):
                return self._vc.block(int(height))

            def commit(self, height=None):
                return self._vc.commit(int(height))

            def validators(self, height=None):
                return self._vc.validators(int(height))

            def tx(self, hash="", prove=True):  # noqa: A002
                return self._vc.tx(bytes.fromhex(hash))

            def broadcast_tx_sync(self, tx=""):
                return self._vc.broadcast_tx_sync(base64.b64decode(tx))

            def abci_query(self, path="", data="", height=0, prove=True):
                return self._vc.abci_query(path, bytes.fromhex(data), int(height))

        self._server = RPCServer(laddr, _Env(verifying_client))

    @property
    def listen_addr(self) -> str:
        return self._server.listen_addr

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()
