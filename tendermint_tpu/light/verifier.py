"""Light-client header verification — hot path #2.

Reference parity: light/verifier.go — VerifyAdjacent (:103),
VerifyNonAdjacent (:33), Verify (:152), VerifyBackwards (:201). The
commit checks route through types.validation (VerifyCommitLight /
VerifyCommitLightTrusting), i.e. through the device batch engine — the
pipelined 1k-header sync workload of BASELINE config #5.
"""

from __future__ import annotations

from ..types import ErrNotEnoughVotingPowerSigned, Fraction, SignedHeader, ValidatorSet
from ..types.validation import (
    verify_commit_light,
    verify_commit_light_trusting,
)
from ..wire.canonical import Timestamp

# light.DefaultTrustLevel (light/verifier.go:20)
DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class ErrNotEnoughTrust(ValueError):
    """verifier.go ErrNewValSetCantBeTrusted."""


class ErrInvalidHeader(ValueError):
    pass


class ErrOldHeaderExpired(ValueError):
    pass


def _ts_add(ts: Timestamp, seconds: float) -> Timestamp:
    total_ns = ts.seconds * 10**9 + ts.nanos + int(seconds * 1e9)
    return Timestamp(seconds=total_ns // 10**9, nanos=total_ns % 10**9)


def _ts_before(a: Timestamp, b: Timestamp) -> bool:
    return (a.seconds, a.nanos) < (b.seconds, b.nanos)


def header_expired(h: SignedHeader, trusting_period: float, now: Timestamp) -> bool:
    """verifier.go HeaderExpired: expiration = header.Time + trustingPeriod."""
    expiration = _ts_add(h.header.time, trusting_period)
    return not _ts_before(now, expiration)


def validate_trust_level(lvl: Fraction) -> None:
    """verifier.go ValidateTrustLevel: must be in [1/3, 1]."""
    if (
        lvl.numerator * 3 < lvl.denominator
        or lvl.numerator > lvl.denominator
        or lvl.denominator == 0
    ):
        raise ValueError(f"trustLevel must be within [1/3, 1], given {lvl}")


def verify_new_header_and_vals(
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusted_header: SignedHeader,
    now: Timestamp,
    max_clock_drift: float,
) -> None:
    """verifier.go:236-283 verifyNewHeaderAndVals."""
    chain_id = trusted_header.header.chain_id
    try:
        untrusted_header.validate_basic(chain_id)
    except ValueError as e:
        raise ErrInvalidHeader(f"untrustedHeader.ValidateBasic failed: {e}") from e
    if untrusted_header.header.height <= trusted_header.header.height:
        raise ErrInvalidHeader(
            f"expected new header height {untrusted_header.header.height} to be greater "
            f"than one of old header {trusted_header.header.height}"
        )
    if not _ts_before(trusted_header.header.time, untrusted_header.header.time):
        raise ErrInvalidHeader("expected new header time to be after old header time")
    if not _ts_before(untrusted_header.header.time, _ts_add(now, max_clock_drift)):
        raise ErrInvalidHeader(
            "new header has a time from the future (max clock drift exceeded)"
        )
    if untrusted_header.header.validators_hash != untrusted_vals.hash():
        raise ErrInvalidHeader(
            f"expected new header validators ({untrusted_header.header.validators_hash.hex()}) "
            f"to match those supplied ({untrusted_vals.hash().hex()})"
        )


def verify_adjacent(
    trusted_header: SignedHeader,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
) -> None:
    """verifier.go:103-150."""
    if untrusted_header.header.height != trusted_header.header.height + 1:
        raise ValueError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period, now):
        raise ErrOldHeaderExpired(f"old header has expired at {now}")
    verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift
    )
    # valhash continuity (verifier.go:134-142)
    if untrusted_header.header.validators_hash != trusted_header.header.next_validators_hash:
        raise ErrInvalidHeader(
            f"expected old header next validators ({trusted_header.header.next_validators_hash.hex()}) "
            f"to match those from new header ({untrusted_header.header.validators_hash.hex()})"
        )
    # full commit verification on the device engine (verifier.go:143-148);
    # any commit defect surfaces as ErrInvalidHeader
    try:
        verify_commit_light(
            trusted_header.header.chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.header.height,
            untrusted_header.commit,
        )
    except ErrInvalidHeader:
        raise
    except ValueError as e:
        raise ErrInvalidHeader(str(e)) from e


def verify_non_adjacent(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction,
) -> None:
    """verifier.go:33-101."""
    if untrusted_header.header.height == trusted_header.header.height + 1:
        raise ValueError("headers must be non adjacent in height")
    validate_trust_level(trust_level)
    if header_expired(trusted_header, trusting_period, now):
        raise ErrOldHeaderExpired(f"old header has expired at {now}")
    verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift
    )
    # trust-level check against the OLD validator set (verifier.go:67-80):
    # only insufficient tallied power is a (retryable) trust failure —
    # any other commit defect is an invalid header.
    try:
        verify_commit_light_trusting(
            trusted_header.header.chain_id,
            trusted_vals,
            untrusted_header.commit,
            trust_level,
        )
    except ErrNotEnoughVotingPowerSigned as e:
        raise ErrNotEnoughTrust(str(e)) from e
    except ValueError as e:
        raise ErrInvalidHeader(str(e)) from e
    # then the full +2/3 of the NEW set (verifier.go:82-88)
    try:
        verify_commit_light(
            trusted_header.header.chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.header.height,
            untrusted_header.commit,
        )
    except ErrInvalidHeader:
        raise
    except ValueError as e:
        raise ErrInvalidHeader(str(e)) from e


def verify(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction,
) -> None:
    """verifier.go:152-176 Verify: dispatch adjacent/non-adjacent."""
    if untrusted_header.header.height != trusted_header.header.height + 1:
        verify_non_adjacent(
            trusted_header, trusted_vals, untrusted_header, untrusted_vals,
            trusting_period, now, max_clock_drift, trust_level,
        )
    else:
        verify_adjacent(
            trusted_header, untrusted_header, untrusted_vals,
            trusting_period, now, max_clock_drift,
        )


def verify_backwards(untrusted_header, trusted_header) -> None:
    """verifier.go:201-234: walk back by hash linkage."""
    if header_expired(trusted_header, 0, trusted_header.header.time):
        pass  # expiry handled by caller in backwards mode
    if untrusted_header.header.chain_id != trusted_header.header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if not _ts_before(untrusted_header.header.time, trusted_header.header.time):
        raise ErrInvalidHeader(
            "expected older header time to be before newer header time"
        )
    if trusted_header.header.last_block_id.hash != untrusted_header.header.hash():
        raise ErrInvalidHeader(
            f"older header hash {untrusted_header.header.hash().hex()} does not match "
            f"trusted header's last block {trusted_header.header.last_block_id.hash.hex()}"
        )
