"""Light-client header verification — hot path #2.

Reference parity: light/verifier.go — VerifyAdjacent (:103),
VerifyNonAdjacent (:33), Verify (:152), VerifyBackwards (:201). The
commit checks route through types.validation (VerifyCommitLight /
VerifyCommitLightTrusting), i.e. through the device batch engine — the
pipelined 1k-header sync workload of BASELINE config #5.
"""

from __future__ import annotations

from typing import Callable, List

from ..types import ErrNotEnoughVotingPowerSigned, Fraction, SignedHeader, ValidatorSet
from ..types import validation as _validation
from ..types.validation import (
    verify_commit_light,
    verify_commit_light_trusting,
)
from ..wire.canonical import Timestamp

# light.DefaultTrustLevel (light/verifier.go:20)
DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class ErrNotEnoughTrust(ValueError):
    """verifier.go ErrNewValSetCantBeTrusted."""


class ErrInvalidHeader(ValueError):
    pass


class ErrOldHeaderExpired(ValueError):
    pass


def _ts_add(ts: Timestamp, seconds: float) -> Timestamp:
    total_ns = ts.seconds * 10**9 + ts.nanos + int(seconds * 1e9)
    return Timestamp(seconds=total_ns // 10**9, nanos=total_ns % 10**9)


def _ts_before(a: Timestamp, b: Timestamp) -> bool:
    return (a.seconds, a.nanos) < (b.seconds, b.nanos)


def header_expired(h: SignedHeader, trusting_period: float, now: Timestamp) -> bool:
    """verifier.go HeaderExpired: expiration = header.Time + trustingPeriod."""
    expiration = _ts_add(h.header.time, trusting_period)
    return not _ts_before(now, expiration)


def validate_trust_level(lvl: Fraction) -> None:
    """verifier.go ValidateTrustLevel: must be in [1/3, 1]."""
    if (
        lvl.numerator * 3 < lvl.denominator
        or lvl.numerator > lvl.denominator
        or lvl.denominator == 0
    ):
        raise ValueError(f"trustLevel must be within [1/3, 1], given {lvl}")


def verify_new_header_and_vals(
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusted_header: SignedHeader,
    now: Timestamp,
    max_clock_drift: float,
) -> None:
    """verifier.go:236-283 verifyNewHeaderAndVals."""
    chain_id = trusted_header.header.chain_id
    try:
        untrusted_header.validate_basic(chain_id)
    except ValueError as e:
        raise ErrInvalidHeader(f"untrustedHeader.ValidateBasic failed: {e}") from e
    if untrusted_header.header.height <= trusted_header.header.height:
        raise ErrInvalidHeader(
            f"expected new header height {untrusted_header.header.height} to be greater "
            f"than one of old header {trusted_header.header.height}"
        )
    if not _ts_before(trusted_header.header.time, untrusted_header.header.time):
        raise ErrInvalidHeader("expected new header time to be after old header time")
    if not _ts_before(untrusted_header.header.time, _ts_add(now, max_clock_drift)):
        raise ErrInvalidHeader(
            "new header has a time from the future (max clock drift exceeded)"
        )
    if untrusted_header.header.validators_hash != untrusted_vals.hash():
        raise ErrInvalidHeader(
            f"expected new header validators ({untrusted_header.header.validators_hash.hex()}) "
            f"to match those supplied ({untrusted_vals.hash().hex()})"
        )


class SigCheck:
    """One commit-signature check of a header verification (ISSUE 11).

    The prepare_* functions below run every NON-sig check host-side
    (heights, trust level, expiry, hash chaining, clock drift — exactly
    the lines the old verify_* bodies ran) and return the sig work as
    SigCheck objects instead of verifying in place. Two consumers:

      run_sync()  the sequential path — calls the SAME types.validation
                  entry point the old code called, with the identical
                  error wrapping, so verify_adjacent/verify_non_adjacent
                  keep their byte-for-byte behavior;
      prepare()   the batched light service — returns (entries, conclude)
                  where `entries` is the check's EntryBlock (epoch
                  metadata attached) to ship through the shared device
                  pipeline and `conclude(valid)` raises the identical
                  (wrapped) error over the device verdict row. A check
                  the async seam cannot represent falls back to
                  run_sync() inside prepare() and returns (None, None),
                  as does the sub-threshold single-signature path.
    """

    __slots__ = ("kind", "_run", "_prep", "_wrap")

    def __init__(self, kind: str, run: Callable[[], None],
                 prep: Callable[[], tuple],
                 wrap: Callable[[BaseException], BaseException]):
        self.kind = kind
        self._run = run
        self._prep = prep
        self._wrap = wrap

    def _raise(self, e: BaseException):
        w = self._wrap(e)
        if w is e:
            raise
        raise w from e

    def run_sync(self) -> None:
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — wrap decides
            self._raise(e)

    def prepare(self):
        try:
            entries, conclude = self._prep()
        except _validation.PrepareUnsupported:
            self.run_sync()
            return None, None
        except Exception as e:  # noqa: BLE001 — wrap decides
            self._raise(e)
        if conclude is None:
            return None, None

        def _conclude(valid) -> None:
            try:
                conclude(valid)
            except Exception as e:  # noqa: BLE001 — wrap decides
                self._raise(e)

        return entries, _conclude


def _wrap_trusting(e: BaseException) -> BaseException:
    """verify_non_adjacent's trusting-stage wrapping (verifier.go:67-80):
    only insufficient tallied power is a (retryable) trust failure — any
    other commit defect is an invalid header."""
    if isinstance(e, ErrNotEnoughVotingPowerSigned):
        return ErrNotEnoughTrust(str(e))
    if isinstance(e, ValueError):
        return ErrInvalidHeader(str(e))
    return e


def _wrap_light(e: BaseException) -> BaseException:
    """The +2/3 commit check's wrapping (verifier.go:143-148): any commit
    defect surfaces as ErrInvalidHeader."""
    if isinstance(e, ErrInvalidHeader):
        return e
    if isinstance(e, ValueError):
        return ErrInvalidHeader(str(e))
    return e


def _light_check(chain_id: str, vals: ValidatorSet, block_id, height: int,
                 commit) -> SigCheck:
    return SigCheck(
        "light",
        run=lambda: verify_commit_light(chain_id, vals, block_id, height, commit),
        prep=lambda: _validation.prepare_commit_light(
            chain_id, vals, block_id, height, commit
        ),
        wrap=_wrap_light,
    )


def _trusting_check(chain_id: str, vals: ValidatorSet, commit,
                    trust_level: Fraction) -> SigCheck:
    return SigCheck(
        "trusting",
        run=lambda: verify_commit_light_trusting(chain_id, vals, commit, trust_level),
        prep=lambda: _validation.prepare_commit_light_trusting(
            chain_id, vals, commit, trust_level
        ),
        wrap=_wrap_trusting,
    )


def prepare_adjacent(
    trusted_header: SignedHeader,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
) -> List[SigCheck]:
    """verifier.go:103-150 host checks; returns the sig work (one +2/3
    commit check) instead of running it."""
    if untrusted_header.header.height != trusted_header.header.height + 1:
        raise ValueError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period, now):
        raise ErrOldHeaderExpired(f"old header has expired at {now}")
    verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift
    )
    # valhash continuity (verifier.go:134-142)
    if untrusted_header.header.validators_hash != trusted_header.header.next_validators_hash:
        raise ErrInvalidHeader(
            f"expected old header next validators ({trusted_header.header.next_validators_hash.hex()}) "
            f"to match those from new header ({untrusted_header.header.validators_hash.hex()})"
        )
    return [
        _light_check(
            trusted_header.header.chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.header.height,
            untrusted_header.commit,
        )
    ]


def prepare_non_adjacent(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction,
) -> List[SigCheck]:
    """verifier.go:33-101 host checks; returns the sig work — the
    trust-level check against the OLD set, then the full +2/3 of the NEW
    set, IN ORDER (the service applies verdicts in stage order so error
    precedence matches the sequential path)."""
    if untrusted_header.header.height == trusted_header.header.height + 1:
        raise ValueError("headers must be non adjacent in height")
    validate_trust_level(trust_level)
    if header_expired(trusted_header, trusting_period, now):
        raise ErrOldHeaderExpired(f"old header has expired at {now}")
    verify_new_header_and_vals(
        untrusted_header, untrusted_vals, trusted_header, now, max_clock_drift
    )
    chain_id = trusted_header.header.chain_id
    return [
        _trusting_check(
            chain_id, trusted_vals, untrusted_header.commit, trust_level
        ),
        _light_check(
            chain_id,
            untrusted_vals,
            untrusted_header.commit.block_id,
            untrusted_header.header.height,
            untrusted_header.commit,
        ),
    ]


def prepare_verify(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction,
) -> List[SigCheck]:
    """verifier.go:152-176 Verify dispatch, over the prepare seam."""
    if untrusted_header.header.height != trusted_header.header.height + 1:
        return prepare_non_adjacent(
            trusted_header, trusted_vals, untrusted_header, untrusted_vals,
            trusting_period, now, max_clock_drift, trust_level,
        )
    return prepare_adjacent(
        trusted_header, untrusted_header, untrusted_vals,
        trusting_period, now, max_clock_drift,
    )


def verify_adjacent(
    trusted_header: SignedHeader,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
) -> None:
    """verifier.go:103-150: the prepare seam driven synchronously —
    full commit verification on the device engine (verifier.go:143-148);
    any commit defect surfaces as ErrInvalidHeader."""
    for chk in prepare_adjacent(
        trusted_header, untrusted_header, untrusted_vals,
        trusting_period, now, max_clock_drift,
    ):
        chk.run_sync()


def verify_non_adjacent(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction,
) -> None:
    """verifier.go:33-101: the prepare seam driven synchronously."""
    for chk in prepare_non_adjacent(
        trusted_header, trusted_vals, untrusted_header, untrusted_vals,
        trusting_period, now, max_clock_drift, trust_level,
    ):
        chk.run_sync()


def verify(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period: float,
    now: Timestamp,
    max_clock_drift: float,
    trust_level: Fraction,
) -> None:
    """verifier.go:152-176 Verify: dispatch adjacent/non-adjacent."""
    if untrusted_header.header.height != trusted_header.header.height + 1:
        verify_non_adjacent(
            trusted_header, trusted_vals, untrusted_header, untrusted_vals,
            trusting_period, now, max_clock_drift, trust_level,
        )
    else:
        verify_adjacent(
            trusted_header, untrusted_header, untrusted_vals,
            trusting_period, now, max_clock_drift,
        )


def verify_backwards(untrusted_header, trusted_header) -> None:
    """verifier.go:201-234: walk back by hash linkage."""
    if header_expired(trusted_header, 0, trusted_header.header.time):
        pass  # expiry handled by caller in backwards mode
    if untrusted_header.header.chain_id != trusted_header.header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if not _ts_before(untrusted_header.header.time, trusted_header.header.time):
        raise ErrInvalidHeader(
            "expected older header time to be before newer header time"
        )
    if trusted_header.header.last_block_id.hash != untrusted_header.header.hash():
        raise ErrInvalidHeader(
            f"older header hash {untrusted_header.header.hash().hex()} does not match "
            f"trusted header's last block {trusted_header.header.last_block_id.hash.hex()}"
        )
