"""Trusted light-block store.

Reference parity: light/store/db — persisted light blocks keyed by height
with first/last queries and pruning.
"""

from __future__ import annotations

import struct
from typing import Optional

from ..db import DB
from ..types import Commit, Header, SignedHeader, ValidatorSet
from ..wire.proto import ProtoWriter, decode_message, field_bytes
from .provider import LightBlock

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + struct.pack(">q", height)


class LightStore:
    def __init__(self, db: DB):
        self._db = db

    def save_light_block(self, lb: LightBlock) -> None:
        w = ProtoWriter()
        sh = ProtoWriter()
        sh.write_message(1, lb.signed_header.header.encode(), always=True)
        sh.write_message(2, lb.signed_header.commit.encode(), always=True)
        w.write_message(1, sh.bytes(), always=True)
        w.write_message(2, lb.validators.encode(), always=True)
        self._db.set(_key(lb.height), w.bytes())

    def light_block(self, height: int) -> Optional[LightBlock]:
        raw = self._db.get(_key(height))
        if raw is None:
            return None
        f = decode_message(raw)
        sh = decode_message(field_bytes(f, 1))
        return LightBlock(
            signed_header=SignedHeader(
                header=Header.decode(field_bytes(sh, 1)),
                commit=Commit.decode(field_bytes(sh, 2)),
            ),
            validators=ValidatorSet.decode(field_bytes(f, 2)),
        )

    def first_light_block_height(self) -> int:
        for k, _ in self._db.iterator(_key(0), _key((1 << 62))):
            return struct.unpack(">q", k[len(_PREFIX):])[0]
        return -1

    def last_light_block_height(self) -> int:
        for k, _ in self._db.reverse_iterator(_key(0), _key((1 << 62))):
            return struct.unpack(">q", k[len(_PREFIX):])[0]
        return -1

    def latest_light_block(self) -> Optional[LightBlock]:
        h = self.last_light_block_height()
        return self.light_block(h) if h >= 0 else None

    def light_block_before(self, height: int) -> Optional[LightBlock]:
        for k, _ in self._db.reverse_iterator(_key(0), _key(height)):
            return self.light_block(struct.unpack(">q", k[len(_PREFIX):])[0])
        return None

    def prune(self, size: int) -> int:
        """Keep only the newest `size` blocks (store/db prune)."""
        heights = [
            struct.unpack(">q", k[len(_PREFIX):])[0]
            for k, _ in self._db.iterator(_key(0), _key(1 << 62))
        ]
        pruned = 0
        for h in heights[: max(0, len(heights) - size)]:
            self._db.delete(_key(h))
            pruned += 1
        return pruned
