"""Rollback — revert chain state by one height (app-hash recovery).

Reference parity: internal/state/rollback.go — rebuilds State at
height-1 from the stores (validators/params checkpoints + block meta),
leaving the block store intact so the block is re-applied on restart.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Tuple

from ..types import BlockID
from . import State
from .store import StateStore


def rollback_state(state_store: StateStore, block_store) -> Tuple[int, bytes]:
    """rollback.go Rollback: returns (new_height, new_app_hash)."""
    invalid_state = state_store.load()
    if invalid_state is None:
        raise RuntimeError("no state found")
    height = block_store.height()

    # State and block persistence are not atomic: a node stopped between
    # save_block and state save leaves the blockstore ONE ahead. No state
    # needs rolling back — return the current state unchanged
    # (rollback.go:24-29).
    if height == invalid_state.last_block_height + 1:
        return invalid_state.last_block_height, invalid_state.app_hash

    # otherwise the stores must agree on the height (rollback.go:31-36)
    if invalid_state.last_block_height != height:
        raise RuntimeError(
            f"statestore height ({invalid_state.last_block_height}) is not "
            f"one below or equal to blockstore height ({height})"
        )
    rollback_height = invalid_state.last_block_height
    rollback_block = block_store.load_block_meta(rollback_height)
    if rollback_block is None:
        raise RuntimeError(f"block at height {rollback_height} not found")
    prev_height = rollback_height - 1
    if prev_height <= 0:
        raise RuntimeError("cannot rollback to height <= 0")
    prev_block = block_store.load_block_meta(prev_height)
    if prev_block is None:
        raise RuntimeError(f"block at height {prev_height} not found")

    prev_validators = state_store.load_validators(prev_height)
    curr_validators = state_store.load_validators(rollback_height)
    next_validators = state_store.load_validators(rollback_height + 1)
    prev_params = state_store.load_consensus_params(rollback_height)

    # the rolled-back state believes `rollback_height - 1` was the last
    # committed block (rollback.go:60-95)
    new_state = State(
        version=replace(
            invalid_state.version, app=prev_params.version.app_version
        ),
        chain_id=invalid_state.chain_id,
        initial_height=invalid_state.initial_height,
        last_block_height=prev_height,
        last_block_id=rollback_block.header.last_block_id,
        last_block_time=prev_block.header.time,
        next_validators=curr_validators,
        validators=prev_validators,
        last_validators=state_store.load_validators(max(prev_height - 1, 1))
        if prev_height > 1
        else prev_validators,
        # clamp change-heights that refer past the rolled-back block
        # (rollback.go:56-66)
        last_height_validators_changed=min(
            invalid_state.last_height_validators_changed, rollback_height
        ),
        consensus_params=prev_params,
        last_height_consensus_params_changed=min(
            invalid_state.last_height_consensus_params_changed, rollback_height
        ),
        last_results_hash=prev_block.header.last_results_hash,
        app_hash=rollback_block.header.app_hash,
    )
    state_store.save(new_state)
    return new_state.last_block_height, new_state.app_hash
