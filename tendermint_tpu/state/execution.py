"""BlockExecutor — validates blocks, drives the ABCI app, updates State.

Reference parity: internal/state/execution.go (ApplyBlock:152,
Commit:246, CreateProposalBlock:103, execBlockOnProxyApp:294,
updateState:445) and internal/state/validation.go (validateBlock).

LastCommit verification inside validateBlock routes through
types.validation.verify_commit — i.e. through the device batch engine.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable, List, Optional, Tuple

from ..abci import types as abci
from ..crypto.encoding import pubkey_from_proto
from ..libs.fail import fail_point
from ..types import Block, BlockID, Commit, Validator, ValidatorSet
from ..types.params import ConsensusParams
from ..types.results import results_hash
from ..types.validation import verify_commit
from ..wire.proto import decode_message, field_bytes, field_int, to_signed64
from . import State, median_time
from .store import ABCIResponses, StateStore


class InvalidBlockError(ValueError):
    pass


class BlockExecutor:
    """execution.go:53-101."""

    def __init__(
        self,
        state_store: StateStore,
        proxy_app,  # consensus-connection ABCI client
        mempool=None,
        evpool=None,
        block_store=None,
        event_bus=None,
    ):
        self._store = state_store
        self._proxy_app = proxy_app
        self._mempool = mempool
        self._evpool = evpool
        self._block_store = block_store
        self._event_bus = event_bus
        self._validated_cache: set = set()

    @property
    def store(self) -> StateStore:
        return self._store

    # -- proposal creation (execution.go:103-150) ------------------------

    def create_proposal_block(
        self, height: int, state: State, commit: Optional[Commit], proposer_addr: bytes
    ):
        max_bytes = state.consensus_params.block.max_bytes
        max_gas = state.consensus_params.block.max_gas
        evidence = []
        if self._evpool is not None:
            evidence = self._evpool.pending_evidence_bytes(
                state.consensus_params.evidence.max_bytes
            )
        txs: List[bytes] = []
        if self._mempool is not None:
            # data cap: MaxDataBytes(maxBytes, evidence size, #validators)
            txs = self._mempool.reap_max_bytes_max_gas(max_bytes, max_gas)
        return state.make_block(height, txs, commit, evidence, proposer_addr)

    # -- validation ------------------------------------------------------

    def validate_block(self, state: State, block: Block) -> None:
        key = bytes(block.hash())
        if key in self._validated_cache:
            return
        validate_block(state, block)
        if self._evpool is not None:
            self._evpool.check_evidence(state, block.evidence)
        self._validated_cache.add(key)

    # -- the main entry (execution.go:152-240) ---------------------------

    def apply_block(self, state: State, block_id: BlockID, block: Block) -> State:
        try:
            self.validate_block(state, block)
        except ValueError as e:
            raise InvalidBlockError(str(e)) from e

        abci_responses = exec_block_on_proxy_app(
            self._proxy_app, block, self._store, state.initial_height
        )
        fail_point(1)
        self._store.save_abci_responses(block.header.height, abci_responses)
        fail_point(2)

        end_block = abci.dec_response_payload("end_block", abci_responses.end_block)
        validate_validator_updates(end_block.validator_updates, state.consensus_params)
        validator_updates = [
            Validator.new(pubkey_from_proto(v.pub_key), v.power)
            for v in end_block.validator_updates
        ]

        state = update_state(state, block_id, block, abci_responses, validator_updates)

        app_hash, retain_height = self.commit(state, block, abci_responses)

        if self._evpool is not None:
            self._evpool.update(state, block.evidence)
        fail_point(3)

        state = replace_app_hash(state, app_hash)
        self._store.save(state)
        fail_point(4)

        if retain_height > 0 and self._block_store is not None:
            try:
                self._block_store.prune_blocks(retain_height)
                self._store.prune_states(retain_height)
            except ValueError:
                pass

        self._validated_cache = set()
        if self._event_bus is not None:
            fire_events(self._event_bus, block, block_id, abci_responses, validator_updates)
        return state

    # -- commit (execution.go:246-292) ------------------------------------

    def commit(self, state: State, block: Block, abci_responses: ABCIResponses):
        if self._mempool is not None:
            self._mempool.lock()
        try:
            if self._mempool is not None:
                self._mempool.flush_app_conn()
            res = self._proxy_app.commit()
            if self._mempool is not None:
                deliver_txs = [
                    abci.dec_response_payload("deliver_tx", raw)
                    for raw in abci_responses.deliver_txs
                ]
                self._mempool.update(
                    block.header.height,
                    block.data.txs,
                    deliver_txs,
                    tx_pre_check(state),
                    tx_post_check(state),
                )
            return res.data, res.retain_height
        finally:
            if self._mempool is not None:
                self._mempool.unlock()


def exec_block_on_proxy_app(
    proxy_app, block: Block, store: StateStore, initial_height: int
) -> ABCIResponses:
    """execution.go:294-376: BeginBlock → DeliverTx×N (pipelined when the
    client supports it) → EndBlock."""
    commit_info = get_begin_block_validator_info(block, store, initial_height)
    byz_vals: List[abci.ABCIEvidence] = []
    from ..types.evidence import evidence_to_abci

    for ev_raw in block.evidence:
        byz_vals.extend(evidence_to_abci(ev_raw))

    begin = proxy_app.begin_block(
        abci.RequestBeginBlock(
            hash=block.hash(),
            header=block.header.encode(),
            last_commit_info=commit_info,
            byzantine_validators=byz_vals,
        )
    )
    futs = []
    if hasattr(proxy_app, "deliver_tx_async"):
        for tx in block.data.txs:
            futs.append(proxy_app.deliver_tx_async(abci.RequestDeliverTx(tx=tx)))
        if hasattr(proxy_app, "flush"):
            proxy_app.flush()
        deliver_responses = [f.result(timeout=60) for f in futs]
    else:
        deliver_responses = [
            proxy_app.deliver_tx(abci.RequestDeliverTx(tx=tx)) for tx in block.data.txs
        ]
    end = proxy_app.end_block(abci.RequestEndBlock(height=block.header.height))
    return ABCIResponses(
        deliver_txs=[abci.enc_response_payload("deliver_tx", r) for r in deliver_responses],
        end_block=abci.enc_response_payload("end_block", end),
        begin_block=abci.enc_response_payload("begin_block", begin),
    )


def get_begin_block_validator_info(
    block: Block, store: StateStore, initial_height: int
) -> abci.LastCommitInfo:
    """execution.go:378-420."""
    last_commit = block.last_commit
    if last_commit is None:
        return abci.LastCommitInfo()
    vote_infos: List[abci.VoteInfo] = []
    if block.header.height > initial_height:
        last_val_set = store.load_validators(block.header.height - 1)
        commit_size = last_commit.size()
        if commit_size != last_val_set.size():
            raise RuntimeError(
                f"commit size ({commit_size}) doesn't match valset length "
                f"({last_val_set.size()}) at height {block.header.height}"
            )
        for i, val in enumerate(last_val_set.validators):
            cs = last_commit.signatures[i]
            vote_infos.append(
                abci.VoteInfo(
                    validator=abci.ABCIValidator(address=val.address, power=val.voting_power),
                    signed_last_block=not cs.is_absent(),
                )
            )
    return abci.LastCommitInfo(round=last_commit.round, votes=vote_infos)


def validate_validator_updates(
    updates: List[abci.ValidatorUpdate], params: ConsensusParams
) -> None:
    """execution.go:422-443."""
    for u in updates:
        if u.power < 0:
            raise ValueError(f"voting power can't be negative: {u}")
        if u.power == 0:
            continue
        pk = pubkey_from_proto(u.pub_key)
        if not params.validator.is_valid_pubkey_type(pk.type()):
            raise ValueError(
                f"validator {pk.address().hex()} is using pubkey {pk.type()}, "
                "which is unsupported for consensus"
            )


def update_state(
    state: State,
    block_id: BlockID,
    block: Block,
    abci_responses: ABCIResponses,
    validator_updates: List[Validator],
) -> State:
    """execution.go:445-520."""
    header = block.header
    n_val_set = state.next_validators.copy()
    last_height_vals_changed = state.last_height_validators_changed
    if validator_updates:
        n_val_set.update_with_change_set(validator_updates)
        last_height_vals_changed = header.height + 1 + 1
    n_val_set.increment_proposer_priority(1)

    next_params = state.consensus_params
    last_height_params_changed = state.last_height_consensus_params_changed
    version = state.version
    end_block = abci.dec_response_payload("end_block", abci_responses.end_block)
    if end_block.consensus_param_updates is not None:
        subset = ConsensusParams.decode_update_subset(end_block.consensus_param_updates)
        next_params = state.consensus_params.update_from_proto_subset(*subset)
        next_params.validate_consensus_params()
        version = replace(version, app=next_params.version.app_version)
        last_height_params_changed = header.height + 1

    deliver_results = [
        _deliver_tx_code_data(raw) for raw in abci_responses.deliver_txs
    ]
    return State(
        version=version,
        chain_id=state.chain_id,
        initial_height=state.initial_height,
        last_block_height=header.height,
        last_block_id=block_id,
        last_block_time=header.time,
        next_validators=n_val_set,
        validators=state.next_validators.copy(),
        last_validators=state.validators.copy(),
        last_height_validators_changed=last_height_vals_changed,
        consensus_params=next_params,
        last_height_consensus_params_changed=last_height_params_changed,
        last_results_hash=results_hash(deliver_results),
        app_hash=b"",  # set after Commit
    )


def _deliver_tx_code_data(raw: bytes) -> Tuple[int, bytes]:
    f = decode_message(raw)
    return field_int(f, 1), field_bytes(f, 2)


def replace_app_hash(state: State, app_hash: bytes) -> State:
    s = state.copy()
    s.app_hash = app_hash
    return s


def validate_block(state: State, block: Block) -> None:
    """internal/state/validation.go:14-120."""
    block.validate_basic()
    h = block.header
    if h.version.app != state.version.app or h.version.block != state.version.block:
        raise ValueError(
            f"wrong Block.Header.Version. Expected {state.version}, got {h.version}"
        )
    if h.chain_id != state.chain_id:
        raise ValueError(
            f"wrong Block.Header.ChainID. Expected {state.chain_id}, got {h.chain_id}"
        )
    if state.last_block_height == 0 and h.height != state.initial_height:
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.initial_height} for initial block, got {h.height}"
        )
    if state.last_block_height > 0 and h.height != state.last_block_height + 1:
        raise ValueError(
            f"wrong Block.Header.Height. Expected {state.last_block_height + 1}, got {h.height}"
        )
    if h.last_block_id != state.last_block_id:
        raise ValueError(
            f"wrong Block.Header.LastBlockID. Expected {state.last_block_id}, got {h.last_block_id}"
        )
    if h.app_hash != state.app_hash:
        raise ValueError(
            f"wrong Block.Header.AppHash. Expected {state.app_hash.hex()}, got {h.app_hash.hex()}"
        )
    hash_cp = state.consensus_params.hash_consensus_params()
    if h.consensus_hash != hash_cp:
        raise ValueError("wrong Block.Header.ConsensusHash")
    if h.last_results_hash != state.last_results_hash:
        raise ValueError("wrong Block.Header.LastResultsHash")
    if h.validators_hash != state.validators.hash():
        raise ValueError("wrong Block.Header.ValidatorsHash")
    if h.next_validators_hash != state.next_validators.hash():
        raise ValueError("wrong Block.Header.NextValidatorsHash")

    if h.height == state.initial_height:
        if block.last_commit is not None and block.last_commit.signatures:
            raise ValueError("initial block can't have LastCommit signatures")
    else:
        # THE batch hot path: LastCommit verified on the device engine.
        verify_commit(
            state.chain_id, state.last_validators, state.last_block_id,
            h.height - 1, block.last_commit,
        )

    if not state.validators.has_address(h.proposer_address):
        raise ValueError(
            f"block.Header.ProposerAddress {h.proposer_address.hex()} is not a validator"
        )

    if h.height > state.initial_height:
        if (h.time.seconds, h.time.nanos) <= (
            state.last_block_time.seconds,
            state.last_block_time.nanos,
        ):
            raise ValueError(
                f"block time {h.time} not greater than last block time {state.last_block_time}"
            )
        med = median_time(block.last_commit, state.last_validators)
        if h.time != med:
            raise ValueError(f"invalid block time. Expected {med}, got {h.time}")
    elif h.height == state.initial_height:
        if h.time != state.last_block_time:
            raise ValueError(
                f"block time {h.time} is not equal to genesis time {state.last_block_time}"
            )


def fire_events(event_bus, block, block_id, abci_responses, validator_updates) -> None:
    """execution.go:575-613 fireEvents."""
    event_bus.publish_new_block(block, block_id, abci_responses)
    event_bus.publish_new_block_header(block.header)
    for i, tx in enumerate(block.data.txs):
        event_bus.publish_tx(block.header.height, i, tx, abci_responses.deliver_txs[i])
    if validator_updates:
        event_bus.publish_validator_set_updates(validator_updates)


def tx_pre_check(state: State) -> Callable:
    """tx_filter.go PreCheckMaxBytes: tx must fit the block."""
    from ..types.block import MAX_HEADER_BYTES

    max_data_bytes = state.consensus_params.block.max_bytes - MAX_HEADER_BYTES - 1000

    def check(tx: bytes) -> None:
        if len(tx) > max_data_bytes:
            raise ValueError(f"tx size {len(tx)} exceeds max {max_data_bytes}")

    return check


def tx_post_check(state: State) -> Callable:
    """tx_filter.go PostCheckMaxGas."""
    max_gas = state.consensus_params.block.max_gas

    def check(tx: bytes, res) -> None:
        if max_gas > -1 and res.gas_wanted > max_gas:
            raise ValueError(f"gas wanted {res.gas_wanted} exceeds max {max_gas}")

    return check
