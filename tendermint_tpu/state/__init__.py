"""State — the replicated chain state between blocks.

Reference parity: internal/state/state.go. Holds the validator-set window
(Last/Current/Next), consensus params, last results/app hashes; produces
proposal blocks (MakeBlock) with BFT-median block time (time.go
weightedMedian).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional, Tuple

from ..types import (
    Block,
    BlockID,
    Commit,
    Data,
    Header,
    Timestamp,
    Validator,
    ValidatorSet,
    Version,
)
from ..types.genesis import GenesisDoc
from ..types.params import ConsensusParams, default_consensus_params
from ..types.part_set import BLOCK_PART_SIZE_BYTES, PartSet
from ..version import BLOCK_PROTOCOL

# InitStateVersion: the Consensus version an empty state starts at
# (internal/state/state.go:38-44).
INIT_STATE_VERSION = Version(block=BLOCK_PROTOCOL, app=0)


@dataclass
class State:
    """internal/state/state.go:66-101."""

    version: Version = field(default_factory=lambda: INIT_STATE_VERSION)
    chain_id: str = ""
    initial_height: int = 1

    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time: Timestamp = field(default_factory=Timestamp.zero)

    next_validators: Optional[ValidatorSet] = None
    validators: Optional[ValidatorSet] = None
    last_validators: Optional[ValidatorSet] = None
    last_height_validators_changed: int = 0

    consensus_params: ConsensusParams = field(default_factory=default_consensus_params)
    last_height_consensus_params_changed: int = 0

    last_results_hash: bytes = b""
    app_hash: bytes = b""

    def copy(self) -> "State":
        return State(
            version=self.version,
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=self.last_block_height,
            last_block_id=self.last_block_id,
            last_block_time=self.last_block_time,
            next_validators=self.next_validators.copy() if self.next_validators else None,
            validators=self.validators.copy() if self.validators else None,
            last_validators=self.last_validators.copy() if self.last_validators else None,
            last_height_validators_changed=self.last_height_validators_changed,
            consensus_params=self.consensus_params,
            last_height_consensus_params_changed=self.last_height_consensus_params_changed,
            last_results_hash=self.last_results_hash,
            app_hash=self.app_hash,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    def equals(self, other: "State") -> bool:
        return (
            self.chain_id == other.chain_id
            and self.last_block_height == other.last_block_height
            and self.last_block_id == other.last_block_id
            and self.app_hash == other.app_hash
        )

    # -- block production ----------------------------------------------

    def make_block(
        self,
        height: int,
        txs: List[bytes],
        commit: Optional[Commit],
        evidence: List[bytes],
        proposer_address: bytes,
    ) -> Tuple[Block, PartSet]:
        """state.go:255-284."""
        if height == self.initial_height:
            timestamp = self.last_block_time  # genesis time
        else:
            timestamp = median_time(commit, self.last_validators)
        header = Header(
            version=self.version,
            chain_id=self.chain_id,
            height=height,
            time=timestamp,
            last_block_id=self.last_block_id,
            validators_hash=self.validators.hash(),
            next_validators_hash=self.next_validators.hash(),
            consensus_hash=self.consensus_params.hash_consensus_params(),
            app_hash=self.app_hash,
            last_results_hash=self.last_results_hash,
            proposer_address=proposer_address,
        )
        block = Block(header=header, data=Data(txs=list(txs)), evidence=list(evidence), last_commit=commit)
        block.fill_header()
        parts = PartSet.from_data(block.encode(), BLOCK_PART_SIZE_BYTES)
        return block, parts


def median_time(commit: Optional[Commit], validators: Optional[ValidatorSet]) -> Timestamp:
    """BFT-safe weighted median of commit timestamps (state.go:290-307,
    time.go weightedMedian)."""
    if commit is None or validators is None:
        return Timestamp.zero()
    weighted: List[Tuple[Timestamp, int]] = []
    total_power = 0
    for cs in commit.signatures:
        if cs.is_absent():
            continue
        _, val = validators.get_by_address(cs.validator_address)
        if val is not None:
            total_power += val.voting_power
            weighted.append((cs.timestamp, val.voting_power))
    weighted.sort(key=lambda wt: (wt[0].seconds, wt[0].nanos))
    median = total_power // 2
    for ts, weight in weighted:
        if median <= weight:
            return ts
        median -= weight
    return Timestamp.zero()


def make_genesis_state(gen_doc: GenesisDoc) -> State:
    """state.go:330-380 MakeGenesisState."""
    gen_doc.validate_and_complete()
    if gen_doc.validators:
        vals = [Validator.new(v.pub_key, v.power) for v in gen_doc.validators]
        validator_set = ValidatorSet.new(vals)
        next_validator_set = validator_set.copy_increment_proposer_priority(1)
    else:
        validator_set = ValidatorSet()  # to be set by InitChain response
        next_validator_set = ValidatorSet()
    params = gen_doc.consensus_params or default_consensus_params()
    return State(
        version=Version(block=BLOCK_PROTOCOL, app=params.version.app_version),
        chain_id=gen_doc.chain_id,
        initial_height=gen_doc.initial_height,
        last_block_height=0,
        last_block_id=BlockID(),
        last_block_time=gen_doc.genesis_time,
        next_validators=next_validator_set,
        validators=validator_set,
        last_validators=ValidatorSet(),
        last_height_validators_changed=gen_doc.initial_height,
        consensus_params=params,
        last_height_consensus_params_changed=gen_doc.initial_height,
        app_hash=gen_doc.app_hash,
    )
