"""State store — persists State, validator sets, params, ABCI responses.

Reference parity: internal/state/store.go. Validator sets are stored at
every height where they changed (with last_height_changed markers so
lookups walk back to the last checkpoint), consensus params likewise;
ABCI responses per height feed the /block_results RPC and last_results
hash.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..db import DB, Batch
from ..types import BlockID, Timestamp, ValidatorSet, Version
from ..types.params import ConsensusParams
from ..wire import canonical as _canon
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, field_repeated_bytes, to_signed64
from . import State

_KEY_STATE = b"stateKey"


def _validators_key(height: int) -> bytes:
    return b"validatorsKey:" + struct.pack(">q", height)


def _params_key(height: int) -> bytes:
    return b"consensusParamsKey:" + struct.pack(">q", height)


def _abci_responses_key(height: int) -> bytes:
    return b"abciResponsesKey:" + struct.pack(">q", height)


@dataclass
class ABCIResponses:
    """proto/tendermint/state ABCIResponses: deliver_txs + end_block +
    begin_block, stored as the already-encoded response payloads."""

    deliver_txs: List[bytes] = field(default_factory=list)
    end_block: bytes = b""
    begin_block: bytes = b""

    def encode(self) -> bytes:
        w = ProtoWriter()
        for tx in self.deliver_txs:
            w.write_message(1, tx, always=True)
        w.write_message(2, self.end_block, always=True)
        w.write_message(3, self.begin_block, always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "ABCIResponses":
        f = decode_message(data)
        return cls(
            deliver_txs=field_repeated_bytes(f, 1),
            end_block=field_bytes(f, 2),
            begin_block=field_bytes(f, 3),
        )


class StateStore:
    """internal/state/store.go:95-660."""

    def __init__(self, db: DB):
        self._db = db

    # -- State ----------------------------------------------------------

    def save(self, state: State) -> None:
        """Save state + its validator/params checkpoints (store.go:102-147)."""
        next_height = state.last_block_height + 1
        if state.last_block_height == 0:
            next_height = state.initial_height
            # genesis bootstrap: store validators for initial and next height
            self._save_validators(next_height, state.validators,
                                  state.last_height_validators_changed)
        self._save_validators(next_height + 1, state.next_validators,
                              state.last_height_validators_changed)
        self._save_params(next_height, state.consensus_params,
                          state.last_height_consensus_params_changed)
        self._db.set(_KEY_STATE, _encode_state(state))

    def load(self) -> Optional[State]:
        raw = self._db.get(_KEY_STATE)
        if raw is None:
            return None
        return _decode_state(raw)

    def bootstrap(self, state: State) -> None:
        """store.go Bootstrap — used by statesync to plant a trusted state."""
        height = state.last_block_height + 1
        if height == state.initial_height and state.last_validators is not None \
                and not state.last_validators.is_nil_or_empty():
            self._save_validators(height - 1, state.last_validators, height - 1)
        if height > state.initial_height and state.last_validators is not None \
                and not state.last_validators.is_nil_or_empty():
            self._save_validators(height - 1, state.last_validators, height - 1)
        self._save_validators(height, state.validators, height)
        self._save_validators(height + 1, state.next_validators, height + 1)
        # full params checkpoint at `height`: after a statesync bootstrap
        # the historical checkpoint last_height_consensus_params_changed
        # points at does not exist locally, so a pointer-only record would
        # dangle (store.go Bootstrap stores the params themselves too)
        self._save_params(height, state.consensus_params, height)
        self._db.set(_KEY_STATE, _encode_state(state))

    # -- validators -----------------------------------------------------

    def _save_validators(self, height: int, vals: ValidatorSet, last_changed: int) -> None:
        w = ProtoWriter()
        w.write_varint(1, last_changed)
        if height == last_changed:
            w.write_message(2, vals.encode(), always=True)
        self._db.set(_validators_key(height), w.bytes())

    def save_validators_at(self, height: int, vals: ValidatorSet) -> None:
        """Checkpointed write for statesync backfill (reactor.go:504):
        stores the full set at `height` so historical evidence over the
        backfilled window can be verified."""
        self._save_validators(height, vals, height)

    def load_validators(self, height: int) -> ValidatorSet:
        """store.go LoadValidators: walk back to the checkpoint then
        increment priorities forward (store.go:244-294)."""
        raw = self._db.get(_validators_key(height))
        if raw is None:
            raise KeyError(f"no validator set at height {height}")
        f = decode_message(raw)
        last_changed = to_signed64(field_int(f, 1))
        if 2 in f:
            return ValidatorSet.decode(field_bytes(f, 2))
        raw2 = self._db.get(_validators_key(last_changed))
        if raw2 is None:
            raise KeyError(
                f"validator checkpoint at height {last_changed} missing for height {height}"
            )
        f2 = decode_message(raw2)
        vals = ValidatorSet.decode(field_bytes(f2, 2))
        vals.increment_proposer_priority(height - last_changed)
        return vals

    # -- params ---------------------------------------------------------

    def _save_params(self, height: int, params: ConsensusParams, last_changed: int) -> None:
        w = ProtoWriter()
        w.write_varint(1, last_changed)
        if height == last_changed:
            w.write_message(2, params.encode(), always=True)
        self._db.set(_params_key(height), w.bytes())

    def load_consensus_params(self, height: int) -> ConsensusParams:
        raw = self._db.get(_params_key(height))
        if raw is None:
            raise KeyError(f"no consensus params at height {height}")
        f = decode_message(raw)
        last_changed = to_signed64(field_int(f, 1))
        if 2 in f:
            return ConsensusParams.decode(field_bytes(f, 2))
        raw2 = self._db.get(_params_key(last_changed))
        if raw2 is None:
            raise KeyError(f"params checkpoint at {last_changed} missing")
        f2 = decode_message(raw2)
        return ConsensusParams.decode(field_bytes(f2, 2))

    # -- ABCI responses --------------------------------------------------

    def save_abci_responses(self, height: int, responses: ABCIResponses) -> None:
        self._db.set(_abci_responses_key(height), responses.encode())

    def load_abci_responses(self, height: int) -> Optional[ABCIResponses]:
        raw = self._db.get(_abci_responses_key(height))
        return ABCIResponses.decode(raw) if raw is not None else None

    # -- pruning (store.go PruneStates) ----------------------------------

    def prune_states(self, retain_height: int) -> None:
        for key_fn in (_validators_key, _params_key, _abci_responses_key):
            for k, _ in list(self._db.iterator(key_fn(0), key_fn(retain_height))):
                self._db.delete(k)


# -- State proto codec (proto/tendermint/state/types.pb.go State) ---------


def _encode_state(s: State) -> bytes:
    w = ProtoWriter()
    ver = ProtoWriter()  # state.Version{1 consensus{1 block,2 app}, 2 software}
    ver.write_message(1, s.version.encode(), always=True)
    w.write_message(1, ver.bytes(), always=True)
    w.write_string(2, s.chain_id)
    w.write_varint(14, s.initial_height)
    w.write_varint(3, s.last_block_height)
    w.write_message(4, s.last_block_id.encode(), always=True)
    w.write_message(5, _canon.encode_timestamp(s.last_block_time), always=True)
    if s.next_validators is not None:
        w.write_message(6, s.next_validators.encode())
    if s.validators is not None:
        w.write_message(7, s.validators.encode())
    if s.last_validators is not None and not s.last_validators.is_nil_or_empty():
        w.write_message(8, s.last_validators.encode())
    w.write_varint(9, s.last_height_validators_changed)
    w.write_message(10, s.consensus_params.encode(), always=True)
    w.write_varint(11, s.last_height_consensus_params_changed)
    w.write_bytes(12, s.last_results_hash)
    w.write_bytes(13, s.app_hash)
    return w.bytes()


def _decode_state(data: bytes) -> State:
    f = decode_message(data)
    ver_f = decode_message(field_bytes(f, 1))
    ts_f = decode_message(field_bytes(f, 5))
    return State(
        version=Version.decode(field_bytes(ver_f, 1)),
        chain_id=field_bytes(f, 2).decode(),
        initial_height=to_signed64(field_int(f, 14)) or 1,
        last_block_height=to_signed64(field_int(f, 3)),
        last_block_id=BlockID.decode(field_bytes(f, 4)),
        last_block_time=Timestamp(
            seconds=to_signed64(field_int(ts_f, 1)), nanos=field_int(ts_f, 2)
        ),
        next_validators=ValidatorSet.decode(field_bytes(f, 6)) if 6 in f else None,
        validators=ValidatorSet.decode(field_bytes(f, 7)) if 7 in f else None,
        last_validators=ValidatorSet.decode(field_bytes(f, 8)) if 8 in f else ValidatorSet(),
        last_height_validators_changed=to_signed64(field_int(f, 9)),
        consensus_params=ConsensusParams.decode(field_bytes(f, 10)),
        last_height_consensus_params_changed=to_signed64(field_int(f, 11)),
        last_results_hash=field_bytes(f, 12),
        app_hash=field_bytes(f, 13),
    )
