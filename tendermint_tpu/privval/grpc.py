"""Remote signer over gRPC.

Reference parity: privval/grpc/ — the `tendermint.privval.PrivValidatorAPI`
service (GetPubKey, SignVote, SignProposal), client (privval/grpc/client.go)
and server (privval/grpc/server.go). Wire payloads reuse this framework's
privval message fields; grpcio's generic handler API carries them as raw
proto bytes (no generated stubs).
"""

from __future__ import annotations

from typing import Optional

from ..crypto import PubKey
from ..crypto import ed25519 as _ed25519
from ..types import Vote
from ..types.proposal import Proposal
from ..wire.proto import ProtoWriter, decode_message, field_bytes
from . import FilePV, PrivValidator
from .remote import RemoteSignerError

SERVICE = "tendermint.privval.PrivValidatorAPI"
_METHODS = ("GetPubKey", "SignVote", "SignProposal")


def _require_grpc():
    import grpc

    return grpc


def _identity(b: bytes) -> bytes:
    return b


def _ok(field: int, payload: bytes) -> bytes:
    w = ProtoWriter()
    w.write_bytes(field, payload)
    return w.bytes()


def _err(msg: str) -> bytes:
    w = ProtoWriter()
    w.write_string(2, msg)
    return w.bytes()


class GRPCSignerServer:
    """privval/grpc/server.go: serves a local FilePV."""

    def __init__(self, pv: FilePV, address: str = "127.0.0.1:0"):
        grpc = _require_grpc()
        from concurrent.futures import ThreadPoolExecutor

        self._pv = pv
        self._server = grpc.server(ThreadPoolExecutor(max_workers=4))
        pv_ = pv

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                try:
                    service, method = details.method.lstrip("/").split("/", 1)
                except ValueError:
                    return None
                if service != SERVICE or method not in _METHODS:
                    return None

                def unary(request: bytes, context) -> bytes:
                    f = decode_message(request)
                    if method == "GetPubKey":
                        return _ok(1, pv_.get_pub_key().bytes())
                    chain_id = field_bytes(f, 2).decode()
                    try:
                        if method == "SignVote":
                            vote = Vote.decode(field_bytes(f, 1))
                            return _ok(1, pv_.sign_vote(chain_id, vote).encode())
                        proposal = Proposal.decode(field_bytes(f, 1))
                        return _ok(1, pv_.sign_proposal(chain_id, proposal).encode())
                    except ValueError as e:
                        return _err(str(e))

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=_identity,
                    response_serializer=_identity,
                )

        self._server.add_generic_rpc_handlers((_Handler(),))
        host, _, port = address.rpartition(":")
        self._port = self._server.add_insecure_port(f"{host or '127.0.0.1'}:{port}")

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self._port}"

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=1)


class GRPCSignerClient(PrivValidator):
    """privval/grpc/client.go: PrivValidator backed by the gRPC service."""

    def __init__(self, address: str, timeout: float = 10.0):
        grpc = _require_grpc()
        for prefix in ("grpc://", "tcp://"):
            if address.startswith(prefix):
                address = address[len(prefix):]
        self._channel = grpc.insecure_channel(address)
        self._timeout = timeout
        self._calls = {
            m: self._channel.unary_unary(
                f"/{SERVICE}/{m}",
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            for m in _METHODS
        }
        self._pub: Optional[PubKey] = None

    def _roundtrip(self, method: str, payload: bytes) -> bytes:
        out = self._calls[method](payload, timeout=self._timeout)
        f = decode_message(out)
        if 2 in f:
            raise RemoteSignerError(field_bytes(f, 2).decode())
        return field_bytes(f, 1)

    def get_pub_key(self) -> PubKey:
        if self._pub is None:
            raw = self._roundtrip("GetPubKey", b"")
            self._pub = _ed25519.PubKey(raw)
        return self._pub

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        w = ProtoWriter()
        w.write_bytes(1, vote.encode())
        w.write_string(2, chain_id)
        return Vote.decode(self._roundtrip("SignVote", w.bytes()))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        w = ProtoWriter()
        w.write_bytes(1, proposal.encode())
        w.write_string(2, chain_id)
        return Proposal.decode(self._roundtrip("SignProposal", w.bytes()))

    def close(self) -> None:
        self._channel.close()
