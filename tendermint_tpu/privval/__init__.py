"""Validator key management — file-based signer with double-sign guard.

Reference parity: privval/file.go — FilePV (key file + last-sign-state
file), CheckHRS monotonicity (file.go:95-137), same-HRS re-signing only
for timestamp changes (file.go:280-320). The PrivValidator interface
matches types/priv_validator.go:28-33.
"""

from __future__ import annotations

import abc
import base64
import json
import os
import tempfile
from dataclasses import dataclass, field, replace
from typing import Optional, Tuple

from ..crypto import PrivKey, PubKey, ed25519
from ..types import Timestamp, Vote
from ..types.block import BlockID
from ..types.vote import PRECOMMIT_TYPE, PREVOTE_TYPE
from ..wire import canonical as _canon
from ..wire.proto import decode_message, field_bytes

STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote_type: int) -> int:
    if vote_type == PREVOTE_TYPE:
        return STEP_PREVOTE
    if vote_type == PRECOMMIT_TYPE:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type {vote_type}")


class PrivValidator(abc.ABC):
    """types/priv_validator.go:28-33."""

    @abc.abstractmethod
    def get_pub_key(self) -> PubKey: ...

    @abc.abstractmethod
    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        """Returns the signed vote. On a same-HRS re-sign where only the
        timestamp differs, the returned vote carries the LAST-SIGNED
        timestamp with the reused signature (file.go:339-341), so the
        signature always verifies over the returned vote's sign bytes."""

    @abc.abstractmethod
    def sign_proposal(self, chain_id: str, proposal):
        """Returns the signed proposal (same timestamp rule as votes)."""


@dataclass
class FilePVLastSignState:
    """privval/file.go:78-93."""

    height: int = 0
    round: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""
    file_path: str = ""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """file.go:95-137: error on regression; True iff exact same HRS
        with a signature already recorded (possible re-sign)."""
        if self.height > height:
            raise ValueError(f"height regression. Got {height}, last height {self.height}")
        if self.height == height:
            if self.round > round_:
                raise ValueError(
                    f"round regression at height {height}. Got {round_}, last round {self.round}"
                )
            if self.round == round_:
                if self.step > step:
                    raise ValueError(
                        f"step regression at height {height} round {round_}. "
                        f"Got {step}, last step {self.step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise ValueError("no sign_bytes found")
                    if not self.signature:
                        raise RuntimeError("signature is nil but sign_bytes is not")
                    return True
        return False

    def save(self) -> None:
        if not self.file_path:
            return
        obj = {
            "height": str(self.height),
            "round": self.round,
            "step": self.step,
            "signature": base64.b64encode(self.signature).decode() if self.signature else None,
            "signbytes": self.sign_bytes.hex().upper() if self.sign_bytes else None,
        }
        _atomic_write(self.file_path, json.dumps(obj, indent=2))

    @classmethod
    def load(cls, path: str) -> "FilePVLastSignState":
        with open(path) as fh:
            obj = json.load(fh)
        return cls(
            height=int(obj.get("height", "0")),
            round=int(obj.get("round", 0)),
            step=int(obj.get("step", 0)),
            signature=base64.b64decode(obj["signature"]) if obj.get("signature") else b"",
            sign_bytes=bytes.fromhex(obj["signbytes"]) if obj.get("signbytes") else b"",
            file_path=path,
        )


class FilePV(PrivValidator):
    """privval/file.go:139-420."""

    def __init__(self, priv_key: PrivKey, key_file_path: str = "", state_file_path: str = ""):
        self._priv_key = priv_key
        self._key_file = key_file_path
        self.last_sign_state = FilePVLastSignState(file_path=state_file_path)

    # -- generation / persistence ---------------------------------------

    @classmethod
    def generate(cls, key_file: str = "", state_file: str = "", seed: Optional[bytes] = None) -> "FilePV":
        return cls(ed25519.gen_priv_key(seed), key_file, state_file)

    @classmethod
    def load_or_generate(cls, key_file: str, state_file: str) -> "FilePV":
        if os.path.exists(key_file):
            return cls.load(key_file, state_file)
        pv = cls.generate(key_file, state_file)
        pv.save()
        return pv

    @classmethod
    def load(cls, key_file: str, state_file: str) -> "FilePV":
        with open(key_file) as fh:
            obj = json.load(fh)
        priv = ed25519.PrivKey(base64.b64decode(obj["priv_key"]["value"]))
        pv = cls(priv, key_file, state_file)
        if os.path.exists(state_file):
            pv.last_sign_state = FilePVLastSignState.load(state_file)
        return pv

    def save(self) -> None:
        pk = self._priv_key.pub_key()
        obj = {
            "address": pk.address().hex().upper(),
            "pub_key": {"type": ed25519.PUB_KEY_NAME, "value": base64.b64encode(pk.bytes()).decode()},
            "priv_key": {
                "type": ed25519.PRIV_KEY_NAME,
                "value": base64.b64encode(self._priv_key.bytes()).decode(),
            },
        }
        if self._key_file:
            _atomic_write(self._key_file, json.dumps(obj, indent=2))
        self.last_sign_state.save()

    # -- PrivValidator ----------------------------------------------------

    def get_pub_key(self) -> PubKey:
        return self._priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        """file.go:280-330 signVote with double-sign protection."""
        height, round_, step = vote.height, vote.round, vote_to_step(vote.type)
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                return replace(vote, signature=lss.signature)
            # Only the timestamp may differ: reuse the stored signature but
            # rewrite the vote's timestamp to the one the signature covers
            # (file.go:339-341) — otherwise the emitted vote would not
            # verify over its own sign bytes.
            if _only_timestamp_differs_vote(lss.sign_bytes, sign_bytes):
                ts = _extract_timestamp(lss.sign_bytes, 5)
                return replace(vote, timestamp=ts, signature=lss.signature)
            raise ValueError("conflicting data")
        sig = self._priv_key.sign(sign_bytes)
        self._save_signed(height, round_, step, sign_bytes, sig)
        return replace(vote, signature=sig)

    def sign_proposal(self, chain_id: str, proposal):
        """file.go:335-370."""
        height, round_ = proposal.height, proposal.round
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, STEP_PROPOSE)
        sign_bytes = proposal.sign_bytes(chain_id)
        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                return replace(proposal, signature=lss.signature)
            if _only_timestamp_differs_proposal(lss.sign_bytes, sign_bytes):
                ts = _extract_timestamp(lss.sign_bytes, 6)
                return replace(proposal, timestamp=ts, signature=lss.signature)
            raise ValueError("conflicting data")
        sig = self._priv_key.sign(sign_bytes)
        self._save_signed(height, round_, STEP_PROPOSE, sign_bytes, sig)
        return replace(proposal, signature=sig)

    def _save_signed(self, height: int, round_: int, step: int, sign_bytes: bytes, sig: bytes) -> None:
        lss = self.last_sign_state
        lss.height, lss.round, lss.step = height, round_, step
        lss.signature = sig
        lss.sign_bytes = sign_bytes
        lss.save()


def _strip_timestamp(sign_bytes: bytes, ts_field: int) -> bytes:
    """Remove the timestamp field from delimited canonical sign bytes so
    two encodings can be compared modulo timestamp (file.go
    checkVotesOnlyDifferByTimestamp)."""
    from ..wire.proto import ProtoWriter, encode_uvarint, unmarshal_delimited

    msg, _ = unmarshal_delimited(sign_bytes)
    fields = decode_message(msg)
    w = ProtoWriter()
    for num in sorted(fields):
        if num == ts_field:
            continue
        for wt, val in fields[num]:
            if wt == 0:
                w.write_varint(num, val, always=True)
            elif wt == 1:
                w.write_sfixed64(num, val, always=True)
            elif wt == 2:
                w.write_bytes(num, val, always=True)
    return w.bytes()


def _extract_timestamp(sign_bytes: bytes, ts_field: int) -> Timestamp:
    """Decode the canonical timestamp field from delimited sign bytes."""
    from ..wire.proto import unmarshal_delimited

    msg, _ = unmarshal_delimited(sign_bytes)
    fields = decode_message(msg)
    raw = field_bytes(fields, ts_field)
    if not raw:
        return Timestamp.zero()
    tf = decode_message(raw)

    def _i64(num: int) -> int:
        vals = tf.get(num)
        if not vals:
            return 0
        v = int(vals[-1][1])
        return v - (1 << 64) if v >= 1 << 63 else v

    return Timestamp(seconds=_i64(1), nanos=_i64(2))


def _only_timestamp_differs_vote(a: bytes, b: bytes) -> bool:
    return _strip_timestamp(a, 5) == _strip_timestamp(b, 5)


def _only_timestamp_differs_proposal(a: bytes, b: bytes) -> bool:
    return _strip_timestamp(a, 6) == _strip_timestamp(b, 6)


def _atomic_write(path: str, content: str) -> None:
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(content)
        os.replace(tmp, path)
    except BaseException:
        os.unlink(tmp)
        raise
