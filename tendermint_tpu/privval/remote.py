"""Remote signer — privval over a socket.

Reference parity: privval/signer_client.go + signer_listener_endpoint.go +
signer_dialer_endpoint.go and privval/grpc: the node listens (or dials),
the signer process holds the key and answers PubKey/SignVote/SignProposal
requests; privval/retry_signer_client.go wraps with retries.

Wire (privval/types.pb.go Message oneof, uvarint-delimited):
  1 pub_key_request{1 chain_id} | 2 pub_key_response{1 pub_key_bytes, 2 error}
  3 sign_vote_request{1 vote, 2 chain_id} | 4 signed_vote_response{1 vote, 2 error}
  5 sign_proposal_request{1 proposal, 2 chain_id}
  | 6 signed_proposal_response{1 proposal, 2 error} | 7 ping_request{} | 8 ping_response{}
The responses carry the FULL signed message (as the reference's
privval/types.pb.go SignedVoteResponse does) so the signer's
last-signed-timestamp rewrite survives the wire.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from ..crypto import PubKey, ed25519
from ..types.proposal import Proposal
from ..types.vote import Vote
from ..wire.proto import (
    ProtoWriter,
    decode_message,
    field_bytes,
    marshal_delimited,
    unmarshal_delimited,
)
from . import FilePV, PrivValidator


class RemoteSignerError(RuntimeError):
    pass


def _msg(kind: int, fields: dict) -> bytes:
    inner = ProtoWriter()
    for num, val in sorted(fields.items()):
        if isinstance(val, bytes):
            inner.write_bytes(num, val)
        elif isinstance(val, str):
            inner.write_string(num, val)
        else:
            inner.write_varint(num, val)
    w = ProtoWriter()
    w.write_message(kind, inner.bytes(), always=True)
    return marshal_delimited(w.bytes())


def _read_msg(sock: socket.socket, buf: bytes):
    while True:
        try:
            msg, consumed = unmarshal_delimited(buf)
            return msg, buf[consumed:]
        except ValueError:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("remote signer connection closed")
            buf += chunk


class SignerServer:
    """The signer process side (tools/tm-signer-harness subject): holds a
    FilePV and serves signing requests; dials the node's listen address
    (SignerDialerEndpoint pattern)."""

    def __init__(self, pv: FilePV, address: str):
        self._pv = pv
        self._address = address
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                host, _, port = self._address.replace("tcp://", "").rpartition(":")
                sock = socket.create_connection((host or "127.0.0.1", int(port)), timeout=5)
                sock.settimeout(1.0)
                self._serve(sock)
            except (OSError, ConnectionError):
                time.sleep(0.2)

    def _serve(self, sock: socket.socket) -> None:
        buf = b""
        while not self._stopped.is_set():
            try:
                msg, buf = _read_msg(sock, buf)
            except socket.timeout:
                continue
            f = decode_message(msg)
            if 1 in f:  # pub_key_request
                pk = self._pv.get_pub_key()
                sock.sendall(_msg(2, {1: pk.bytes()}))
            elif 3 in f:  # sign_vote_request
                r = decode_message(field_bytes(f, 3))
                vote = Vote.decode(field_bytes(r, 1))
                chain_id = field_bytes(r, 2).decode()
                try:
                    signed = self._pv.sign_vote(chain_id, vote)
                    sock.sendall(_msg(4, {1: signed.encode()}))
                except ValueError as e:
                    sock.sendall(_msg(4, {2: str(e)}))
            elif 5 in f:  # sign_proposal_request
                r = decode_message(field_bytes(f, 5))
                proposal = Proposal.decode(field_bytes(r, 1))
                chain_id = field_bytes(r, 2).decode()
                try:
                    signed = self._pv.sign_proposal(chain_id, proposal)
                    sock.sendall(_msg(6, {1: signed.encode()}))
                except ValueError as e:
                    sock.sendall(_msg(6, {2: str(e)}))
            elif 7 in f:  # ping
                sock.sendall(_msg(8, {}))


class SignerClient(PrivValidator):
    """The node side (SignerListenerEndpoint + SignerClient): listens for
    the signer's dial-in, then forwards signing requests."""

    def __init__(self, listen_addr: str, timeout: float = 10.0):
        host, _, port = listen_addr.replace("tcp://", "").rpartition(":")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host or "127.0.0.1", int(port)))
        self._listener.listen(1)
        h, p = self._listener.getsockname()
        self.listen_addr = f"tcp://{h}:{p}"
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._mtx = threading.Lock()

    def _ensure_conn(self) -> socket.socket:
        if self._sock is None:
            self._listener.settimeout(self._timeout)
            sock, _ = self._listener.accept()
            sock.settimeout(self._timeout)
            self._sock = sock
        return self._sock

    def _round_trip(self, request: bytes, want_field: int) -> bytes:
        with self._mtx:
            for attempt in range(2):
                sock = self._ensure_conn()
                try:
                    sock.sendall(request)
                    msg, self._buf = _read_msg(sock, self._buf)
                    break
                except (OSError, ConnectionError):
                    self._sock = None
                    self._buf = b""
                    if attempt == 1:
                        raise RemoteSignerError("remote signer unreachable")
        try:
            f = decode_message(msg)
            if want_field not in f:
                raise RemoteSignerError(f"unexpected response {list(f)}")
            r = decode_message(field_bytes(f, want_field))
            err = field_bytes(r, 2)
        except ValueError as e:
            # malformed frame: a TRANSPORT-class failure (retryable),
            # not a signer-reported refusal
            raise RemoteSignerError(f"undecodable response: {e}") from e
        if err:
            raise ValueError(err.decode())
        return field_bytes(r, 1)

    def get_pub_key(self) -> PubKey:
        raw = self._round_trip(_msg(1, {1: ""}), 2)
        return ed25519.PubKey(raw)

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        raw = self._round_trip(_msg(3, {1: vote.encode(), 2: chain_id}), 4)
        try:
            return Vote.decode(raw)
        except ValueError as e:
            raise RemoteSignerError(f"undecodable signed vote: {e}") from e

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        raw = self._round_trip(_msg(5, {1: proposal.encode(), 2: chain_id}), 6)
        try:
            return Proposal.decode(raw)
        except ValueError as e:
            raise RemoteSignerError(f"undecodable signed proposal: {e}") from e

    def ping(self) -> None:
        self._round_trip(_msg(7, {}), 8)

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
        self._listener.close()


class RetrySignerClient(PrivValidator):
    """privval/retry_signer_client.go: wraps SignerClient, retrying each
    operation (except ping) with a delay between attempts. retries=0
    retries indefinitely. Transport failures (RemoteSignerError / OSError)
    are retried; a signer-REPORTED error (ValueError — e.g. the remote
    double-sign guard refusing) is never retried."""

    def __init__(self, next_client: SignerClient, retries: int = 5, timeout: float = 1.0):
        self._next = next_client
        self._retries = retries
        self._timeout = timeout

    def _retry(self, fn):
        last: Exception = RemoteSignerError("no attempts made")
        i = 0
        while self._retries == 0 or i < self._retries:
            i += 1
            try:
                return fn()
            except ValueError:
                raise  # signer-reported: do not retry
            except (RemoteSignerError, OSError) as e:
                last = e
                if self._retries == 0 or i < self._retries:
                    time.sleep(self._timeout)  # only between attempts
        raise RemoteSignerError(f"exhausted all attempts: {last}") from last

    def get_pub_key(self) -> PubKey:
        return self._retry(self._next.get_pub_key)

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        return self._retry(lambda: self._next.sign_vote(chain_id, vote))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        return self._retry(lambda: self._next.sign_proposal(chain_id, proposal))

    def ping(self) -> None:
        self._next.ping()  # no retry, like the reference

    def close(self) -> None:
        self._next.close()
