"""Evidence pool — gathers, verifies, stores and gossips misbehavior proofs.

Reference parity: internal/evidence/ — Pool (pool.go:91-287): pending DB
with expiry pruning, committed markers, ABCI conversion at block
proposal; verify.go: DuplicateVoteEvidence (:202) checks both votes
against the historical validator set; LightClientAttackEvidence (:159)
uses VerifyCommitLightTrusting (the device batch path).
"""

from __future__ import annotations

import struct
import threading
import time
from typing import List, Optional, Tuple

from ..db import DB, MemDB
from ..types import Timestamp
from ..types.evidence import (
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
    decode_evidence,
    encode_evidence,
)
from ..types.validation import verify_commit_light_trusting, Fraction

_PREFIX_PENDING = b"\x00"
_PREFIX_COMMITTED = b"\x01"


def _key(prefix: bytes, height: int, ev_hash: bytes) -> bytes:
    return prefix + struct.pack(">q", height) + ev_hash


class EvidenceError(ValueError):
    pass


class Pool:
    """internal/evidence/pool.go:91-400."""

    def __init__(self, db: Optional[DB] = None, state_store=None, block_store=None):
        self._db = db or MemDB()
        self._state_store = state_store
        self._block_store = block_store
        self._mtx = threading.RLock()
        self._state = None  # latest State; set via update()
        self._broadcast_hooks: List = []  # evidence reactor attaches here

    def set_state(self, state) -> None:
        with self._mtx:
            self._state = state

    # -- adding ----------------------------------------------------------

    def add_evidence(self, ev) -> None:
        """pool.go:137-180 AddEvidence."""
        with self._mtx:
            if self._is_pending(ev) or self._is_committed(ev):
                return
            if self._state is not None:
                self.verify(ev)
            self._db.set(
                _key(_PREFIX_PENDING, ev.height(), ev.hash()), encode_evidence(ev)
            )
        for hook in self._broadcast_hooks:
            try:
                hook(ev)
            except Exception:  # noqa: BLE001
                pass

    def on_broadcast(self, hook) -> None:
        self._broadcast_hooks.append(hook)

    def _is_pending(self, ev) -> bool:
        return self._db.has(_key(_PREFIX_PENDING, ev.height(), ev.hash()))

    def _is_committed(self, ev) -> bool:
        return self._db.has(_key(_PREFIX_COMMITTED, ev.height(), ev.hash()))

    # -- verification (verify.go) ----------------------------------------

    def verify(self, ev) -> None:
        """verify.go:24-100 verify: age window + type-specific checks."""
        state = self._state
        if state is None:
            raise EvidenceError("evidence pool has no state")
        height = state.last_block_height
        ev_params = state.consensus_params.evidence
        age_num_blocks = height - ev.height()
        # internal/evidence/verify.go:48: evidence expires only when BOTH
        # the duration bound and the block-count bound are exceeded.
        lbt, evt = state.last_block_time, ev.time()
        age_duration_ns = (lbt.seconds - evt.seconds) * 10**9 + (
            lbt.nanos - evt.nanos
        )
        if (
            age_duration_ns > ev_params.max_age_duration_ns
            and age_num_blocks > ev_params.max_age_num_blocks
        ):
            raise EvidenceError(
                f"evidence from height {ev.height()} is too old; "
                f"min height is {height - ev_params.max_age_num_blocks} "
                f"(age {age_duration_ns}ns > {ev_params.max_age_duration_ns}ns)"
            )
        if isinstance(ev, DuplicateVoteEvidence):
            self._verify_duplicate_vote(ev, state)
        elif isinstance(ev, LightClientAttackEvidence):
            self._verify_light_client_attack(ev, state)
        else:
            raise EvidenceError(f"unrecognized evidence type {type(ev)}")

    def _verify_duplicate_vote(self, ev: DuplicateVoteEvidence, state) -> None:
        """verify.go:202-280 VerifyDuplicateVote."""
        a, b = ev.vote_a, ev.vote_b
        if a.height != b.height or a.round != b.round or a.type != b.type:
            raise EvidenceError("votes are for different height/round/type")
        if a.block_id == b.block_id:
            raise EvidenceError("block IDs are the same — not a duplicate vote")
        if a.validator_address != b.validator_address:
            raise EvidenceError(
                f"validator addresses do not match: "
                f"{a.validator_address.hex()} vs {b.validator_address.hex()}"
            )
        if self._state_store is not None:
            try:
                val_set = self._state_store.load_validators(a.height)
            except KeyError as e:
                raise EvidenceError(str(e)) from e
            _, val = val_set.get_by_address(a.validator_address)
            if val is None:
                raise EvidenceError(
                    f"address {a.validator_address.hex()} was not a validator at height {a.height}"
                )
            if ev.validator_power != val.voting_power:
                raise EvidenceError("validator power mismatch")
            if ev.total_voting_power != val_set.total_voting_power():
                raise EvidenceError("total voting power mismatch")
            chain_id = self._state.chain_id
            a.verify(chain_id, val.pub_key)
            b.verify(chain_id, val.pub_key)

    def _verify_light_client_attack(self, ev: LightClientAttackEvidence, state) -> None:
        """verify.go:159-200: common validators must satisfy 1/3 trust on
        the conflicting commit (device batch path)."""
        if self._state_store is None:
            return
        try:
            common_vals = self._state_store.load_validators(ev.common_height)
        except KeyError as e:
            raise EvidenceError(str(e)) from e
        commit = ev.conflicting_block.commit()
        verify_commit_light_trusting(
            self._state.chain_id, common_vals, commit, Fraction(1, 3)
        )
        if ev.total_voting_power != common_vals.total_voting_power():
            raise EvidenceError("total voting power mismatch")

    # -- for block production (pool.go PendingEvidence) -------------------

    def pending_evidence(self, max_bytes: int) -> List:
        out, _ = self._pending(max_bytes)
        return out

    def pending_evidence_bytes(self, max_bytes: int) -> List[bytes]:
        _, raws = self._pending(max_bytes)
        return raws

    def _pending(self, max_bytes: int) -> Tuple[List, List[bytes]]:
        evs, raws, total = [], [], 0
        for _, raw in self._db.iterator(_PREFIX_PENDING, _PREFIX_COMMITTED):
            if max_bytes >= 0 and total + len(raw) > max_bytes:
                break
            total += len(raw)
            evs.append(decode_evidence(raw))
            raws.append(raw)
        return evs, raws

    # -- post-commit (pool.go Update:220-287) -----------------------------

    def update(self, state, block_evidence: List[bytes]) -> None:
        with self._mtx:
            self._state = state
            for raw in block_evidence:
                ev = decode_evidence(raw)
                self._db.set(
                    _key(_PREFIX_COMMITTED, ev.height(), ev.hash()), b"\x01"
                )
                self._db.delete(_key(_PREFIX_PENDING, ev.height(), ev.hash()))
            self._prune_expired(state)

    def check_evidence(self, state, block_evidence: List[bytes]) -> None:
        """pool.go CheckEvidence: verify all evidence in a proposed block."""
        with self._mtx:
            prev = self._state
            self._state = state
            try:
                seen = set()
                for raw in block_evidence:
                    ev = decode_evidence(raw)
                    h = ev.hash()
                    if h in seen:
                        raise EvidenceError("duplicate evidence in block")
                    seen.add(h)
                    # pool.go:210-212: a block may not carry evidence that
                    # was already committed — otherwise a byzantine proposer
                    # could replay the same evidence every block and trigger
                    # repeated slashing of the same offense.
                    if self._is_committed(ev):
                        raise EvidenceError(
                            f"evidence {h.hex()} was already committed"
                        )
                    self.verify(ev)
            finally:
                self._state = prev if prev is not None else state

    def _prune_expired(self, state) -> None:
        params = state.consensus_params.evidence
        min_height = state.last_block_height - params.max_age_num_blocks
        for k, _ in list(self._db.iterator(_PREFIX_PENDING, _PREFIX_COMMITTED)):
            height = struct.unpack(">q", k[1:9])[0]
            if height < min_height:
                self._db.delete(k)
