"""Evidence reactor — gossips evidence to peers.

Reference parity: internal/evidence/reactor.go — channel 0x38,
EvidenceList message; on receive, evidence is verified by the pool and
relayed if fresh.
"""

from __future__ import annotations

import queue
import threading
from typing import Set

from ..p2p.conn.mconnection import ChannelDescriptor
from ..p2p.router import Router
from ..types.evidence import decode_evidence, encode_evidence
from ..wire.proto import ProtoWriter, decode_message
from . import EvidenceError, Pool

EVIDENCE_CHANNEL = 0x38
EVIDENCE_DESC = ChannelDescriptor(
    id=EVIDENCE_CHANNEL, priority=6, recv_message_capacity=1024 * 1024
)


def encode_evidence_list(evs) -> bytes:
    w = ProtoWriter()
    for ev in evs:
        w.write_message(1, encode_evidence(ev), always=True)
    return w.bytes()


def decode_evidence_list(data: bytes):
    f = decode_message(data)
    from ..wire.proto import field_repeated_bytes
    return [decode_evidence(raw) for raw in field_repeated_bytes(f, 1)]


class EvidenceReactor:
    def __init__(self, pool: Pool, router: Router):
        self._pool = pool
        self._router = router
        self._ch = router.open_channel(EVIDENCE_DESC)
        self._stopped = threading.Event()
        self._seen: Set[bytes] = set()
        pool.on_broadcast(self._broadcast_evidence)

    def start(self) -> None:
        t = threading.Thread(target=self._recv_loop, daemon=True)
        t.start()

    def stop(self) -> None:
        self._stopped.set()

    def _broadcast_evidence(self, ev) -> None:
        self._ch.broadcast(encode_evidence_list([ev]))

    def _recv_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                env = self._ch.receive(timeout=0.5)
            except queue.Empty:
                continue
            try:
                evs = decode_evidence_list(env.message)
            except (ValueError, KeyError):
                continue
            for ev in evs:
                h = ev.hash()
                if h in self._seen:
                    continue
                self._seen.add(h)
                try:
                    self._pool.add_evidence(ev)
                except (EvidenceError, ValueError):
                    continue
