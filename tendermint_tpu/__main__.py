"""python -m tendermint_tpu — the CLI entry point (cmd/tendermint)."""

import sys

from .cli import main

sys.exit(main())
