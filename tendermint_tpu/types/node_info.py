"""NodeInfo — the identity/version record peers exchange at handshake.

Reference parity: types/node_info.go — NodeInfo with protocol versions,
node id, listen addr, network (chain id), channels, moniker; compatibility
check on block protocol + network match (node_info.go CompatibleWith).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from ..version import BLOCK_PROTOCOL, P2P_PROTOCOL, TM_VERSION
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int

MAX_NODE_INFO_SIZE = 10240  # node_info.go:15


class IncompatiblePeerError(ValueError):
    pass


@dataclass
class NodeInfo:
    """node_info.go:30-60 (proto: p2p/types.pb.go NodeInfo)."""

    p2p_version: int = P2P_PROTOCOL
    block_version: int = BLOCK_PROTOCOL
    app_version: int = 0
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""
    version: str = TM_VERSION
    channels: bytes = b""
    moniker: str = ""

    def validate_basic(self) -> None:
        """node_info.go Validate."""
        if not self.node_id:
            raise ValueError("no node ID")
        if len(self.channels) > 16:
            raise ValueError("too many channels")
        if len(self.moniker) > 64:
            raise ValueError("moniker too long")

    def compatible_with(self, other: "NodeInfo") -> None:
        """node_info.go CompatibleWith: block protocol + network + at least
        one common channel."""
        if self.block_version != other.block_version:
            raise IncompatiblePeerError(
                f"peer is on a different Block version: {other.block_version} != {self.block_version}"
            )
        if self.network != other.network:
            raise IncompatiblePeerError(
                f"peer is on a different network: {other.network!r} != {self.network!r}"
            )
        if self.channels and other.channels:
            if not set(self.channels) & set(other.channels):
                raise IncompatiblePeerError("no common channels")

    def encode(self) -> bytes:
        w = ProtoWriter()
        ver = ProtoWriter()
        ver.write_varint(1, self.p2p_version)
        ver.write_varint(2, self.block_version)
        ver.write_varint(3, self.app_version)
        w.write_message(1, ver.bytes(), always=True)
        w.write_string(2, self.node_id)
        w.write_string(3, self.listen_addr)
        w.write_string(4, self.network)
        w.write_string(5, self.version)
        w.write_bytes(6, self.channels)
        w.write_string(7, self.moniker)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "NodeInfo":
        if len(data) > MAX_NODE_INFO_SIZE:
            raise ValueError("node info too large")
        f = decode_message(data)
        ver = decode_message(field_bytes(f, 1))
        return cls(
            p2p_version=field_int(ver, 1),
            block_version=field_int(ver, 2),
            app_version=field_int(ver, 3),
            node_id=field_bytes(f, 2).decode(),
            listen_addr=field_bytes(f, 3).decode(),
            network=field_bytes(f, 4).decode(),
            version=field_bytes(f, 5).decode(),
            channels=field_bytes(f, 6),
            moniker=field_bytes(f, 7).decode(),
        )
