"""PartSet — block chunking for gossip (64kB parts + merkle proofs).

Reference parity: types/part_set.go. A block is proto-encoded then split
into BlockPartSizeBytes chunks; each Part carries a merkle proof against
the PartSetHeader hash so peers can verify parts independently.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto import merkle, tmhash
from ..libs.bits import BitArray
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int
from .block import PartSetHeader

BLOCK_PART_SIZE_BYTES = 65536  # types/params.go BlockPartSizeBytes
MAX_PARTS_COUNT = 1601  # 100MB / 64kB + 1 (types/part_set.go:23)


@dataclass(frozen=True)
class Part:
    index: int
    bytes: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        """part_set.go:48-62."""
        if len(self.bytes) > BLOCK_PART_SIZE_BYTES:
            raise ValueError(f"part too big: {len(self.bytes)} > {BLOCK_PART_SIZE_BYTES}")
        if (
            self.proof.leaf_hash != merkle.leaf_hash(self.bytes)
            or len(self.proof.leaf_hash) != tmhash.SIZE
        ):
            raise ValueError("wrong leaf hash in part proof")

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.index)
        w.write_bytes(2, self.bytes)
        w.write_message(3, self.proof.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Part":
        f = decode_message(data)
        return cls(
            index=field_int(f, 1),
            bytes=field_bytes(f, 2),
            proof=merkle.Proof.decode(field_bytes(f, 3)),
        )


class PartSet:
    """part_set.go:150-400."""

    def __init__(
        self,
        header: PartSetHeader,
        parts: List[Optional[Part]],
        parts_bit_array: BitArray,
        count: int,
        byte_size: int,
    ):
        self._header = header
        self._parts = parts
        self._bit_array = parts_bit_array
        self._count = count
        self._byte_size = byte_size
        self._mtx = threading.Lock()

    # -- constructors ---------------------------------------------------

    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES) -> "PartSet":
        """NewPartSetFromData (part_set.go:158-189): chunk + build proofs."""
        total = (len(data) + part_size - 1) // part_size
        if total == 0:
            total = 1
        chunks = [data[i * part_size : (i + 1) * part_size] for i in range(total)]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        parts: List[Optional[Part]] = [
            Part(index=i, bytes=chunks[i], proof=proofs[i]) for i in range(total)
        ]
        ba = BitArray(total)
        for i in range(total):
            ba.set_index(i, True)
        return cls(
            header=PartSetHeader(total=total, hash=root),
            parts=parts,
            parts_bit_array=ba,
            count=total,
            byte_size=len(data),
        )

    @classmethod
    def new_from_header(cls, header: PartSetHeader) -> "PartSet":
        return cls(
            header=header,
            parts=[None] * header.total,
            parts_bit_array=BitArray(header.total),
            count=0,
            byte_size=0,
        )

    # -- accessors ------------------------------------------------------

    def header(self) -> PartSetHeader:
        return self._header

    def has_header(self, header: PartSetHeader) -> bool:
        return self._header == header

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self._bit_array.copy()

    def hash(self) -> bytes:
        return self._header.hash

    def total(self) -> int:
        return self._header.total

    def count(self) -> int:
        return self._count

    def byte_size(self) -> int:
        return self._byte_size

    def is_complete(self) -> bool:
        return self._count == self._header.total

    def get_part(self, index: int) -> Optional[Part]:
        with self._mtx:
            if index >= len(self._parts):
                return None
            return self._parts[index]

    # -- assembly -------------------------------------------------------

    def add_part(self, part: Optional[Part]) -> bool:
        """part_set.go:260-292: False for duplicates; raises for invalid."""
        if part is None:
            raise ValueError("nil part")
        with self._mtx:
            if part.index >= self._header.total:
                raise ValueError("unexpected part index")
            if self._parts[part.index] is not None:
                return False
            # Check hash proof against the part set root.
            part.validate_basic()
            part.proof.verify(self._header.hash, part.bytes)
            self._parts[part.index] = part
            self._bit_array.set_index(part.index, True)
            self._count += 1
            self._byte_size += len(part.bytes)
            return True

    def assemble(self) -> bytes:
        """Reader equivalent: concatenated part bytes (must be complete)."""
        if not self.is_complete():
            raise ValueError("part set is not complete")
        return b"".join(p.bytes for p in self._parts)  # type: ignore[union-attr]
