"""ABCI deliver-tx results hashing (the header's last_results_hash).

Reference parity: types/results.go — ABCIResults.Hash() is the merkle root
over *deterministic* proto encodings of each ResponseDeliverTx, where
deterministic means only {Code, Data} are kept
(types/results.go:41-48 deterministicResponseDeliverTx).
"""

from __future__ import annotations

from typing import List, Sequence

from ..crypto import merkle
from ..wire.proto import ProtoWriter


def deterministic_response_deliver_tx(code: int, data: bytes) -> bytes:
    """ResponseDeliverTx{1 code, 2 data} subset encoding."""
    w = ProtoWriter()
    w.write_varint(1, code)
    w.write_bytes(2, data)
    return w.bytes()


def results_hash(results: Sequence[tuple]) -> bytes:
    """results: iterable of (code, data) pairs from DeliverTx responses."""
    return merkle.hash_from_byte_slices(
        [deterministic_response_deliver_tx(c, d) for c, d in results]
    )
