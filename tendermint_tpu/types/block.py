"""Block, Header, Commit, CommitSig, BlockID — core chain data types.

Reference parity: types/block.go. Hashing is bit-exact:
- Header.hash: merkle root over 14 proto-encoded fields (block.go:448-483)
- Commit.hash: merkle root over proto-encoded CommitSigs (block.go:732-751)
- Data.hash: merkle root over raw txs (types/tx.go Txs.Hash)
- cdcEncode wrappers (types/encoding_helper.go): gogotypes
  {String,Int64,Bytes}Value with the value in field 1; empty -> nil leaf.
"""

from __future__ import annotations

from collections.abc import MutableSequence
from dataclasses import dataclass, field, replace
from typing import List, Optional

from ..crypto import merkle, tmhash
from ..wire import canonical as _canon
from ..wire.canonical import GO_ZERO_TIME_SECONDS, Timestamp
from ..wire.proto import (
    WT_BYTES,
    WT_VARINT,
    ProtoWriter,
    decode_message,
    field_bytes,
    field_int,
    field_repeated_bytes,
    iter_fields,
    to_signed32,
    to_signed64,
)

MAX_HEADER_BYTES = 626  # types/block.go:570
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3

MAX_SIGNATURE_SIZE = 64  # ed25519/sr25519; secp256k1 is <= 72 (types/vote.go:24)


def cdc_encode_string(s: str) -> bytes:
    if not s:
        return b""
    w = ProtoWriter()
    w.write_string(1, s)
    return w.bytes()


def cdc_encode_int64(v: int) -> bytes:
    if not v:
        return b""
    w = ProtoWriter()
    w.write_varint(1, v)
    return w.bytes()


def cdc_encode_bytes(b: bytes) -> bytes:
    if not b:
        return b""
    w = ProtoWriter()
    w.write_bytes(1, b)
    return w.bytes()


@dataclass(frozen=True)
class Version:
    """Consensus version (proto/tendermint/version, version/version.go)."""

    block: int = 11  # version.BlockProtocol (version/version.go:25)
    app: int = 0

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.block)
        w.write_varint(2, self.app)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Version":
        f = decode_message(data)
        return cls(block=field_int(f, 1), app=field_int(f, 2))


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.total)
        w.write_bytes(2, self.hash)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "PartSetHeader":
        f = decode_message(data)
        return cls(total=field_int(f, 1), hash=field_bytes(f, 2))

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("wrong PartSetHeader hash size")


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        return not self.hash and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        """ValidateBasic-completeness (types/block.go:1153): hash and part
        set header both fully set."""
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == tmhash.SIZE
        )

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_bytes(1, self.hash)
        w.write_message(2, self.part_set_header.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "BlockID":
        f = decode_message(data)
        return cls(
            hash=field_bytes(f, 1),
            part_set_header=PartSetHeader.decode(field_bytes(f, 2)),
        )

    def canonical(self) -> Optional[_canon.CanonicalBlockID]:
        """types/canonical.go CanonicalizeBlockID: nil for the zero ID."""
        if self.is_zero():
            return None
        return _canon.CanonicalBlockID(
            hash=self.hash,
            part_set_header=_canon.CanonicalPartSetHeader(
                total=self.part_set_header.total,
                hash=self.part_set_header.hash,
            ),
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError("wrong BlockID hash size")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key (types/block.go BlockID.Key)."""
        return self.hash + self.part_set_header.encode()


ZERO_BLOCK_ID = BlockID()


@dataclass(frozen=True)
class Header:
    """types/block.go:370-412."""

    version: Version = field(default_factory=Version)
    chain_id: str = ""
    height: int = 0
    time: Timestamp = field(default_factory=Timestamp.zero)
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""
    # memoized merkle root: the class is FROZEN so the 14-leaf tree can
    # never change under a live instance, and init=False makes
    # dataclasses.replace() re-default the memo to None (a forged-header
    # copy must never inherit the original's hash). compare=False keeps
    # __eq__/__hash__ on the real fields.
    _hash_memo: Optional[bytes] = field(
        default=None, init=False, repr=False, compare=False
    )

    def hash(self) -> bytes:
        """Merkle root of proto-encoded fields (types/block.go:448-483).
        Returns b"" when the header is incomplete (nil in Go)."""
        if not self.validators_hash:
            return b""
        h = self._hash_memo
        if h is not None:
            return h
        h = merkle.hash_from_byte_slices(
            [
                self.version.encode(),
                cdc_encode_string(self.chain_id),
                cdc_encode_int64(self.height),
                _canon.encode_timestamp(self.time),
                self.last_block_id.encode(),
                cdc_encode_bytes(self.last_commit_hash),
                cdc_encode_bytes(self.data_hash),
                cdc_encode_bytes(self.validators_hash),
                cdc_encode_bytes(self.next_validators_hash),
                cdc_encode_bytes(self.consensus_hash),
                cdc_encode_bytes(self.app_hash),
                cdc_encode_bytes(self.last_results_hash),
                cdc_encode_bytes(self.evidence_hash),
                cdc_encode_bytes(self.proposer_address),
            ]
        )
        object.__setattr__(self, "_hash_memo", h)
        return h

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_message(1, self.version.encode(), always=True)
        w.write_string(2, self.chain_id)
        w.write_varint(3, self.height)
        w.write_message(4, _canon.encode_timestamp(self.time), always=True)
        w.write_message(5, self.last_block_id.encode(), always=True)
        w.write_bytes(6, self.last_commit_hash)
        w.write_bytes(7, self.data_hash)
        w.write_bytes(8, self.validators_hash)
        w.write_bytes(9, self.next_validators_hash)
        w.write_bytes(10, self.consensus_hash)
        w.write_bytes(11, self.app_hash)
        w.write_bytes(12, self.last_results_hash)
        w.write_bytes(13, self.evidence_hash)
        w.write_bytes(14, self.proposer_address)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Header":
        f = decode_message(data)
        ts_f = decode_message(field_bytes(f, 4))
        return cls(
            version=Version.decode(field_bytes(f, 1)),
            chain_id=field_bytes(f, 2).decode("utf-8"),
            height=to_signed64(field_int(f, 3)),
            time=Timestamp(
                seconds=to_signed64(field_int(ts_f, 1)),
                nanos=to_signed32(field_int(ts_f, 2)),
            ),
            last_block_id=BlockID.decode(field_bytes(f, 5)),
            last_commit_hash=field_bytes(f, 6),
            data_hash=field_bytes(f, 7),
            validators_hash=field_bytes(f, 8),
            next_validators_hash=field_bytes(f, 9),
            consensus_hash=field_bytes(f, 10),
            app_hash=field_bytes(f, 11),
            last_results_hash=field_bytes(f, 12),
            evidence_hash=field_bytes(f, 13),
            proposer_address=field_bytes(f, 14),
        )

    def validate_basic(self) -> None:
        """types/block.go:413-446."""
        if len(self.chain_id) > 50:
            raise ValueError("chain_id is too long")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.height == 0:
            raise ValueError("zero Height")
        self.last_block_id.validate_basic()
        for name, h in (
            ("last_commit_hash", self.last_commit_hash),
            ("data_hash", self.data_hash),
            ("evidence_hash", self.evidence_hash),
            ("last_results_hash", self.last_results_hash),
            ("validators_hash", self.validators_hash),
            ("next_validators_hash", self.next_validators_hash),
            ("consensus_hash", self.consensus_hash),
        ):
            if h and len(h) != tmhash.SIZE:
                raise ValueError(f"wrong {name} size")
        if self.proposer_address and len(self.proposer_address) != tmhash.TRUNCATED_SIZE:
            raise ValueError("invalid proposer_address size")


@dataclass(frozen=True)
class CommitSig:
    """types/block.go:590-700."""

    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp: Timestamp = field(default_factory=Timestamp.zero)
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls(block_id_flag=BLOCK_ID_FLAG_ABSENT)

    def for_block(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def is_absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The vote's BlockID implied by the flag (types/block.go:685-700)."""
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            return BlockID()
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        if self.block_id_flag == BLOCK_ID_FLAG_NIL:
            return BlockID()
        raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.block_id_flag)
        w.write_bytes(2, self.validator_address)
        w.write_message(3, _canon.encode_timestamp(self.timestamp), always=True)
        w.write_bytes(4, self.signature)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "CommitSig":
        f = decode_message(data)
        ts_f = decode_message(field_bytes(f, 3))
        return cls(
            block_id_flag=field_int(f, 1),
            validator_address=field_bytes(f, 2),
            timestamp=Timestamp(
                seconds=to_signed64(field_int(ts_f, 1)),
                nanos=to_signed32(field_int(ts_f, 2)),
            ),
            signature=field_bytes(f, 4),
        )

    def validate_basic(self) -> None:
        """types/block.go:702-741."""
        if self.block_id_flag not in (
            BLOCK_ID_FLAG_ABSENT,
            BLOCK_ID_FLAG_COMMIT,
            BLOCK_ID_FLAG_NIL,
        ):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.block_id_flag == BLOCK_ID_FLAG_ABSENT:
            if self.validator_address:
                raise ValueError("validator address is present for absent CommitSig")
            if not self.timestamp.is_zero():
                raise ValueError("time is present for absent CommitSig")
            if self.signature:
                raise ValueError("signature is present for absent CommitSig")
        else:
            if len(self.validator_address) != tmhash.TRUNCATED_SIZE:
                raise ValueError("expected ValidatorAddress size")
            if not self.signature:
                raise ValueError("signature is missing")
            if len(self.signature) > MAX_SIGNATURE_SIZE:
                raise ValueError("signature is too big")


class CommitSigs(MutableSequence):
    """`commit.signatures` backed by a columnar CommitBlock
    (ops/entry_block.py): the columns are the source of truth from wire
    decode onward, and CommitSig OBJECTS are materialized lazily, one per
    accessed index, as views over them. The verify hot path
    (types/validation.py fused branch) reads the columns directly and
    never triggers materialization.

    Mutation (setitem/delitem/insert) first materializes every lane into
    a plain object list and DETACHES the columns — the mutated list is
    then the truth and the owning Commit rebuilds its block on demand —
    so list semantics (including the tests' in-place signature tampering)
    are preserved exactly."""

    __slots__ = ("_block", "_items")

    def __init__(self, block):
        self._block = block
        self._items: list = [None] * len(block)

    # -- lazy view ------------------------------------------------------

    def _materialize(self, i: int) -> CommitSig:
        cs = self._items[i]
        if cs is None:
            b = self._block
            flag = int(b.flags[i])
            if flag == BLOCK_ID_FLAG_ABSENT:
                cs = CommitSig(block_id_flag=flag)
            else:
                cs = CommitSig(
                    block_id_flag=flag,
                    validator_address=b.addr[i].tobytes(),
                    timestamp=Timestamp(
                        int(b.ts_seconds[i]), int(b.ts_nanos[i])
                    ),
                    signature=b.sig[i].tobytes(),
                )
            self._items[i] = cs
        return cs

    def _detach(self) -> None:
        """Materialize everything and drop the columns (mutation path)."""
        if self._block is None:
            return
        for i in range(len(self._items)):
            self._materialize(i)
        self._block = None

    def block(self):
        """The backing CommitBlock, or None once mutated."""
        return self._block

    # -- sequence protocol ---------------------------------------------

    def __len__(self) -> int:
        return len(self._items)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [
                self._materialize(j)
                for j in range(*i.indices(len(self._items)))
            ]
        if self._items[i] is None:  # also validates the index
            if i < 0:
                i += len(self._items)
            return self._materialize(i)
        return self._items[i]

    def __setitem__(self, i, value) -> None:
        self._detach()
        self._items[i] = value

    def __delitem__(self, i) -> None:
        self._detach()
        del self._items[i]

    def insert(self, i, value) -> None:
        self._detach()
        self._items.insert(i, value)

    def __eq__(self, other) -> bool:
        if isinstance(other, CommitSigs):
            return list(self) == list(other)
        if isinstance(other, list):
            return list(self) == other
        return NotImplemented

    def __repr__(self) -> str:
        return repr(list(self))


def _commit_sig_columns(sigs) -> Optional[object]:
    """Build a CommitBlock from CommitSig objects — the path for commits
    assembled in-process (consensus MakeCommit, tests). Returns None when
    any lane deviates from the canonical shape (wrong-size address or
    signature, unknown flag, absent lane with data): those commits keep
    the object path and its exact error behavior."""
    import numpy as np

    from ..ops.entry_block import CommitBlock

    n = len(sigs)
    if n == 0:
        return None
    flags = []
    sig_chunks = []
    addr_chunks = []
    secs = []
    nanos = []
    for cs in sigs:
        f = cs.block_id_flag
        if f == BLOCK_ID_FLAG_ABSENT:
            if (
                cs.validator_address
                or cs.signature
                or not cs.timestamp.is_zero()
            ):
                return None
            sig_chunks.append(_ZERO64)
            addr_chunks.append(_ZERO20)
        elif f in (BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL):
            if len(cs.validator_address) != 20 or len(cs.signature) != 64:
                return None
            sig_chunks.append(cs.signature)
            addr_chunks.append(cs.validator_address)
        else:
            return None
        flags.append(f)
        secs.append(cs.timestamp.seconds)
        nanos.append(cs.timestamp.nanos)
    return CommitBlock(
        flags=np.array(flags, dtype=np.uint8),
        val_idx=np.arange(n, dtype=np.int32),
        sig=np.frombuffer(b"".join(sig_chunks), dtype=np.uint8).reshape(
            n, 64
        ),
        ts_seconds=np.array(secs, dtype=np.int64),
        ts_nanos=np.array(nanos, dtype=np.int32),
        addr=np.frombuffer(b"".join(addr_chunks), dtype=np.uint8).reshape(
            n, 20
        ),
    )


_ZERO64 = bytes(64)
_ZERO20 = bytes(20)

_KNOWN_FLAGS = (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL)


class _NonCanonical(Exception):
    """Wire record deviates from the canonical CommitSig shape."""


def _decode_sig_record(raw: bytes):
    """One CommitSig wire record -> (flag, addr, secs, nanos, sig),
    canonical-shape-checked. Raises _NonCanonical on ANY deviation
    (unknown/duplicate fields, wrong wire types, non-canonical lane
    shape) — the caller falls back to CommitSig.decode per record, which
    reproduces the object path's exact tolerance and errors."""
    flag = 0
    addr = b""
    sig = b""
    ts_raw = None
    seen = 0
    for f, wt, val in iter_fields(raw):
        bit = 1 << f
        if seen & bit:
            raise _NonCanonical
        seen |= bit
        if f == 1 and wt == WT_VARINT:
            flag = val
        elif f == 2 and wt == WT_BYTES:
            addr = val
        elif f == 3 and wt == WT_BYTES:
            ts_raw = val
        elif f == 4 and wt == WT_BYTES:
            sig = val
        else:
            raise _NonCanonical
    secs = 0
    nanos = 0
    if ts_raw is not None:
        seen_ts = 0
        for f, wt, val in iter_fields(ts_raw):
            if wt != WT_VARINT or f not in (1, 2) or seen_ts & (1 << f):
                raise _NonCanonical
            seen_ts |= 1 << f
            if f == 1:
                secs = to_signed64(val)
            else:
                nanos = to_signed32(val)
    if flag == BLOCK_ID_FLAG_ABSENT:
        if addr or sig or secs != GO_ZERO_TIME_SECONDS or nanos != 0:
            raise _NonCanonical
    elif flag in (BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL):
        if len(addr) != 20 or len(sig) != 64:
            raise _NonCanonical
    else:
        raise _NonCanonical
    return flag, addr, secs, nanos, sig


def _decode_commit_sigs(raws: List[bytes]):
    """Decode a commit's signature records COLUMNAR-FIRST: one pass fills
    CommitBlock columns and the result is a lazy CommitSigs view. Any
    non-canonical record falls the whole commit back to plain CommitSig
    objects (identical to the pre-columnar decode)."""
    n = len(raws)
    if n == 0:
        return []
    try:
        rows = [_decode_sig_record(raw) for raw in raws]
    except (_NonCanonical, ValueError):
        return [CommitSig.decode(raw) for raw in raws]
    import numpy as np

    from ..ops.entry_block import CommitBlock

    block = CommitBlock(
        flags=np.fromiter((r[0] for r in rows), dtype=np.uint8, count=n),
        val_idx=np.arange(n, dtype=np.int32),
        sig=np.frombuffer(
            b"".join(r[4] or _ZERO64 for r in rows), dtype=np.uint8
        ).reshape(n, 64),
        ts_seconds=np.fromiter(
            (r[2] for r in rows), dtype=np.int64, count=n
        ),
        ts_nanos=np.fromiter((r[3] for r in rows), dtype=np.int32, count=n),
        addr=np.frombuffer(
            b"".join(r[1] or _ZERO20 for r in rows), dtype=np.uint8
        ).reshape(n, 20),
    )
    return CommitSigs(block)


@dataclass
class Commit:
    """types/block.go:744-830."""

    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    signatures: List[CommitSig] = field(default_factory=list)

    _hash: Optional[bytes] = field(default=None, repr=False, compare=False)
    _sb_tpl: Optional[dict] = field(default=None, repr=False, compare=False)

    def __setattr__(self, name, value):
        # reassigning `signatures` invalidates the signature-dependent
        # hash — the tests' wholesale `commit.signatures = [...]`
        # replacement stays correct
        object.__setattr__(self, name, value)
        if name == "signatures":
            object.__setattr__(self, "_hash", None)

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.encode() for cs in self.signatures]
            )
        return self._hash

    def size(self) -> int:
        return len(self.signatures)

    def sign_bytes_template(self, chain_id: str, flag: int) -> tuple:
        """(prefix, suffix) canonical-vote template for a BlockIDFlag —
        only the timestamp differs across a commit's signatures for a
        given flag, so the constant fields are encoded once per
        (chain_id, flag) and reused. The columnar fused prep
        (ops/commit_prep.py) composes every lane's sign bytes from these
        templates plus the timestamp columns."""
        if self._sb_tpl is None:
            self._sb_tpl = {}
        key = (chain_id, flag)
        tpl = self._sb_tpl.get(key)
        if tpl is None:
            # the vote's BlockID implied by the flag (CommitSig.block_id):
            # the commit's for COMMIT, the zero BlockID for ABSENT/NIL
            if flag == BLOCK_ID_FLAG_COMMIT:
                bid = self.block_id
            elif flag in (BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_NIL):
                bid = BlockID()
            else:
                raise ValueError(f"unknown BlockIDFlag: {flag}")
            tpl = _canon.canonical_vote_template(
                chain_id=chain_id,
                msg_type=_canon.SIGNED_MSG_TYPE_PRECOMMIT,
                height=self.height,
                round_=self.round,
                block_id=bid.canonical(),
            )
            self._sb_tpl[key] = tpl
        return tpl

    def vote_sign_bytes(self, chain_id: str, idx: int) -> bytes:
        """Canonical sign bytes of the vote at idx (types/block.go:816-819).

        Only the timestamp differs across a commit's signatures (for a
        given BlockIDFlag), so the constant fields are encoded once per
        (chain_id, flag) and reused — the 10k-signature batch path walks
        this for every lane."""
        cs = self.signatures[idx]
        tpl = self.sign_bytes_template(chain_id, cs.block_id_flag)
        return _canon.compose_vote_sign_bytes(tpl, cs.timestamp)

    def commit_block(self):
        """The commit's columnar CommitBlock (ops/entry_block.py), or
        None when the signatures deviate from the canonical shape.

        Wire-decoded commits carry their block from decode (the
        signatures list is a lazy CommitSigs view over it — zero cost
        here, and mutating the view detaches it, so the columns can
        never go stale). Commits assembled from objects in-process
        build columns FRESH on every call — deliberately uncached:
        `commit.signatures[i] = ...` on a plain list has no hook, so a
        cache here would let a mutated (tampered) signature verify
        against the pre-mutation bytes. The object build is a single
        O(n) pass (~4 ms at 10k lanes); the wire path — the hot one —
        never pays it."""
        sigs = self.signatures
        if isinstance(sigs, CommitSigs):
            blk = sigs.block()
            if blk is not None:
                return blk
        return _commit_sig_columns(sigs)

    def vote_sign_bytes_many(self, chain_id: str, idxs) -> list:
        """Batch form of vote_sign_bytes: one native compose call for all
        requested lanes (the pure-Python composer is ~27us/sig, which was
        the host bottleneck of pipelined header sync at 128 vals/header).
        Falls back to the per-index path without the native module or for
        mixed BlockIDFlags."""
        idxs = list(idxs)
        if len(idxs) >= 8:
            flag = self.signatures[idxs[0]].block_id_flag
            if all(self.signatures[i].block_id_flag == flag for i in idxs):
                from ..native import load as _load_native

                native = _load_native()
                if native is not None and hasattr(native, "vote_sign_bytes_batch"):
                    # materialize the (chain_id, flag) template via the
                    # single-lane path once
                    self.vote_sign_bytes(chain_id, idxs[0])
                    prefix, suffix = self._sb_tpl[(chain_id, flag)]
                    import struct as _struct

                    times = b"".join(
                        _struct.pack(
                            "<qq",
                            self.signatures[i].timestamp.seconds,
                            self.signatures[i].timestamp.nanos,
                        )
                        for i in idxs
                    )
                    return native.vote_sign_bytes_batch(prefix, suffix, times)
        return [self.vote_sign_bytes(chain_id, i) for i in idxs]

    def vote_sign_bytes_block(self, chain_id: str, idxs) -> tuple:
        """Buffer-writing variant of vote_sign_bytes_many: every requested
        lane's sign bytes composed into ONE contiguous buffer + an
        (len(idxs)+1,) int64 offset table — the columnar EntryBlock msgs
        form (ops/entry_block.py). The native composer fills the buffer in
        a single GIL-released call; the pure-Python fallback is
        byte-identical (wire/canonical.compose_vote_sign_bytes_block)."""
        import numpy as np

        idxs = list(idxs)
        n = len(idxs)
        if n == 0:
            return b"", np.zeros(1, dtype=np.int64)
        flag = self.signatures[idxs[0]].block_id_flag
        if all(self.signatures[i].block_id_flag == flag for i in idxs):
            # materialize the (chain_id, flag) template via the
            # single-lane path once
            self.vote_sign_bytes(chain_id, idxs[0])
            prefix, suffix = self._sb_tpl[(chain_id, flag)]
            from ..native import load as _load_native

            native = _load_native()
            if native is not None and hasattr(
                native, "vote_sign_bytes_batch_buf"
            ):
                import struct as _struct

                times = b"".join(
                    _struct.pack(
                        "<qq",
                        self.signatures[i].timestamp.seconds,
                        self.signatures[i].timestamp.nanos,
                    )
                    for i in idxs
                )
                buf, offs = native.vote_sign_bytes_batch_buf(
                    prefix, suffix, times
                )
                return buf, np.frombuffer(offs, dtype=np.int64)
            return _canon.compose_vote_sign_bytes_block(
                (prefix, suffix),
                [self.signatures[i].timestamp for i in idxs],
            )
        # mixed BlockIDFlags (never a single commit's for-block set, but
        # the API allows it): per-index compose, one join
        chunks = [self.vote_sign_bytes(chain_id, i) for i in idxs]
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(c) for c in chunks], out=offsets[1:])
        return b"".join(chunks), offsets

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.height)
        w.write_varint(2, self.round)
        w.write_message(3, self.block_id.encode(), always=True)
        for cs in self.signatures:
            w.write_message(4, cs.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Commit":
        """Columnar-from-decode: canonical-shaped signature records parse
        straight into CommitBlock columns (ONE pass, no CommitSig or
        Timestamp objects); `signatures` is a lazy view over them. A
        non-canonical commit decodes to plain objects as before."""
        f = decode_message(data)
        sigs = _decode_commit_sigs(field_repeated_bytes(f, 4))
        return cls(
            height=to_signed64(field_int(f, 1)),
            round=to_signed32(field_int(f, 2)),
            block_id=BlockID.decode(field_bytes(f, 3)),
            signatures=sigs,
        )

    def validate_basic(self) -> None:
        """types/block.go:779-800."""
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for i, cs in enumerate(self.signatures):
                try:
                    cs.validate_basic()
                except ValueError as e:
                    raise ValueError(f"wrong CommitSig #{i}: {e}") from e


BLS_AGG_SIGNATURE_SIZE = 96  # compressed G2 (min-pubkey BLS12-381)


@dataclass
class AggregatedCommit:
    """BLS12-381 aggregated commit (ISSUE 20): the committee's V
    per-validator precommit signatures collapse into ONE compressed G2
    aggregate plus a signer bitmap — 96 bytes + ceil(V/8) on the wire
    instead of V x (64-byte signature + address + timestamp). This is
    the committee-scale wire diet of "Performance of EdDSA and BLS
    Signatures in Committee-Based Consensus" (arXiv 2302.00418).

    Every signer signs the SAME canonical precommit: the per-signature
    timestamp is dropped (Timestamp.zero() in the canonical vote), which
    is exactly what makes the signatures aggregatable — EdDSA commits
    carry per-signature timestamps, so each validator signs a DIFFERENT
    message and nothing aggregates. The zero-timestamp tradeoff (no
    median-time from commits) is the paper's documented cost.

    Wire framing (local extension — upstream tendermint has no
    aggregated commit message):

        1 height (varint)   2 round (varint)   3 block_id (message)
        4 signature (bytes) 5 signers (BitArray message)
    """

    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    signature: bytes = b""
    signers: Optional["BitArray"] = None  # libs/bits.BitArray

    def sign_bytes(self, chain_id: str) -> bytes:
        """The ONE message every signer signed: the canonical precommit
        with the zero timestamp."""
        tpl = _canon.canonical_vote_template(
            chain_id=chain_id,
            msg_type=_canon.SIGNED_MSG_TYPE_PRECOMMIT,
            height=self.height,
            round_=self.round,
            block_id=self.block_id.canonical(),
        )
        return _canon.compose_vote_sign_bytes(tpl, Timestamp.zero())

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.height)
        w.write_varint(2, self.round)
        w.write_message(3, self.block_id.encode(), always=True)
        w.write_bytes(4, self.signature)
        if self.signers is not None:
            w.write_message(5, self.signers.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "AggregatedCommit":
        from ..libs.bits import BitArray

        f = decode_message(data)
        signers = None
        if 5 in f:
            signers = BitArray.decode(field_bytes(f, 5))
        return cls(
            height=to_signed64(field_int(f, 1)),
            round=to_signed32(field_int(f, 2)),
            block_id=BlockID.decode(field_bytes(f, 3)),
            signature=field_bytes(f, 4),
            signers=signers,
        )

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if len(self.signature) != BLS_AGG_SIGNATURE_SIZE:
                raise ValueError(
                    "aggregate signature is "
                    f"{len(self.signature)} bytes, want "
                    f"{BLS_AGG_SIGNATURE_SIZE}"
                )
            if self.signers is None or self.signers.size() == 0:
                raise ValueError("no signer bitmap in aggregated commit")


@dataclass
class Data:
    """Block transactions (types/block.go Data)."""

    txs: List[bytes] = field(default_factory=list)
    _hash: Optional[bytes] = field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(list(self.txs))
        return self._hash

    def encode(self) -> bytes:
        w = ProtoWriter()
        for tx in self.txs:
            w.write_bytes(1, tx, always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Data":
        f = decode_message(data)
        return cls(txs=field_repeated_bytes(f, 1))


@dataclass
class Block:
    """types/block.go:37-67 (evidence carried as raw encoded list for now;
    typed evidence lands with types/evidence.py)."""

    header: Header = field(default_factory=Header)
    data: Data = field(default_factory=Data)
    evidence: List[bytes] = field(default_factory=list)  # encoded Evidence msgs
    last_commit: Optional[Commit] = None

    def hash(self) -> bytes:
        return self.header.hash()

    def hash_evidence(self) -> bytes:
        return merkle.hash_from_byte_slices(list(self.evidence))

    def fill_header(self) -> None:
        """types/block.go:108-124: populate derived header hashes."""
        h = self.header
        updates = {}
        if not h.last_commit_hash and self.last_commit is not None:
            updates["last_commit_hash"] = self.last_commit.hash()
        if not h.data_hash:
            updates["data_hash"] = self.data.hash()
        if not h.evidence_hash:
            updates["evidence_hash"] = self.hash_evidence()
        if updates:
            self.header = replace(h, **updates)

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_message(1, self.header.encode(), always=True)
        w.write_message(2, self.data.encode(), always=True)
        ev = ProtoWriter()
        for e in self.evidence:
            ev.write_message(1, e, always=True)
        w.write_message(3, ev.bytes(), always=True)
        if self.last_commit is not None:
            w.write_message(4, self.last_commit.encode())
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        f = decode_message(data)
        ev_f = decode_message(field_bytes(f, 3))
        return cls(
            header=Header.decode(field_bytes(f, 1)),
            data=Data.decode(field_bytes(f, 2)),
            evidence=field_repeated_bytes(ev_f, 1),
            last_commit=Commit.decode(field_bytes(f, 4)) if 4 in f else None,
        )

    def validate_basic(self) -> None:
        """types/block.go:69-106."""
        self.header.validate_basic()
        if self.last_commit is not None:
            self.last_commit.validate_basic()
            if self.header.last_commit_hash != self.last_commit.hash():
                raise ValueError("wrong last_commit_hash")
        elif self.header.height > 1:
            raise ValueError("nil LastCommit")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong data_hash")
        if self.header.evidence_hash != self.hash_evidence():
            raise ValueError("wrong evidence_hash")


@dataclass(frozen=True)
class SignedHeader:
    """Header + the commit that signed it (types/block.go:833-890)."""

    header: Optional[Header] = None
    commit: Optional[Commit] = None

    def validate_basic(self, chain_id: str) -> None:
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header belongs to another chain {self.header.chain_id!r}, not {chain_id!r}"
            )
        if self.header.height != self.commit.height:
            raise ValueError("header and commit height mismatch")
        if self.header.hash() != self.commit.block_id.hash:
            raise ValueError("commit signs a header other than this one")
