"""tendermint_tpu.types — core chain data types (reference types/, L2).

Block/Header/Commit/Vote/VoteSet/ValidatorSet plus commit verification
routed through the device batch-verify engine (types/validation.py).
"""

from .block import (  # noqa: F401
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    Block,
    BlockID,
    Commit,
    CommitSig,
    Data,
    Header,
    PartSetHeader,
    SignedHeader,
    Version,
    ZERO_BLOCK_ID,
)
from .part_set import BLOCK_PART_SIZE_BYTES, Part, PartSet  # noqa: F401
from .validation import (  # noqa: F401
    DEFAULT_TRUST_LEVEL,
    Fraction,
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from .validator_set import (  # noqa: F401
    MAX_TOTAL_VOTING_POWER,
    ErrNotEnoughVotingPowerSigned,
    Validator,
    ValidatorSet,
)
from .vote import (  # noqa: F401
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    Vote,
    vote_from_commit_sig,
)
from .vote_set import MAX_VOTES_COUNT, ErrVoteConflictingVotes, VoteSet  # noqa: F401
from ..wire.canonical import Timestamp  # noqa: F401
