"""Validator and ValidatorSet with exact proposer-priority rotation.

Reference parity: types/validator.go, types/validator_set.go. Every integer
operation mirrors the Go int64 semantics (safeAddClip/safeSubClip clipping,
floor-vs-truncated division differences are respected: Go's `/` truncates
toward zero; Python's `//` floors — use _go_div for signed divisions).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Iterable, List, Optional, Sequence, Tuple

from ..crypto import PubKey, merkle
from ..crypto.encoding import pubkey_from_proto, pubkey_to_proto
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, field_repeated_bytes, to_signed64

INT64_MAX = (1 << 63) - 1
INT64_MIN = -(1 << 63)

MAX_TOTAL_VOTING_POWER = INT64_MAX // 8  # validator_set.go:25
PRIORITY_WINDOW_SIZE_FACTOR = 2  # validator_set.go:30

# ed25519_columns cache sentinel: "computed, not columnar-representable"
_NO_ED_COLS = object()

# secp256k1_columns cache sentinel (same protocol)
_NO_SECP_COLS = object()

# bls12381_columns cache sentinel (same protocol)
_NO_BLS_COLS = object()


def _clip64(v: int) -> int:
    return max(INT64_MIN, min(INT64_MAX, v))


def safe_add_clip(a: int, b: int) -> int:
    return _clip64(a + b)


def safe_sub_clip(a: int, b: int) -> int:
    return _clip64(a - b)


def safe_mul(a: int, b: int) -> Tuple[int, bool]:
    v = a * b
    if v > INT64_MAX or v < INT64_MIN:
        return 0, True
    return v, False


def _go_div(a: int, b: int) -> int:
    """Go's truncated integer division (Python // floors)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


@dataclass
class Validator:
    """types/validator.go:20-33."""

    address: bytes
    pub_key: PubKey
    voting_power: int
    proposer_priority: int = 0

    @classmethod
    def new(cls, pub_key: PubKey, voting_power: int) -> "Validator":
        return cls(
            address=pub_key.address(),
            pub_key=pub_key,
            voting_power=voting_power,
            proposer_priority=0,
        )

    def copy(self) -> "Validator":
        return Validator(self.address, self.pub_key, self.voting_power, self.proposer_priority)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != 20:
            raise ValueError("validator address is the wrong size")

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """validator.go:63-83: higher priority wins, ties to lower address."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        if self.address < other.address:
            return self
        if self.address > other.address:
            return other
        raise ValueError("cannot compare identical validators")

    def bytes(self) -> bytes:
        """SimpleValidator proto (validator.go:116-132) — the ValidatorSet
        hash leaf: 1 pub_key(msg) 2 voting_power(varint)."""
        w = ProtoWriter()
        w.write_message(1, pubkey_to_proto(self.pub_key), always=True)
        w.write_varint(2, self.voting_power)
        return w.bytes()

    def encode(self) -> bytes:
        """Full Validator proto (validator.pb.go:88-91)."""
        w = ProtoWriter()
        w.write_bytes(1, self.address)
        w.write_message(2, pubkey_to_proto(self.pub_key), always=True)
        w.write_varint(3, self.voting_power)
        w.write_varint(4, self.proposer_priority)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Validator":
        f = decode_message(data)
        return cls(
            address=field_bytes(f, 1),
            pub_key=pubkey_from_proto(field_bytes(f, 2)),
            voting_power=to_signed64(field_int(f, 3)),
            proposer_priority=to_signed64(field_int(f, 4)),
        )


def _sort_by_voting_power(vals: List[Validator]) -> None:
    """ValidatorsByVotingPower: descending power, ties by ascending address."""
    vals.sort(key=lambda v: (-v.voting_power, v.address))


def _sort_by_address(vals: List[Validator]) -> None:
    vals.sort(key=lambda v: v.address)


class ValidatorSet:
    """types/validator_set.go:51-60."""

    def __init__(self, validators: Optional[List[Validator]] = None, proposer: Optional[Validator] = None):
        self.validators: List[Validator] = validators if validators is not None else []
        self.proposer: Optional[Validator] = proposer
        self._total_voting_power: int = 0
        self._hash: Optional[bytes] = None
        self._ed_cols: Optional[tuple] = None
        self._secp_cols: Optional[tuple] = None
        self._bls_cols: Optional[tuple] = None

    # ---- construction -------------------------------------------------

    @classmethod
    def new(cls, valz: Sequence[Validator]) -> "ValidatorSet":
        """NewValidatorSet (validator_set.go:70-81). Raises on invalid."""
        vals = cls()
        vals._update_with_change_set([v.copy() for v in valz], allow_deletes=False)
        if valz:
            vals.increment_proposer_priority(1)
        return vals

    @classmethod
    def from_existing(cls, valz: List[Validator]) -> "ValidatorSet":
        """ValidatorSetFromExistingValidators (validator_set.go:858-879):
        rebuild without touching priorities; recover previous proposer."""
        if not valz:
            raise ValueError("validator set is empty")
        for v in valz:
            v.validate_basic()
        vals = cls(validators=valz)
        vals.proposer = vals._find_previous_proposer()
        vals._update_total_voting_power()
        _sort_by_voting_power(vals.validators)
        return vals

    def copy(self) -> "ValidatorSet":
        c = ValidatorSet(
            validators=[v.copy() for v in self.validators],
            proposer=self.proposer,
        )
        c._total_voting_power = self._total_voting_power
        # the hash and ed25519 columns cover (pub_key, power) only, which
        # copy preserves — sharing both caches keeps a copied set on the
        # same device epoch (ops/epoch_cache.py keys on hash())
        c._hash = self._hash
        c._ed_cols = self._ed_cols
        c._secp_cols = self._secp_cols
        c._bls_cols = self._bls_cols
        return c

    # ---- queries ------------------------------------------------------

    def is_nil_or_empty(self) -> bool:
        return not self.validators

    def size(self) -> int:
        return len(self.validators)

    def __len__(self) -> int:
        return len(self.validators)

    def has_address(self, address: bytes) -> bool:
        return any(v.address == address for v in self.validators)

    def get_by_address(self, address: bytes) -> Tuple[int, Optional[Validator]]:
        for i, v in enumerate(self.validators):
            if v.address == address:
                return i, v.copy()
        return -1, None

    def get_by_index(self, index: int) -> Tuple[Optional[bytes], Optional[Validator]]:
        if index < 0 or index >= len(self.validators):
            return None, None
        v = self.validators[index]
        return v.address, v.copy()

    def total_voting_power(self) -> int:
        if self._total_voting_power == 0:
            self._update_total_voting_power()
        return self._total_voting_power

    def _update_total_voting_power(self) -> None:
        s = 0
        for v in self.validators:
            s = safe_add_clip(s, v.voting_power)
            if s > MAX_TOTAL_VOTING_POWER:
                raise OverflowError(
                    f"total voting power exceeds max {MAX_TOTAL_VOTING_POWER}: {s}"
                )
        self._total_voting_power = s

    def get_proposer(self) -> Optional[Validator]:
        if not self.validators:
            return None
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer.copy()

    def _find_proposer(self) -> Validator:
        proposer: Optional[Validator] = None
        for v in self.validators:
            if proposer is None:
                proposer = v
            elif v.address != proposer.address:
                proposer = proposer.compare_proposer_priority(v)
        return proposer

    def _find_previous_proposer(self) -> Optional[Validator]:
        """validator_set.go:680-692: lowest priority = previous proposer."""
        prev: Optional[Validator] = None
        for v in self.validators:
            if prev is None:
                prev = v
                continue
            if prev is prev.compare_proposer_priority(v):
                prev = v
        return prev

    def hash(self) -> bytes:
        # Cached: the hash covers (pub_key, voting_power) only — proposer-
        # priority churn does not touch it — and membership/power changes
        # all flow through _update_with_change_set, which invalidates.
        # (Header sync hashes the same set once per header; the recompute
        # was 76% of the pipelined-header host cost at 128 validators.)
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [v.bytes() for v in self.validators]
            )
        return self._hash

    def ed25519_columns(self) -> Optional[tuple]:
        """(pub (n, 32) uint8, power (n,) int64) columns over the set, or
        None unless EVERY validator key is ed25519 — the commit verify
        fast path (types/validation.py fused branch) gathers selected
        lanes from these instead of walking Validator objects per
        signature. Cached; invalidated with the hash cache on membership/
        power changes (everything flows through _update_with_change_set).
        A None result also serves as the per-key TYPE check: a mixed-key
        set falls back to the object path, which raises exactly as
        per-entry add() did."""
        if self._ed_cols is not None:
            cols = self._ed_cols
            return cols if cols is not _NO_ED_COLS else None
        import numpy as np

        from ..crypto import ed25519 as _ed25519

        vals = self.validators
        n = len(vals)
        cols = None
        if n and all(
            isinstance(v.pub_key, _ed25519.PubKey) for v in vals
        ):
            pub_b = b"".join(v.pub_key.bytes() for v in vals)
            if len(pub_b) == 32 * n:
                cols = (
                    np.frombuffer(pub_b, dtype=np.uint8).reshape(n, 32),
                    np.fromiter(
                        (v.voting_power for v in vals),
                        dtype=np.int64,
                        count=n,
                    ),
                )
        self._ed_cols = cols if cols is not None else _NO_ED_COLS
        return cols

    def secp256k1_columns(self) -> Optional[tuple]:
        """(pub (n, 33) uint8, power (n,) int64) columns over the set, or
        None unless EVERY validator key is secp256k1 — the scheme-lane
        analog of ed25519_columns (ISSUE 19): the batched commit prep
        gathers selected 33-byte SEC1 keys from here and the epoch cache
        keys its decompressed affine Q table on the same hash(). Cached;
        invalidated alongside the hash cache by _update_with_change_set
        and shared by copy(). A None result is the TYPE check: mixed or
        non-secp sets fall back to the object path."""
        if self._secp_cols is not None:
            cols = self._secp_cols
            return cols if cols is not _NO_SECP_COLS else None
        import numpy as np

        from ..crypto import secp256k1 as _secp

        vals = self.validators
        n = len(vals)
        cols = None
        if n and all(
            isinstance(v.pub_key, _secp.PubKey) for v in vals
        ):
            pub_b = b"".join(v.pub_key.bytes() for v in vals)
            if len(pub_b) == 33 * n:
                cols = (
                    np.frombuffer(pub_b, dtype=np.uint8).reshape(n, 33),
                    np.fromiter(
                        (v.voting_power for v in vals),
                        dtype=np.int64,
                        count=n,
                    ),
                )
        self._secp_cols = cols if cols is not None else _NO_SECP_COLS
        return cols

    def bls12381_columns(self) -> Optional[tuple]:
        """(pub (n, 48) uint8, power (n,) int64) columns over the set, or
        None unless EVERY validator key is bls12381 — the aggregation
        lane's committee snapshot (ISSUE 20): prepare_aggregated_commit
        carries these compressed G1 rows on the AggBlock and the epoch
        cache keys its decompressed G1 limb table on the same hash().
        Cached; invalidated alongside the hash cache by
        _update_with_change_set and shared by copy()."""
        if self._bls_cols is not None:
            cols = self._bls_cols
            return cols if cols is not _NO_BLS_COLS else None
        import numpy as np

        from ..crypto import bls12381 as _bls

        vals = self.validators
        n = len(vals)
        cols = None
        if n and all(
            isinstance(v.pub_key, _bls.PubKey) for v in vals
        ):
            pub_b = b"".join(v.pub_key.bytes() for v in vals)
            if len(pub_b) == 48 * n:
                cols = (
                    np.frombuffer(pub_b, dtype=np.uint8).reshape(n, 48),
                    np.fromiter(
                        (v.voting_power for v in vals),
                        dtype=np.int64,
                        count=n,
                    ),
                )
        self._bls_cols = cols if cols is not None else _NO_BLS_COLS
        return cols

    def scheme_rows(self) -> Optional[tuple]:
        """Per-validator scheme partition for MIXED device-batchable sets
        (ISSUE 19 tentpole c): (kinds (n,) uint8 — 0 = ed25519, 1 =
        secp256k1, pub (n, 32) uint8, aux (n,) uint8). For ed25519 rows
        `pub` is the key and aux is 0; for secp256k1 rows `pub` is X and
        aux the SEC1 prefix — exactly EntryBlock's (pub, pub_aux) split,
        so the commit prep gathers per-scheme blocks without touching
        Validator objects. None when any key is neither scheme (those
        sets stay on the object path). Not cached separately: derives
        from the per-scheme columns when the set is pure, else builds
        once per call (mixed sets are the rare shape; the gather itself
        is what the hot path repeats)."""
        import numpy as np

        from ..crypto import ed25519 as _ed25519
        from ..crypto import secp256k1 as _secp

        vals = self.validators
        n = len(vals)
        if not n:
            return None
        kinds = np.zeros(n, dtype=np.uint8)
        pub = np.zeros((n, 32), dtype=np.uint8)
        aux = np.zeros(n, dtype=np.uint8)
        for i, v in enumerate(vals):
            k = v.pub_key
            if isinstance(k, _ed25519.PubKey):
                pub[i] = np.frombuffer(k.bytes(), dtype=np.uint8)
            elif isinstance(k, _secp.PubKey):
                kinds[i] = 1
                b = k.bytes()
                aux[i] = b[0]
                pub[i] = np.frombuffer(b, dtype=np.uint8)[1:]
            else:
                return None
        return kinds, pub, aux

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValueError("validator set is nil or empty")
        for i, v in enumerate(self.validators):
            try:
                v.validate_basic()
            except ValueError as e:
                raise ValueError(f"invalid validator #{i}: {e}") from e
        if self.proposer is None:
            raise ValueError("proposer failed validate basic: nil")
        self.proposer.validate_basic()

    # ---- proposer rotation (consensus-critical integer math) ----------

    def increment_proposer_priority(self, times: int) -> None:
        """validator_set.go:115-138."""
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("cannot call with non-positive times")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    def rescale_priorities(self, diff_max: int) -> None:
        """validator_set.go:143-165: divide priorities by ceil(diff/diffMax)."""
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if diff_max <= 0:
            return
        diff = self._compute_max_min_priority_diff()
        ratio = (diff + diff_max - 1) // diff_max  # both nonneg: floor==trunc
        if diff > diff_max:
            for v in self.validators:
                v.proposer_priority = _go_div(v.proposer_priority, ratio)

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = safe_add_clip(v.proposer_priority, v.voting_power)
        mostest = None
        for v in self.validators:
            mostest = v if mostest is None else mostest.compare_proposer_priority(v)
        mostest.proposer_priority = safe_sub_clip(
            mostest.proposer_priority, self.total_voting_power()
        )
        return mostest

    def _compute_avg_proposer_priority(self) -> int:
        # validator_set.go:181-195 uses big.Int.Div — Euclidean division,
        # which floors for a positive divisor: exactly Python's //.
        n = len(self.validators)
        total = sum(v.proposer_priority for v in self.validators)
        return total // n

    def _compute_max_min_priority_diff(self) -> int:
        mx = max(v.proposer_priority for v in self.validators)
        mn = min(v.proposer_priority for v in self.validators)
        d = mx - mn
        return -d if d < 0 else d

    def _shift_by_avg_proposer_priority(self) -> None:
        avg = self._compute_avg_proposer_priority()
        for v in self.validators:
            v.proposer_priority = safe_sub_clip(v.proposer_priority, avg)

    # ---- updates (validator_set.go:365-655) ---------------------------

    def update_with_change_set(self, changes: Sequence[Validator]) -> None:
        self._update_with_change_set([v.copy() for v in changes], allow_deletes=True)

    def _update_with_change_set(self, changes: List[Validator], allow_deletes: bool) -> None:
        self._hash = None  # membership/power may change below
        self._ed_cols = None
        self._secp_cols = None
        self._bls_cols = None
        if not changes:
            return
        updates, deletes = _process_changes(changes)
        if not allow_deletes and deletes:
            raise ValueError(f"cannot process validators with voting power 0: {deletes}")
        if _num_new_validators(updates, self) == 0 and len(self.validators) == len(deletes):
            raise ValueError("applying the validator changes would result in empty set")
        removed_power = _verify_removals(deletes, self)
        tvp_after_updates_before_removals = _verify_updates(updates, self, removed_power)
        _compute_new_priorities(updates, self, tvp_after_updates_before_removals)
        self._apply_updates(updates)
        self._apply_removals(deletes)
        self._update_total_voting_power()
        self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
        self._shift_by_avg_proposer_priority()
        _sort_by_voting_power(self.validators)

    def _apply_updates(self, updates: List[Validator]) -> None:
        existing = list(self.validators)
        _sort_by_address(existing)
        merged: List[Validator] = []
        i = j = 0
        while i < len(existing) and j < len(updates):
            if existing[i].address < updates[j].address:
                merged.append(existing[i])
                i += 1
            else:
                merged.append(updates[j])
                if existing[i].address == updates[j].address:
                    i += 1
                j += 1
        merged.extend(existing[i:])
        merged.extend(updates[j:])
        self.validators = merged

    def _apply_removals(self, deletes: List[Validator]) -> None:
        existing = list(self.validators)
        merged: List[Validator] = []
        di = 0
        for v in existing:
            if di < len(deletes) and v.address == deletes[di].address:
                di += 1
            else:
                merged.append(v)
        self.validators = merged

    # ---- commit verification façade -----------------------------------

    def verify_commit(self, chain_id: str, block_id, height: int, commit) -> None:
        from . import validation

        validation.verify_commit(chain_id, self, block_id, height, commit)

    def verify_commit_light(self, chain_id: str, block_id, height: int, commit) -> None:
        from . import validation

        validation.verify_commit_light(chain_id, self, block_id, height, commit)

    def verify_commit_light_trusting(self, chain_id: str, commit, trust_level) -> None:
        from . import validation

        validation.verify_commit_light_trusting(chain_id, self, commit, trust_level)

    # ---- proto --------------------------------------------------------

    def encode(self) -> bytes:
        w = ProtoWriter()
        for v in self.validators:
            w.write_message(1, v.encode(), always=True)
        if self.proposer is not None:
            w.write_message(2, self.proposer.encode())
        # TotalVotingPower deliberately zeroed (validator_set.go:797-800).
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorSet":
        f = decode_message(data)
        vals = [Validator.decode(raw) for raw in field_repeated_bytes(f, 1)]
        proposer = Validator.decode(field_bytes(f, 2)) if 2 in f else None
        vs = cls(validators=vals, proposer=proposer)
        vs.total_voting_power()  # recompute, never trust the wire
        vs.validate_basic()
        return vs


class ErrNotEnoughVotingPowerSigned(ValueError):
    """validator_set.go:703-713."""

    def __init__(self, got: int, needed: int):
        super().__init__(
            f"invalid commit -- insufficient voting power: got {got}, needed more than {needed}"
        )
        self.got = got
        self.needed = needed


# ---- free helpers (validator_set.go:365-520) --------------------------


def _process_changes(orig: List[Validator]) -> Tuple[List[Validator], List[Validator]]:
    changes = [v.copy() for v in orig]
    _sort_by_address(changes)
    updates: List[Validator] = []
    removals: List[Validator] = []
    prev_addr: Optional[bytes] = None
    for u in changes:
        if u.address == prev_addr:
            raise ValueError(f"duplicate entry {u} in {changes}")
        if u.voting_power < 0:
            raise ValueError(f"voting power can't be negative: {u.voting_power}")
        if u.voting_power > MAX_TOTAL_VOTING_POWER:
            raise ValueError(
                f"to prevent clipping/overflow, voting power can't be higher than {MAX_TOTAL_VOTING_POWER}: {u.voting_power}"
            )
        if u.voting_power == 0:
            removals.append(u)
        else:
            updates.append(u)
        prev_addr = u.address
    return updates, removals


def _verify_updates(updates: List[Validator], vals: ValidatorSet, removed_power: int) -> int:
    def delta(update: Validator) -> int:
        _, val = vals.get_by_address(update.address)
        if val is not None:
            return update.voting_power - val.voting_power
        return update.voting_power

    updates_copy = sorted(updates, key=delta)
    tvp_after_removals = vals.total_voting_power() - removed_power
    for upd in updates_copy:
        tvp_after_removals += delta(upd)
        if tvp_after_removals > MAX_TOTAL_VOTING_POWER:
            raise OverflowError(
                f"total voting power of resulting valset exceeds max {MAX_TOTAL_VOTING_POWER}"
            )
    return tvp_after_removals + removed_power


def _num_new_validators(updates: List[Validator], vals: ValidatorSet) -> int:
    return sum(1 for u in updates if not vals.has_address(u.address))


def _compute_new_priorities(updates: List[Validator], vals: ValidatorSet, updated_tvp: int) -> None:
    for u in updates:
        _, val = vals.get_by_address(u.address)
        if val is None:
            # -1.125 * updatedTotalVotingPower (validator_set.go:473-489);
            # Go's >> on non-negative int64 == Python's >>.
            u.proposer_priority = -(updated_tvp + (updated_tvp >> 3))
        else:
            u.proposer_priority = val.proposer_priority


def _verify_removals(deletes: List[Validator], vals: ValidatorSet) -> int:
    removed = 0
    for d in deletes:
        _, val = vals.get_by_address(d.address)
        if val is None:
            raise ValueError(f"failed to find validator {d.address.hex().upper()} to remove")
        removed += val.voting_power
    if len(deletes) > len(vals.validators):
        raise ValueError("more deletes than validators")
    return removed
