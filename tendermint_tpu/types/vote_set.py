"""VoteSet — accumulates votes for one (height, round, type).

Reference parity: types/vote_set.go. Tracks the canonical vote per
validator, per-block vote tallies (votesByBlock), the first +2/3 block
(maj23), conflicting votes for evidence, and peer maj23 claims.

The signature check in add_vote is the per-vote hot path
(vote_set.go:203 → vote.Verify); commits arriving via blocksync/light
flow through types.validation instead, where the device batch engine runs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..libs.bits import BitArray
from .block import BlockID, Commit, CommitSig
from .validator_set import ValidatorSet
from .vote import (
    PRECOMMIT_TYPE,
    ErrVoteInvalidSignature,
    Vote,
    is_vote_type_valid,
)

MAX_VOTES_COUNT = 10000  # vote_set.go:18


class ErrVoteUnexpectedStep(ValueError):
    pass


class ErrVoteInvalidValidatorIndex(ValueError):
    pass


class ErrVoteInvalidValidatorAddress(ValueError):
    pass


class ErrVoteNonDeterministicSignature(ValueError):
    pass


class ErrVoteConflictingVotes(ValueError):
    """NewConflictingVoteError (types/errors.go): carries both votes for
    DuplicateVoteEvidence construction."""

    def __init__(self, vote_a: Vote, vote_b: Vote):
        super().__init__("conflicting votes from validator "
                         f"{vote_a.validator_address.hex().upper()}")
        self.vote_a = vote_a
        self.vote_b = vote_b


@dataclass(frozen=True)
class CheckedVote:
    """Host-stage result for one vote (ISSUE 15): everything add_vote
    establishes BEFORE the signature check. `pub_key` drives either the
    inline host verify (sequential path) or an EntryBlock row (batched
    ingress); `block_key`/`voting_power` feed the verdict-application
    stage."""

    vote: Vote
    pub_key: object  # crypto.PubKey
    voting_power: int
    block_key: bytes


class _BlockVotes:
    """vote_set.go:625-660."""

    __slots__ = ("peer_maj23", "bit_array", "votes", "sum")

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(num_validators)
        self.votes: List[Optional[Vote]] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.bit_array.set_index(idx, True)
            self.votes[idx] = vote
            self.sum += voting_power

    def get_by_index(self, idx: int) -> Optional[Vote]:
        return self.votes[idx]


class VoteSet:
    """vote_set.go:62-137."""

    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: int,
        val_set: ValidatorSet,
    ):
        if height == 0:
            raise ValueError("cannot make VoteSet for height == 0")
        if not is_vote_type_valid(signed_msg_type):
            raise ValueError(f"invalid vote type {signed_msg_type}")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self._mtx = threading.RLock()
        self.votes_bit_array = BitArray(val_set.size())
        self.votes: List[Optional[Vote]] = [None] * val_set.size()
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        self.votes_by_block: Dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: Dict[str, BlockID] = {}

    # -- adding votes ---------------------------------------------------

    def add_vote(self, vote: Optional[Vote]) -> bool:
        """vote_set.go:143-216. Returns True if the vote was added; False
        for exact duplicates; raises for everything else."""
        with self._mtx:
            return self._add_vote(vote)

    def _add_vote(self, vote: Optional[Vote]) -> bool:
        chk = self._check_vote(vote)
        if chk is None:
            return False  # duplicate
        # Check signature (the per-vote hot path).
        valid = chk.pub_key.verify_signature(
            vote.sign_bytes(self.chain_id), vote.signature
        )
        return self._apply_checked(vote, chk, valid)

    def check_vote(self, vote: Optional[Vote]) -> Optional[CheckedVote]:
        """Host stage of add_vote (ISSUE 15): every check that does NOT
        need the signature verdict — index/address/step validation, the
        exact-duplicate and non-deterministic-signature checks, the
        pubkey-vs-address match. Returns None for an exact duplicate
        (sequential add_vote would return False); raises exactly what
        add_vote raises for each malformed shape. The returned CheckedVote
        feeds either a device EntryBlock row or apply_vote_verdict."""
        with self._mtx:
            return self._check_vote(vote)

    def apply_vote_verdict(self, vote: Vote, valid: bool) -> bool:
        """Verdict-application stage of add_vote (ISSUE 15). Re-runs the
        host checks under the lock — VoteSet state may have moved between
        dispatch and verdict (a re-gossiped copy landing first turns this
        call into the duplicate=False / non-deterministic-signature case,
        exactly as if the votes had arrived sequentially) — then applies
        the device verdict: False raises the same ErrVoteInvalidSignature
        Vote.verify raises, True runs _add_verified_vote with its
        ErrVoteConflictingVotes semantics."""
        with self._mtx:
            chk = self._check_vote(vote)
            if chk is None:
                return False  # duplicate
            return self._apply_checked(vote, chk, bool(valid))

    def _check_vote(self, vote: Optional[Vote]) -> Optional[CheckedVote]:
        if vote is None:
            raise ValueError("nil vote")
        val_index = vote.validator_index
        val_addr = vote.validator_address
        block_key = vote.block_id.key()

        if val_index < 0:
            raise ErrVoteInvalidValidatorIndex("index < 0")
        if not val_addr:
            raise ErrVoteInvalidValidatorAddress("empty address")
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type != self.signed_msg_type
        ):
            raise ErrVoteUnexpectedStep(
                f"expected {self.height}/{self.round}/{self.signed_msg_type}, "
                f"got {vote.height}/{vote.round}/{vote.type}"
            )
        lookup_addr, val = self.val_set.get_by_index(val_index)
        if val is None:
            raise ErrVoteInvalidValidatorIndex(
                f"cannot find validator {val_index} in valSet of size {self.val_set.size()}"
            )
        if val_addr != lookup_addr:
            raise ErrVoteInvalidValidatorAddress(
                f"vote.validator_address ({val_addr.hex()}) does not match address "
                f"({lookup_addr.hex()}) for vote.validator_index ({val_index})"
            )
        existing = self._get_vote(val_index, block_key)
        if existing is not None:
            if existing.signature == vote.signature:
                return None  # duplicate
            raise ErrVoteNonDeterministicSignature(
                f"existing vote: {existing}; new vote: {vote}"
            )
        # The host half of vote.Verify (address-vs-pubkey) stays in check
        # order: after the duplicate check, before any signature math.
        vote.verify_address(val.pub_key)
        return CheckedVote(
            vote=vote,
            pub_key=val.pub_key,
            voting_power=val.voting_power,
            block_key=block_key,
        )

    def _apply_checked(self, vote: Vote, chk: CheckedVote, valid: bool) -> bool:
        if not valid:
            raise ErrVoteInvalidSignature("invalid signature")
        added, conflicting = self._add_verified_vote(
            vote, chk.block_key, chk.voting_power
        )
        if conflicting is not None:
            raise ErrVoteConflictingVotes(conflicting, vote)
        if not added:
            raise RuntimeError("expected to add non-conflicting vote")
        return added

    def _get_vote(self, val_index: int, block_key: bytes) -> Optional[Vote]:
        existing = self.votes[val_index]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(val_index)
        return None

    def _add_verified_vote(
        self, vote: Vote, block_key: bytes, voting_power: int
    ) -> Tuple[bool, Optional[Vote]]:
        """vote_set.go:230-296."""
        conflicting: Optional[Vote] = None
        val_index = vote.validator_index

        existing = self.votes[val_index]
        if existing is not None:
            if existing.block_id == vote.block_id:
                raise RuntimeError("addVerifiedVote does not expect duplicate votes")
            conflicting = existing
            if self.maj23 is not None and self.maj23.key() == block_key:
                self.votes[val_index] = vote
                self.votes_bit_array.set_index(val_index, True)
        else:
            self.votes[val_index] = vote
            self.votes_bit_array.set_index(val_index, True)
            self.sum += voting_power

        votes_by_block = self.votes_by_block.get(block_key)
        if votes_by_block is not None:
            if conflicting is not None and not votes_by_block.peer_maj23:
                return False, conflicting
        else:
            if conflicting is not None:
                return False, conflicting
            votes_by_block = _BlockVotes(False, self.val_set.size())
            self.votes_by_block[block_key] = votes_by_block

        orig_sum = votes_by_block.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        votes_by_block.add_verified_vote(vote, voting_power)
        if orig_sum < quorum <= votes_by_block.sum:
            if self.maj23 is None:
                self.maj23 = vote.block_id
                for i, v in enumerate(votes_by_block.votes):
                    if v is not None:
                        self.votes[i] = v
        return True, conflicting

    # -- peer claims ----------------------------------------------------

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """vote_set.go:303-337."""
        with self._mtx:
            block_key = block_id.key()
            existing = self.peer_maj23s.get(peer_id)
            if existing is not None:
                if existing == block_id:
                    return
                raise ValueError(
                    f"setPeerMaj23: received conflicting blockID from peer {peer_id}: "
                    f"got {block_id}, expected {existing}"
                )
            self.peer_maj23s[peer_id] = block_id
            votes_by_block = self.votes_by_block.get(block_key)
            if votes_by_block is not None:
                votes_by_block.peer_maj23 = True
            else:
                self.votes_by_block[block_key] = _BlockVotes(True, self.val_set.size())

    # -- queries --------------------------------------------------------

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[BitArray]:
        with self._mtx:
            bv = self.votes_by_block.get(block_id.key())
            return bv.bit_array.copy() if bv is not None else None

    def get_by_index(self, val_index: int) -> Optional[Vote]:
        with self._mtx:
            if val_index >= len(self.votes):
                return None
            return self.votes[val_index]

    def get_by_address(self, address: bytes) -> Optional[Vote]:
        with self._mtx:
            idx, val = self.val_set.get_by_address(address)
            if val is None:
                raise ValueError("address not in validator set")
            return self.votes[idx]

    def has_two_thirds_majority(self) -> bool:
        with self._mtx:
            return self.maj23 is not None

    def is_commit(self) -> bool:
        if self.signed_msg_type != PRECOMMIT_TYPE:
            return False
        with self._mtx:
            return self.maj23 is not None

    def has_two_thirds_any(self) -> bool:
        with self._mtx:
            return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        with self._mtx:
            return self.sum == self.val_set.total_voting_power()

    def two_thirds_majority(self) -> Tuple[BlockID, bool]:
        with self._mtx:
            if self.maj23 is not None:
                return self.maj23, True
            return BlockID(), False

    # -- commit construction --------------------------------------------

    def make_commit(self) -> Commit:
        """vote_set.go:596-623."""
        if self.signed_msg_type != PRECOMMIT_TYPE:
            raise ValueError("cannot make_commit() unless VoteSet.type is precommit")
        with self._mtx:
            if self.maj23 is None:
                raise ValueError("cannot make_commit() unless a blockhash has +2/3")
            commit_sigs: List[CommitSig] = []
            for v in self.votes:
                if v is None:
                    cs = CommitSig.absent()
                else:
                    cs = v.to_commit_sig()
                    if cs.for_block() and v.block_id != self.maj23:
                        cs = CommitSig.absent()
                commit_sigs.append(cs)
            return Commit(
                height=self.height,
                round=self.round,
                block_id=self.maj23,
                signatures=commit_sigs,
            )
