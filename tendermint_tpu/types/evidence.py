"""Evidence — proofs of validator misbehavior.

Reference parity: types/evidence.go. DuplicateVoteEvidence (equivocation)
and LightClientAttackEvidence (conflicting light block), their wire forms
(proto/tendermint/types/evidence.pb.go), hashing, ABCI conversion, and
the EvidenceList hashing used by Block.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto import merkle, tmhash
from ..abci import types as abci
from ..wire import canonical as _canon
from ..wire.canonical import Timestamp
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, field_repeated_bytes, to_signed32, to_signed64
from .block import Commit, Header
from .validator_set import Validator, ValidatorSet
from .vote import Vote


@dataclass
class DuplicateVoteEvidence:
    """types/evidence.go:38-48."""

    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp: Timestamp = field(default_factory=Timestamp.zero)

    @classmethod
    def new(
        cls, vote1: Vote, vote2: Vote, block_time: Timestamp, val_set: ValidatorSet
    ) -> "DuplicateVoteEvidence":
        """evidence.go:51-80: votes ordered by BlockID key."""
        if vote1 is None or vote2 is None:
            raise ValueError("missing vote")
        if val_set is None:
            raise ValueError("missing validator set")
        idx, val = val_set.get_by_address(vote1.validator_address)
        if idx == -1:
            raise ValueError("validator not in validator set")
        if vote1.block_id.key() < vote2.block_id.key():
            vote_a, vote_b = vote1, vote2
        else:
            vote_a, vote_b = vote2, vote1
        return cls(
            vote_a=vote_a,
            vote_b=vote_b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp=block_time,
        )

    def height(self) -> int:
        return self.vote_a.height

    def time(self) -> Timestamp:
        return self.timestamp

    def bytes(self) -> bytes:
        return self.encode()

    def hash(self) -> bytes:
        return tmhash.sum_sha256(self.encode())

    def abci(self) -> List[abci.ABCIEvidence]:
        return [
            abci.ABCIEvidence(
                type=abci.EVIDENCE_TYPE_DUPLICATE_VOTE,
                validator=abci.ABCIValidator(
                    address=self.vote_a.validator_address, power=self.validator_power
                ),
                height=self.vote_a.height,
                time=self.timestamp,
                total_voting_power=self.total_voting_power,
            )
        ]

    def validate_basic(self) -> None:
        """evidence.go:127-147."""
        if self.vote_a is None or self.vote_b is None:
            raise ValueError("empty duplicate vote evidence")
        self.vote_a.validate_basic()
        self.vote_b.validate_basic()
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise ValueError("duplicate votes in invalid order")

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_message(1, self.vote_a.encode(), always=True)
        w.write_message(2, self.vote_b.encode(), always=True)
        w.write_varint(3, self.total_voting_power)
        w.write_varint(4, self.validator_power)
        w.write_message(5, _canon.encode_timestamp(self.timestamp), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "DuplicateVoteEvidence":
        f = decode_message(data)
        ts = decode_message(field_bytes(f, 5))
        return cls(
            vote_a=Vote.decode(field_bytes(f, 1)),
            vote_b=Vote.decode(field_bytes(f, 2)),
            total_voting_power=to_signed64(field_int(f, 3)),
            validator_power=to_signed64(field_int(f, 4)),
            timestamp=Timestamp(
                seconds=to_signed64(field_int(ts, 1)), nanos=to_signed32(field_int(ts, 2))
            ),
        )


@dataclass
class LightBlockData:
    """SignedHeader + ValidatorSet (types.LightBlock wire subset)."""

    signed_header_raw: bytes  # encoded SignedHeader {1 header, 2 commit}
    validator_set_raw: bytes  # encoded ValidatorSet

    def header(self) -> Header:
        f = decode_message(self.signed_header_raw)
        return Header.decode(field_bytes(f, 1))

    def commit(self) -> Commit:
        f = decode_message(self.signed_header_raw)
        return Commit.decode(field_bytes(f, 2))

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet.decode(self.validator_set_raw)

    @classmethod
    def from_parts(cls, signed_header, validator_set) -> "LightBlockData":
        """Encode a (SignedHeader, ValidatorSet) pair into wire form — the
        shape the light-client detector captures a conflicting block in
        (detector.go:406 newLightClientAttackEvidence)."""
        w = ProtoWriter()
        w.write_message(1, signed_header.header.encode(), always=True)
        w.write_message(2, signed_header.commit.encode(), always=True)
        return cls(
            signed_header_raw=w.bytes(), validator_set_raw=validator_set.encode()
        )


@dataclass
class LightClientAttackEvidence:
    """types/evidence.go:200-248."""

    conflicting_block: LightBlockData
    common_height: int
    byzantine_validators: List[Validator] = field(default_factory=list)
    total_voting_power: int = 0
    timestamp: Timestamp = field(default_factory=Timestamp.zero)

    def height(self) -> int:
        return self.common_height

    def time(self) -> Timestamp:
        return self.timestamp

    def bytes(self) -> bytes:
        return self.encode()

    def hash(self) -> bytes:
        """evidence.go:309-318: hash of (conflicting header hash, common
        height) — stable across byzantine-validator discovery."""
        w = ProtoWriter()
        w.write_bytes(1, self.conflicting_block.header().hash())
        w.write_varint(2, self.common_height)
        return tmhash.sum_sha256(w.bytes())

    def abci(self) -> List[abci.ABCIEvidence]:
        return [
            abci.ABCIEvidence(
                type=abci.EVIDENCE_TYPE_LIGHT_CLIENT_ATTACK,
                validator=abci.ABCIValidator(address=v.address, power=v.voting_power),
                height=self.common_height,
                time=self.timestamp,
                total_voting_power=self.total_voting_power,
            )
            for v in self.byzantine_validators
        ]

    def get_byzantine_validators(
        self, common_vals: ValidatorSet, trusted
    ) -> List[Validator]:
        """evidence.go GetByzantineValidators: lunatic attack -> the
        common-height validators who signed the lunatic header;
        equivocation (same round) -> validators who signed both blocks;
        amnesia (different rounds) -> indeterminable, empty set.
        `trusted` is the SignedHeader at the conflicting height."""
        out: List[Validator] = []
        commit = self.conflicting_block.commit()
        if self.conflicting_header_is_invalid(trusted.header):
            # Lunatic: blame common-height validators who voted for it.
            for cs in commit.signatures:
                if not cs.for_block():
                    continue
                _, val = common_vals.get_by_address(cs.validator_address)
                if val is not None:
                    out.append(val)
            return out
        if trusted.commit.round == commit.round:
            # Equivocation: blame validators who signed both conflicting
            # blocks (same commit index in both commits).
            vals = self.conflicting_block.validator_set()
            for i, sig_a in enumerate(commit.signatures):
                if not sig_a.for_block():
                    continue
                if i >= len(trusted.commit.signatures):
                    continue
                sig_b = trusted.commit.signatures[i]
                if not sig_b.for_block():
                    continue
                _, val = vals.get_by_address(sig_a.validator_address)
                if val is not None:
                    out.append(val)
        # Amnesia (differing rounds): byzantine set not deducible.
        return out

    def conflicting_header_is_invalid(self, trusted_header: Header) -> bool:
        """evidence.go ConflictingHeaderIsInvalid: lunatic iff the
        conflicting header forges any of the hashes the application/state
        machine determines (valhash, next-valhash, consensus, app,
        last-results)."""
        ch = self.conflicting_block.header()
        return not (
            trusted_header.validators_hash == ch.validators_hash
            and trusted_header.next_validators_hash == ch.next_validators_hash
            and trusted_header.consensus_hash == ch.consensus_hash
            and trusted_header.app_hash == ch.app_hash
            and trusted_header.last_results_hash == ch.last_results_hash
        )

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise ValueError("conflicting block is nil")
        if self.common_height <= 0:
            raise ValueError("negative or zero common height")
        self.conflicting_block.header()  # must parse

    def encode(self) -> bytes:
        w = ProtoWriter()
        lb = ProtoWriter()
        lb.write_message(1, self.conflicting_block.signed_header_raw, always=True)
        lb.write_message(2, self.conflicting_block.validator_set_raw, always=True)
        w.write_message(1, lb.bytes(), always=True)
        w.write_varint(2, self.common_height)
        for v in self.byzantine_validators:
            w.write_message(3, v.encode(), always=True)
        w.write_varint(4, self.total_voting_power)
        w.write_message(5, _canon.encode_timestamp(self.timestamp), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "LightClientAttackEvidence":
        f = decode_message(data)
        lb = decode_message(field_bytes(f, 1))
        ts = decode_message(field_bytes(f, 5))
        return cls(
            conflicting_block=LightBlockData(
                signed_header_raw=field_bytes(lb, 1),
                validator_set_raw=field_bytes(lb, 2),
            ),
            common_height=to_signed64(field_int(f, 2)),
            byzantine_validators=[Validator.decode(raw) for raw in field_repeated_bytes(f, 3)],
            total_voting_power=to_signed64(field_int(f, 4)),
            timestamp=Timestamp(
                seconds=to_signed64(field_int(ts, 1)), nanos=to_signed32(field_int(ts, 2))
            ),
        )


# -- Evidence oneof wrapper (proto/tendermint/types/evidence.pb.go) -------

_FIELD_DUPLICATE = 1
_FIELD_LIGHT_ATTACK = 2


def encode_evidence(ev) -> bytes:
    w = ProtoWriter()
    if isinstance(ev, DuplicateVoteEvidence):
        w.write_message(_FIELD_DUPLICATE, ev.encode(), always=True)
    elif isinstance(ev, LightClientAttackEvidence):
        w.write_message(_FIELD_LIGHT_ATTACK, ev.encode(), always=True)
    else:
        raise TypeError(f"unknown evidence type {type(ev)}")
    return w.bytes()


def decode_evidence(data: bytes):
    f = decode_message(data)
    if _FIELD_DUPLICATE in f:
        return DuplicateVoteEvidence.decode(field_bytes(f, _FIELD_DUPLICATE))
    if _FIELD_LIGHT_ATTACK in f:
        return LightClientAttackEvidence.decode(field_bytes(f, _FIELD_LIGHT_ATTACK))
    raise ValueError("unknown evidence oneof")


def evidence_to_abci(ev_raw: bytes) -> List[abci.ABCIEvidence]:
    """Raw encoded Evidence -> abci.Evidence list (block execution path)."""
    return decode_evidence(ev_raw).abci()


def evidence_list_hash(evidence_raws: List[bytes]) -> bytes:
    return merkle.hash_from_byte_slices(list(evidence_raws))
