"""Proposal — a proposed block at (height, round) signed by the proposer.

Reference parity: types/proposal.go. Sign bytes are the delimited proto
CanonicalProposal (proposal.go ProposalSignBytes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..wire import canonical as _canon
from ..wire.canonical import Timestamp
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, to_signed32, to_signed64
from .block import BlockID, MAX_SIGNATURE_SIZE


@dataclass(frozen=True)
class Proposal:
    """types/proposal.go:21-34."""

    type: int = _canon.SIGNED_MSG_TYPE_PROPOSAL
    height: int = 0
    round: int = 0
    pol_round: int = -1  # -1 if no proof-of-lock
    block_id: BlockID = field(default_factory=BlockID)
    timestamp: Timestamp = field(default_factory=Timestamp.zero)
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return _canon.canonical_proposal_sign_bytes(
            chain_id=chain_id,
            height=self.height,
            round_=self.round,
            pol_round=self.pol_round,
            block_id=self.block_id.canonical(),
            timestamp=self.timestamp,
        )

    def validate_basic(self) -> None:
        """proposal.go:65-96."""
        if self.type != _canon.SIGNED_MSG_TYPE_PROPOSAL:
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        if self.pol_round < -1:
            raise ValueError("negative POLRound (exception: -1)")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError(f"expected a complete, non-empty BlockID, got: {self.block_id}")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature is too big")

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.type)
        w.write_varint(2, self.height)
        w.write_varint(3, self.round)
        w.write_varint(4, self.pol_round)
        w.write_message(5, self.block_id.encode(), always=True)
        w.write_message(6, _canon.encode_timestamp(self.timestamp), always=True)
        w.write_bytes(7, self.signature)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Proposal":
        f = decode_message(data)
        ts = decode_message(field_bytes(f, 6))
        return cls(
            type=field_int(f, 1),
            height=to_signed64(field_int(f, 2)),
            round=to_signed32(field_int(f, 3)),
            pol_round=to_signed32(field_int(f, 4)),
            block_id=BlockID.decode(field_bytes(f, 5)),
            timestamp=Timestamp(
                seconds=to_signed64(field_int(ts, 1)), nanos=to_signed32(field_int(ts, 2))
            ),
            signature=field_bytes(f, 7),
        )
