"""Commit verification — the north-star hot path.

Reference parity: types/validation.go. VerifyCommit/VerifyCommitLight/
VerifyCommitLightTrusting route through the crypto.batch seam, where the
device (TPU) batch verifier is installed — a commit's signatures become one
fixed-shape device batch (SURVEY.md §3.4). Behavior (error cases, tally
accounting, blame assignment for the first bad signature) is byte-identical
to the single-verify path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..crypto import batch as _batch
from ..crypto import tmhash
from ..observability import trace as _trace
from .block import BlockID, Commit, CommitSig
from .validator_set import ErrNotEnoughVotingPowerSigned, ValidatorSet, safe_mul

_span = _trace.span

_OPS = None


def _note_host_verified(n: int) -> None:
    """Per-signature host verifications (the sub-threshold single path)
    count toward the ops sigs_verified series like every other path."""
    global _OPS
    if not n:
        return
    if _OPS is None:
        from ..libs import metrics as _metrics

        _OPS = _metrics.ops_metrics()
    _OPS.sigs_verified.inc(n, path="host")

BATCH_VERIFY_THRESHOLD = 2  # validation.go:12


@dataclass(frozen=True)
class Fraction:
    """libs/math.Fraction (used for light-client trust level)."""

    numerator: int
    denominator: int

    def validate(self) -> None:
        if self.denominator == 0:
            raise ValueError("fraction has zero denominator")


DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class ErrInvalidCommitHeight(ValueError):
    def __init__(self, expected: int, actual: int):
        super().__init__(f"invalid commit height: expected {expected}, got {actual}")


class ErrInvalidCommitSignatures(ValueError):
    def __init__(self, expected: int, actual: int):
        super().__init__(
            f"invalid commit -- wrong set size: {expected} vs {actual}"
        )


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    proposer = vals.get_proposer()
    return len(commit.signatures) >= BATCH_VERIFY_THRESHOLD and _batch.supports_batch_verifier(
        proposer.pub_key if proposer else None
    )


def _should_batch_prepare(vals: ValidatorSet, commit: Commit) -> bool:
    """The async seam's batch gate (ISSUE 19): the reference's per-key
    batch-verifier gate, OR a scheme column view the device lanes can
    take — an all-secp256k1 committee batches through the secp kernel
    even though crypto/batch.go has no secp verifier (batch.go:26-33
    returns nil; the device lane is a superset, not a parity break,
    because verdicts and blame are bit-identical to the single path)."""
    if _should_batch_verify(vals, commit):
        return True
    return (
        len(commit.signatures) >= BATCH_VERIFY_THRESHOLD
        and vals.secp256k1_columns() is not None
    )


def _ignore_absent(c: CommitSig) -> bool:
    return c.is_absent()


def _ignore_not_for_block(c: CommitSig) -> bool:
    return not c.for_block()


def _count_for_block(c: CommitSig) -> bool:
    return c.for_block()


def _count_all(c: CommitSig) -> bool:
    return True


def verify_commit(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> None:
    """validation.go:25-52: +2/3 signed, ALL signatures checked (the app's
    LastCommitInfo incentive accounting depends on every sig)."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = _ignore_absent
    count = _count_for_block
    with _span("verify_commit", n=len(commit.signatures), height=height):
        if _should_batch_verify(vals, commit):
            _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore, count, True, True
            )
        else:
            _verify_commit_single(
                chain_id, vals, commit, voting_power_needed, ignore, count, True, True
            )


def verify_commit_light(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> None:
    """validation.go:59-86: +2/3 signed; may exit early."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    ignore = _ignore_not_for_block
    count = _count_all
    with _span("verify_commit", n=len(commit.signatures), height=height,
               mode="light"):
        if _should_batch_verify(vals, commit):
            _verify_commit_batch(
                chain_id, vals, commit, voting_power_needed, ignore, count, False, True
            )
        else:
            _verify_commit_single(
                chain_id, vals, commit, voting_power_needed, ignore, count, False, True
            )


def verify_commit_light_trusting(
    chain_id: str, vals: ValidatorSet, commit: Commit, trust_level: Fraction
) -> None:
    """validation.go:94-135: trustLevel of vals signed; vals need not match
    the commit's validator set — look up by address, reject double votes."""
    if vals is None:
        raise ValueError("nil validator set")
    if trust_level.denominator == 0:
        raise ValueError("trustLevel has zero Denominator")
    if commit is None:
        raise ValueError("nil commit")
    total_mul, overflow = safe_mul(vals.total_voting_power(), trust_level.numerator)
    if overflow:
        raise OverflowError(
            "int64 overflow while calculating voting power needed; "
            "please provide smaller trustLevel numerator"
        )
    voting_power_needed = total_mul // trust_level.denominator
    ignore = _ignore_not_for_block
    count = _count_all
    if _should_batch_verify(vals, commit):
        _verify_commit_batch(
            chain_id, vals, commit, voting_power_needed, ignore, count, False, False
        )
    else:
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed, ignore, count, False, False
        )


def validate_hash(h: bytes) -> None:
    """validation.go:138-147."""
    if h and len(h) != tmhash.SIZE:
        raise ValueError(f"expected size to be {tmhash.SIZE} bytes, got {len(h)} bytes")


class PrepareUnsupported(Exception):
    """prepare_commit_batch cannot represent this commit/valset for the
    async seam (e.g. a non-columnar or mixed-key validator set); the
    caller falls back to the synchronous verify path, which handles
    every case the reference handles."""


def prepare_commit_light(chain_id: str, vals: ValidatorSet, block_id: BlockID,
                         height: int, commit: Commit):
    """verify_commit_light's host half (ISSUE 11 seam): the basic
    val/commit binding checks plus prepare_commit_batch with the light
    predicates. Returns (entries, conclude); (None, None) means the
    commit rode the sub-threshold single-signature path synchronously
    and is already fully verified. Raises exactly what
    verify_commit_light raises host-side, or PrepareUnsupported when the
    async seam cannot represent the set."""
    _verify_basic_vals_and_commit(vals, commit, height, block_id)
    voting_power_needed = vals.total_voting_power() * 2 // 3
    if not _should_batch_prepare(vals, commit):
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            _ignore_not_for_block, _count_all, False, True,
        )
        return None, None
    return prepare_commit_batch(
        chain_id, vals, commit, voting_power_needed,
        _ignore_not_for_block, _count_all, False, True,
    )


def prepare_commit_range(chain_id: str, vals: ValidatorSet, items):
    """Range form of the prepare seam (ISSUE 14): `items` is an ordered
    iterable of (height, block_id, commit) all claimed to be signed by
    the SAME validator set `vals` (the caller cut the range at every
    valset-changing height). Returns (prepared, synced):

      prepared  [(height, entries, conclude)] — device work per height,
                in range order; each conclude reproduces the sequential
                path's exact blame error for its height
      synced    [height] — heights that rode the sub-threshold
                single-signature path and are ALREADY fully verified

    Host-side failures raise exactly what verify_commit_light raises for
    the offending height (PrepareUnsupported included) — the caller is
    expected to fall back to per-height sequential verification for the
    range, which reproduces the same error byte-for-byte."""
    prepared = []
    synced = []
    for height, block_id, commit in items:
        entries, conclude = prepare_commit_light(
            chain_id, vals, block_id, height, commit
        )
        if entries is None:
            synced.append(height)
        else:
            prepared.append((height, entries, conclude))
    return prepared, synced


def prepare_commit_light_trusting(chain_id: str, vals: ValidatorSet,
                                  commit: Commit, trust_level: Fraction):
    """verify_commit_light_trusting's host half (ISSUE 11 seam): nil and
    overflow checks, by-address selection with double-vote detection and
    the trust-level tally — returning the sig work instead of verifying
    in place. Same return/raise contract as prepare_commit_light."""
    if vals is None:
        raise ValueError("nil validator set")
    if trust_level.denominator == 0:
        raise ValueError("trustLevel has zero Denominator")
    if commit is None:
        raise ValueError("nil commit")
    total_mul, overflow = safe_mul(vals.total_voting_power(), trust_level.numerator)
    if overflow:
        raise OverflowError(
            "int64 overflow while calculating voting power needed; "
            "please provide smaller trustLevel numerator"
        )
    voting_power_needed = total_mul // trust_level.denominator
    if not _should_batch_prepare(vals, commit):
        _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            _ignore_not_for_block, _count_all, False, False,
        )
        return None, None
    return prepare_commit_batch(
        chain_id, vals, commit, voting_power_needed,
        _ignore_not_for_block, _count_all, False, False,
    )


def _blame_conclude(sig_idxs, commit):
    """The verdict half of _verify_commit_batch over a device validity
    row: all-valid returns, otherwise the FIRST invalid lane maps back
    through the selection to the reference's blame string
    (validation.go:242-248)."""
    import numpy as _np

    def conclude(valid) -> None:
        valid_arr = _np.asarray(valid, dtype=bool)
        if valid_arr.size and valid_arr.all():
            return
        if not valid_arr.all() and valid_arr.size:
            idx = int(sig_idxs[int(_np.argmin(valid_arr))])
            sig = commit.signatures[idx]
            raise ValueError(
                f"wrong signature (#{idx}): {sig.signature.hex().upper()}"
            )
        raise RuntimeError(
            "BUG: batch verification failed with no invalid signatures"
        )

    return conclude


def prepare_commit_batch(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
):
    """The host half of _verify_commit_batch with the device verify
    EXTRACTED (ISSUE 11): selection, double-vote detection, length
    checks and the voting-power tally run here, but instead of calling
    bv.verify() the prepared EntryBlock is RETURNED (epoch metadata
    attached, so the shared AsyncBatchVerifier can coalesce it with
    other same-epoch work across requests) together with a
    conclude(valid) callable reproducing the exact blame errors.
    Host-side failures raise exactly what _verify_commit_batch raises
    before its verify call."""
    proposer = vals.get_proposer()
    cols = vals.ed25519_columns()
    scols = None if cols is not None else vals.secp256k1_columns()
    if (
        proposer is None
        or len(commit.signatures) < BATCH_VERIFY_THRESHOLD
        or (scols is None
            and not _batch.supports_batch_verifier(proposer.pub_key))
    ):
        raise RuntimeError(
            "unsupported signature algorithm or insufficient signatures for batch verification"
        )
    if cols is None and scols is None:
        # mixed/non-columnar set: ONE EntryBlock cannot represent it
        # (per-scheme kernels); mesh-aware callers take
        # prepare_commit_scheme_split, everyone else falls back to the
        # synchronous per-key path, which handles every case
        raise PrepareUnsupported("validator set is not single-scheme columnar")
    if look_up_by_index and cols is not None:
        fused = _fused_commit_prep(
            chain_id, vals, commit, voting_power_needed,
            ignore_sig, count_sig, count_all_signatures,
        )
        if fused is not None:
            sel_idx, tallied, eblk = fused
            if eblk is None:
                raise ErrNotEnoughVotingPowerSigned(
                    got=tallied, needed=voting_power_needed
                )
            return eblk, _blame_conclude(sel_idx, commit)
    selected, tallied = _select_commit_sigs(
        vals, commit, voting_power_needed,
        ignore_sig, count_sig, count_all_signatures, look_up_by_index,
    )
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(got=tallied, needed=voting_power_needed)
    import numpy as _np

    from ..ops import epoch_cache as _epoch
    from ..ops.entry_block import EntryBlock

    batch_sig_idxs = [i for i, _, _ in selected]
    with _span("verify_commit.sign_bytes", n=len(selected)):
        buf, offsets = commit.vote_sign_bytes_block(chain_id, batch_sig_idxs)
    # gather pub rows from the cached columns (key TYPE safety is
    # structural: ed25519_columns is None for any mixed set) and attach
    # the epoch metadata so warm epochs ship only per-sig data —
    # val_idx rows are VALIDATOR-SET rows (the device-table gather key),
    # which differ from signature indexes on the by-address path
    rows = _np.asarray([r for _, r, _ in selected], dtype=_np.int32)
    if cols is not None:
        scheme, pub, pub_aux = "ed25519", cols[0][rows], None
    else:
        # all-secp256k1 committee (ISSUE 19): 33-byte SEC1 rows split
        # into the prefix column so downstream columns stay 32-wide
        raw = scols[0][rows]
        scheme = "secp256k1"
        pub_aux = _np.ascontiguousarray(raw[:, 0])
        pub = _np.ascontiguousarray(raw[:, 1:])
    epoch_key = _epoch.note_valset(vals)
    sigs_list = commit.signatures
    sig = _np.frombuffer(
        b"".join(sigs_list[i].signature for i in batch_sig_idxs),
        dtype=_np.uint8,
    ).reshape(len(selected), 64)
    eblk = EntryBlock(pub, sig, buf, offsets,
                      val_idx=rows, epoch_key=epoch_key,
                      scheme=scheme, pub_aux=pub_aux)
    return eblk, _blame_conclude(batch_sig_idxs, commit)


def prepare_commit_scheme_split(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool] = _ignore_not_for_block,
    count_sig: Callable[[CommitSig], bool] = _count_all,
    count_all_signatures: bool = False,
    look_up_by_index: bool = True,
):
    """Mixed-committee prep (ISSUE 19): selection and tally run ONCE
    (same _select_commit_sigs the sequential path shares), then the
    selected lanes split per key scheme into one EntryBlock each —
    submitted together, the mesh packer lands both schemes in different
    lanes of the SAME superbatch, so a mixed commit still costs one
    dispatch. Returns (blocks, conclude): `blocks` is the per-scheme
    EntryBlock list in (ed25519, secp256k1) order and `conclude` takes
    the verdict rows CONCATENATED in that block order, reproducing the
    sequential path's exact blame string (first invalid lane in
    signature order, not concat order). Raises PrepareUnsupported when
    any key is neither scheme."""
    view = vals.scheme_rows()
    if view is None:
        raise PrepareUnsupported("validator set has non-device key schemes")
    kinds, pub32, aux = view
    selected, tallied = _select_commit_sigs(
        vals, commit, voting_power_needed,
        ignore_sig, count_sig, count_all_signatures, look_up_by_index,
    )
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(
            got=tallied, needed=voting_power_needed
        )
    import numpy as _np

    from ..ops.entry_block import EntryBlock

    per: dict = {0: [], 1: []}
    for sig_idx, val_row, _ in selected:
        per[int(kinds[val_row])].append((sig_idx, val_row))
    blocks = []
    parts_sig_idxs = []
    sigs_list = commit.signatures
    for kind, scheme in ((0, "ed25519"), (1, "secp256k1")):
        lanes = per[kind]
        if not lanes:
            continue
        sig_idxs = [i for i, _ in lanes]
        with _span("verify_commit.sign_bytes", n=len(lanes), scheme=scheme):
            buf, offsets = commit.vote_sign_bytes_block(chain_id, sig_idxs)
        rows = _np.asarray([r for _, r in lanes], dtype=_np.int32)
        sig = _np.frombuffer(
            b"".join(sigs_list[i].signature for i in sig_idxs),
            dtype=_np.uint8,
        ).reshape(len(lanes), 64)
        blocks.append(EntryBlock(
            pub32[rows], sig, buf, offsets, val_idx=rows,
            scheme=scheme,
            pub_aux=(_np.ascontiguousarray(aux[rows])
                     if scheme == "secp256k1" else None),
        ))
        parts_sig_idxs.append(sig_idxs)
    all_idx = _np.concatenate(
        [_np.asarray(p, dtype=_np.int64) for p in parts_sig_idxs]
    ) if parts_sig_idxs else _np.zeros(0, dtype=_np.int64)

    def conclude(valid) -> None:
        valid_arr = _np.asarray(valid, dtype=bool)
        if valid_arr.size and valid_arr.all():
            return
        if not valid_arr.all() and valid_arr.size:
            # first invalid lane in SIGNATURE order: the concat order is
            # per-scheme, so min() over the offending sig indexes — not
            # argmin over the row — matches the sequential walk
            idx = int(all_idx[~valid_arr].min())
            sig = commit.signatures[idx]
            raise ValueError(
                f"wrong signature (#{idx}): {sig.signature.hex().upper()}"
            )
        raise RuntimeError(
            "BUG: batch verification failed with no invalid signatures"
        )

    return blocks, conclude


# -- BLS12-381 aggregated commits (ISSUE 20) --------------------------------
#
# Blame strings are built ONCE by the helpers below and shared by the
# sequential reference walk and the batched conclude(), so the
# byte-exactness the acceptance gate pins cannot drift between paths.

_AGG_APK_IDENTITY = "aggregate pubkey is the identity"


def _agg_sig_blame(word: str, sig: bytes) -> str:
    return f"{word} aggregate signature: {sig.hex().upper()}"


def _agg_pub_blame(word: str, idx: int) -> str:
    return f"{word} aggregate pubkey (validator #{idx})"


def _agg_basic_and_tally(vals, block_id, height, agg,
                         voting_power_needed: int):
    """Shared host half of both aggregated-commit paths: shape checks,
    bitmap-size sanity, then the power tally — which runs BEFORE any
    crypto (a commit that cannot reach quorum must not spend pairings).
    Returns the signer validator rows in ascending order."""
    if vals is None:
        raise ValueError("nil validator set")
    if agg is None:
        raise ValueError("nil commit")
    if agg.signers is None or agg.signers.size() != vals.size():
        raise ErrInvalidCommitSignatures(
            vals.size(),
            agg.signers.size() if agg.signers is not None else 0,
        )
    if height != agg.height:
        raise ErrInvalidCommitHeight(height, agg.height)
    if block_id != agg.block_id:
        raise ValueError(
            f"invalid commit -- wrong block ID: want {block_id}, "
            f"got {agg.block_id}"
        )
    idxs = agg.signers.get_true_indices()
    tallied = sum(vals.validators[i].voting_power for i in idxs)
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(
            got=tallied, needed=voting_power_needed
        )
    return idxs


def verify_aggregated_commit(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, agg
) -> None:
    """Sequential reference for an aggregated commit (the pure-Python
    oracle walk the batched path is pinned byte-exact against). Check
    order IS the contract: basic shape -> bitmap size -> power tally
    (before any crypto) -> aggregate signature status -> pubkey statuses
    in ascending validator order -> apk-is-identity -> the one pairing
    check."""
    from ..crypto import bls12381 as _bls

    voting_power_needed = vals.total_voting_power() * 2 // 3
    with _span("verify_agg_commit", n=1, height=height):
        idxs = _agg_basic_and_tally(
            vals, block_id, height, agg, voting_power_needed
        )
        sig = bytes(agg.signature)
        _, reason = _bls.signature_status(sig)
        if reason is not None:
            raise ValueError(_agg_sig_blame(reason, sig))
        pubs = []
        for i in idxs:
            pub = vals.validators[i].pub_key.bytes()
            _, preason = _bls.pubkey_status(pub)
            if preason is not None:
                raise ValueError(_agg_pub_blame(preason, i))
            pubs.append(pub)
        apk, _ = _bls.aggregate_pubkeys(pubs)
        if apk is None:
            raise ValueError(_AGG_APK_IDENTITY)
        if not _bls.fast_aggregate_verify(
            pubs, agg.sign_bytes(chain_id), sig
        ):
            raise ValueError(_agg_sig_blame("wrong", sig))


def prepare_aggregated_commit(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    agg,
    k_hint: int = 1,
):
    """The async-seam half for an aggregated commit: host checks run
    here (raising exactly what the sequential walk raises), then the
    commit is returned as a one-row AggBlock plus a conclude(codes)
    decoding the device lane's int32 verdict code back into the SAME
    pinned blame strings. The shared pipeline coalesces same-committee
    AggBlocks, so K concurrent commits still land in one fused
    multi-pairing launch.

    `k_hint` is the caller's concurrency estimate: below
    backend.BLS_DEVICE_THRESHOLD a fused launch cannot amortize its
    final exponentiation, so the commit verifies synchronously through
    the oracle and (None, None) is returned."""
    from ..ops import backend as _backend

    if k_hint < _backend.BLS_DEVICE_THRESHOLD:
        verify_aggregated_commit(chain_id, vals, block_id, height, agg)
        return None, None
    voting_power_needed = vals.total_voting_power() * 2 // 3
    idxs = _agg_basic_and_tally(
        vals, block_id, height, agg, voting_power_needed
    )
    cols = vals.bls12381_columns()
    if cols is None:
        raise PrepareUnsupported(
            "validator set is not bls12381-columnar"
        )
    pub48 = cols[0]
    import numpy as _np

    from ..ops import epoch_cache as _epoch
    from ..ops.entry_block import AggBlock

    bits = _np.zeros(vals.size(), dtype=bool)
    bits[idxs] = True
    _epoch.note_valset(vals)  # register/refresh the G1 epoch tables
    sig = bytes(agg.signature)
    blk = AggBlock.from_commits(
        [(bits, agg.sign_bytes(chain_id), sig)], pub48, vals.hash()
    )

    def conclude(codes) -> None:
        from ..crypto import bls12381 as _bls
        from ..ops import bls_verify as _bv

        code = int(_np.asarray(codes).reshape(-1)[0])
        if code == _bv.CODE_VALID:
            return
        if code == _bv.CODE_PAIRING:
            raise ValueError(_agg_sig_blame("wrong", sig))
        if code == _bv.CODE_APK_IDENTITY:
            raise ValueError(_AGG_APK_IDENTITY)
        word = _bv.SIG_CODE_WORDS.get(code)
        if word is not None:
            raise ValueError(_agg_sig_blame(word, sig))
        if code >= _bv.CODE_PUB_BASE:
            i = code - _bv.CODE_PUB_BASE
            # the word re-derives from the committee snapshot — the
            # status is memoized per key bytes, so this is a dict hit
            word = _bls.pubkey_status(pub48[i].tobytes())[1]
            raise ValueError(_agg_pub_blame(word or "malformed", i))
        raise RuntimeError(f"BUG: unknown BLS verdict code {code}")

    return blk, conclude


def _select_commit_sigs(
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
):
    """Selection + tally half of the batch path (validation.go:152-240):
    flag filtering, by-index/by-address lookup with double-vote
    detection, signature-length checks, and the voting-power tally with
    the reference's early-stop semantics. Returns (selected, tallied)
    with selected = [(sig_idx, val_row, validator), ...] in signature
    order — val_row is the validator's row in `vals` (== sig_idx when
    looking up by index). Raises exactly the errors the inline selection
    raised. Shared by _verify_commit_batch and prepare_commit_batch so
    the sequential and batched-service paths cannot drift."""
    tallied = 0
    if count_all_signatures and look_up_by_index and ignore_sig is _ignore_absent:
        # verify_commit's exact predicate set on a 10k-validator commit is
        # the benchmark hot path: flag-attribute listcomps cut the
        # 3-calls-per-signature selection ~3x. The whole selection is
        # GIL-held, so this directly bounds how many concurrent commit
        # verifies the async device pipeline can keep fed.
        from .block import BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT

        sigs = commit.signatures
        validators = vals.validators
        flags = [c.block_id_flag for c in sigs]
        selected = [
            (i, i, validators[i])
            for i, f in enumerate(flags)
            if f != BLOCK_ID_FLAG_ABSENT
        ]
        if any(len(sigs[i].signature) != 64 for i, _, _ in selected):
            raise ValueError("invalid signature length")
        if count_sig is _count_for_block:
            tallied = sum(
                validators[i].voting_power
                for i, f in enumerate(flags)
                if f == BLOCK_ID_FLAG_COMMIT
            )
        else:
            tallied = sum(v.voting_power for _, _, v in selected)
        return selected, tallied
    selected = []  # (sig_idx, val_row, val) in signature order
    seen_vals: dict = {}
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val_row, val = idx, vals.validators[idx]
        else:
            val_row, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                continue
            if val_row in seen_vals:
                raise ValueError(
                    f"double vote from {val} ({seen_vals[val_row]} and {idx})"
                )
            seen_vals[val_row] = idx
        # length check here, not at the deferred bv.add below — the
        # error must surface per-lane before the voting-power tally
        # concludes, exactly as when add() ran inside this loop
        # (BatchVerifier.Add order, crypto/ed25519/ed25519.go:203-217)
        if len(commit_sig.signature) != 64:
            raise ValueError("invalid signature length")
        selected.append((idx, val_row, val))
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    return selected, tallied


def _fused_commit_prep(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
):
    """Columnar fast path: CommitBlock + validator columns through ONE
    fused prep call (ops/commit_prep.py — native GIL-released when
    built). Returns (sel_idx, tallied, EntryBlock-or-None) or None when
    this commit/valset/predicate combination is not columnar-
    representable (the object path below then reproduces the exact
    legacy behavior and errors)."""
    from ..ops import commit_prep as _cp

    if ignore_sig is _ignore_not_for_block:
        mode = _cp.MODE_SELECT_COMMIT_ONLY
    elif ignore_sig is _ignore_absent:
        mode = 0
    else:
        return None
    if count_sig is _count_for_block:
        mode |= _cp.MODE_COUNT_FOR_BLOCK
    elif count_sig is not _count_all:
        return None
    if not count_all_signatures:
        mode |= _cp.MODE_EARLY_STOP
    with _span("verify_commit.prep_fused", n=len(commit.signatures)):
        return _cp.prep_commit_from(
            commit, vals, chain_id, voting_power_needed, mode
        )


def _verify_commit_batch(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """validation.go:152-263."""
    proposer = vals.get_proposer()
    bv = _batch.create_batch_verifier(proposer.pub_key if proposer else None)
    if bv is None or len(commit.signatures) < BATCH_VERIFY_THRESHOLD:
        raise RuntimeError(
            "unsupported signature algorithm or insufficient signatures for batch verification"
        )
    add_block = getattr(bv, "add_block", None)
    if look_up_by_index and add_block is not None:
        fused = _fused_commit_prep(
            chain_id,
            vals,
            commit,
            voting_power_needed,
            ignore_sig,
            count_sig,
            count_all_signatures,
        )
        if fused is not None:
            import numpy as _np

            sel_idx, tallied, eblk = fused
            if eblk is None:
                raise ErrNotEnoughVotingPowerSigned(
                    got=tallied, needed=voting_power_needed
                )
            # key TYPE safety is proven by ed25519_columns (all-ed25519
            # or the fused path is not taken); signature lengths are
            # structural in the CommitBlock's (n, 64) column
            add_block(eblk)
            with _span("verify_commit.verify", n=len(eblk)):
                ok, valid_sigs = bv.verify()
            if ok:
                return
            # vectorized blame: first invalid lane via argmin over the
            # bool verdict array (no per-entry Python scan)
            valid_arr = _np.asarray(valid_sigs, dtype=bool)
            if not valid_arr.all() and valid_arr.size:
                idx = int(sel_idx[int(_np.argmin(valid_arr))])
                sig = commit.signatures[idx]
                raise ValueError(
                    f"wrong signature (#{idx}): {sig.signature.hex().upper()}"
                )
            raise RuntimeError(
                "BUG: batch verification failed with no invalid signatures"
            )
    sel_rows, tallied = _select_commit_sigs(
        vals, commit, voting_power_needed,
        ignore_sig, count_sig, count_all_signatures, look_up_by_index,
    )
    selected = [(idx, val) for idx, _, val in sel_rows]
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(got=tallied, needed=voting_power_needed)
    batch_sig_idxs = [idx for idx, _ in selected]
    add_block = getattr(bv, "add_block", None)
    if add_block is not None:
        # Columnar zero-copy path: the sign bytes land in ONE contiguous
        # buffer + offset table (no per-lane PyBytes), pub/sig join once
        # into (n, 32)/(n, 64) arrays, and the EntryBlock rides by
        # reference through the pipeline to the kernel prep. The per-key
        # TYPE check rides along (`keys`) — a mixed-key validator set
        # must fail exactly as per-entry add() did.
        import numpy as _np

        from ..ops.entry_block import EntryBlock

        with _span("verify_commit.sign_bytes", n=len(selected)):
            buf, offsets = commit.vote_sign_bytes_block(
                chain_id, batch_sig_idxs
            )
        sigs_list = commit.signatures
        n_sel = len(selected)
        keys = [val.pub_key for _, val in selected]
        pub_b = b"".join(k.bytes() for k in keys)
        if len(pub_b) != 32 * n_sel:
            # a wrong-size key (e.g. secp256k1 in an ed25519 set) must
            # surface as the same error per-entry add() raised, not as a
            # reshape failure
            raise TypeError("pubkey is not ed25519")
        pub = _np.frombuffer(pub_b, dtype=_np.uint8).reshape(n_sel, 32)
        sig = _np.frombuffer(
            b"".join(sigs_list[idx].signature for idx, _ in selected),
            dtype=_np.uint8,
        ).reshape(n_sel, 64)
        add_block(EntryBlock(pub, sig, buf, offsets), keys=keys)
    else:
        # one batch sign-bytes composition for all selected lanes (native
        # composer; the per-lane Python encode was the dominant host cost
        # on large commits)
        with _span("verify_commit.sign_bytes", n=len(selected)):
            sign_bytes = commit.vote_sign_bytes_many(
                chain_id, [i for i, _ in selected]
            )
        add_many = getattr(bv, "add_entries", None)
        if add_many is not None:
            # bulk accumulate in ONE pass: lengths were checked during
            # selection and the key type during verifier creation, so the
            # entry build can go straight to wire bytes (every extra
            # 10k-element pass here is GIL-held and serializes concurrent
            # commit verifies)
            sigs_list = commit.signatures
            add_many(
                [
                    (val.pub_key, sb, sigs_list[idx].signature)
                    for (idx, val), sb in zip(selected, sign_bytes, strict=True)
                ],
                lengths_checked=True,
            )
        else:
            for (idx, val), sb in zip(selected, sign_bytes, strict=True):
                bv.add(val.pub_key, sb, commit.signatures[idx].signature)
    with _span("verify_commit.verify", n=len(selected)):
        ok, valid_sigs = bv.verify()
    if ok:
        return
    import numpy as _np

    valid_arr = _np.asarray(valid_sigs, dtype=bool)
    if not valid_arr.all() and valid_arr.size:
        idx = batch_sig_idxs[int(_np.argmin(valid_arr))]
        sig = commit.signatures[idx]
        raise ValueError(
            f"wrong signature (#{idx}): {sig.signature.hex().upper()}"
        )
    raise RuntimeError("BUG: batch verification failed with no invalid signatures")


def _verify_commit_single(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    look_up_by_index: bool,
) -> None:
    """validation.go:265-334."""
    tallied = 0
    checked = 0
    seen_vals: dict = {}
    for idx, commit_sig in enumerate(commit.signatures):
        if ignore_sig(commit_sig):
            continue
        if look_up_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(commit_sig.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise ValueError(
                    f"double vote from {val} ({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        vote_sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        if not val.pub_key.verify_signature(vote_sign_bytes, commit_sig.signature):
            raise ValueError(
                f"wrong signature (#{idx}): {commit_sig.signature.hex().upper()}"
            )
        checked += 1
        if count_sig(commit_sig):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            _note_host_verified(checked)
            return
    _note_host_verified(checked)
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(got=tallied, needed=voting_power_needed)


def _verify_basic_vals_and_commit(
    vals: Optional[ValidatorSet],
    commit: Optional[Commit],
    height: int,
    block_id: BlockID,
) -> None:
    """validation.go:336-358."""
    if vals is None:
        raise ValueError("nil validator set")
    if commit is None:
        raise ValueError("nil commit")
    if vals.size() != len(commit.signatures):
        raise ErrInvalidCommitSignatures(vals.size(), len(commit.signatures))
    if height != commit.height:
        raise ErrInvalidCommitHeight(height, commit.height)
    if block_id != commit.block_id:
        raise ValueError(
            f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
        )
