"""Event types and keys.

Reference parity: types/events.go — event string constants and the
composite keys (tm.event, tx.hash, tx.height) the indexer and RPC
subscriptions filter on.
"""

from __future__ import annotations

EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"

EventNewBlock = "NewBlock"
EventNewBlockHeader = "NewBlockHeader"
EventNewEvidence = "NewEvidence"
EventTx = "Tx"
EventValidatorSetUpdates = "ValidatorSetUpdates"

# consensus round events
EventNewRound = "NewRound"
EventNewRoundStep = "NewRoundStep"
EventCompleteProposal = "CompleteProposal"
EventPolka = "Polka"
EventRelock = "Relock"
EventLock = "Lock"
EventUnlock = "Unlock"
EventVote = "Vote"
EventValidBlock = "ValidBlock"
EventTimeoutPropose = "TimeoutPropose"
EventTimeoutWait = "TimeoutWait"


def query_for_event(event_type: str) -> str:
    return f"{EVENT_TYPE_KEY}='{event_type}'"
