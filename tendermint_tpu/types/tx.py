"""Tx helpers — hashing, merkle proofs over block data.

Reference parity: types/tx.go (Tx.Hash = SHA256, Txs.Hash = merkle root of
raw txs, TxProof)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

from ..crypto import merkle, tmhash


def tx_hash(tx: bytes) -> bytes:
    """types/tx.go:31-33."""
    return tmhash.sum_sha256(tx)


def txs_hash(txs: Sequence[bytes]) -> bytes:
    return merkle.hash_from_byte_slices(list(txs))


def tx_key(tx: bytes) -> bytes:
    """Mempool cache key (types/tx.go TxKey): the full SHA256."""
    return tx_hash(tx)


@dataclass(frozen=True)
class TxProof:
    """types/tx.go:59-89: inclusion proof of a tx in a block's data hash."""

    root_hash: bytes
    data: bytes
    proof: merkle.Proof

    def validate(self, data_hash: bytes) -> None:
        if data_hash != self.root_hash:
            raise ValueError("proof matches different data hash")
        self.leaf_check()

    def leaf_check(self) -> None:
        self.proof.verify(self.root_hash, self.data)


def tx_proof(txs: Sequence[bytes], index: int) -> TxProof:
    root, proofs = merkle.proofs_from_byte_slices(list(txs))
    return TxProof(root_hash=root, data=bytes(txs[index]), proof=proofs[index])


def compute_proto_size_overhead(n_txs: int, total_tx_bytes: int) -> int:
    """Approximation of types.ComputeProtoSizeForTxs for block-size checks:
    field tag + varint length per tx."""
    overhead = 0
    # each tx: tag(1) + uvarint(len)
    # conservative: 1 + 5 bytes per tx
    overhead += n_txs * 6
    return total_tx_bytes + overhead
