"""Vote — a prevote/precommit signed by a validator.

Reference parity: types/vote.go. Sign bytes are the uvarint-delimited
proto encoding of CanonicalVote (vote.go:93-101); Vote.Verify checks the
signer address and the signature over them (vote.go:147-165).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import PubKey, tmhash
from ..wire import canonical as _canon
from ..wire.canonical import Timestamp
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, to_signed32, to_signed64
from .block import BlockID, MAX_SIGNATURE_SIZE, CommitSig, BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, BLOCK_ID_FLAG_NIL

PREVOTE_TYPE = _canon.SIGNED_MSG_TYPE_PREVOTE
PRECOMMIT_TYPE = _canon.SIGNED_MSG_TYPE_PRECOMMIT


def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE_TYPE, PRECOMMIT_TYPE)


class ErrVoteInvalidValidatorAddress(ValueError):
    pass


class ErrVoteInvalidSignature(ValueError):
    pass


@dataclass(frozen=True)
class Vote:
    """types/vote.go:51-63."""

    type: int = 0
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    timestamp: Timestamp = field(default_factory=Timestamp.zero)
    validator_address: bytes = b""
    validator_index: int = 0
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        """VoteSignBytes (vote.go:93-101)."""
        return _canon.canonical_vote_sign_bytes(
            chain_id=chain_id,
            msg_type=self.type,
            height=self.height,
            round_=self.round,
            block_id=self.block_id.canonical(),
            timestamp=self.timestamp,
        )

    def verify_address(self, pub_key: PubKey) -> None:
        """The host half of Verify (vote.go:148-153): the signer address
        must match the public key. Split out so the batched ingress path
        (consensus/vote_ingress.py) can run it BEFORE device dispatch and
        raise the same error the sequential path raises."""
        if pub_key.address() != self.validator_address:
            raise ErrVoteInvalidValidatorAddress("invalid validator address")

    def verify(self, chain_id: str, pub_key: PubKey) -> None:
        """vote.go:147-165: address match + signature over sign bytes."""
        self.verify_address(pub_key)
        if not pub_key.verify_signature(self.sign_bytes(chain_id), self.signature):
            raise ErrVoteInvalidSignature("invalid signature")

    def to_commit_sig(self) -> CommitSig:
        """vote.go:246-266 (CommitSig): flag from the vote's BlockID."""
        if self.block_id.is_complete():
            flag = BLOCK_ID_FLAG_COMMIT
        elif self.block_id.is_zero():
            flag = BLOCK_ID_FLAG_NIL
        else:
            raise ValueError(f"blockID {self.block_id} is not commit nor nil")
        return CommitSig(
            block_id_flag=flag,
            validator_address=self.validator_address,
            timestamp=self.timestamp,
            signature=self.signature,
        )

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.type)
        w.write_varint(2, self.height)
        w.write_varint(3, self.round)
        w.write_message(4, self.block_id.encode(), always=True)
        w.write_message(5, _canon.encode_timestamp(self.timestamp), always=True)
        w.write_bytes(6, self.validator_address)
        w.write_varint(7, self.validator_index)
        w.write_bytes(8, self.signature)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Vote":
        f = decode_message(data)
        ts_f = decode_message(field_bytes(f, 5))
        return cls(
            type=field_int(f, 1),
            height=to_signed64(field_int(f, 2)),
            round=to_signed32(field_int(f, 3)),
            block_id=BlockID.decode(field_bytes(f, 4)),
            timestamp=Timestamp(
                seconds=to_signed64(field_int(ts_f, 1)),
                nanos=to_signed32(field_int(ts_f, 2)),
            ),
            validator_address=field_bytes(f, 6),
            validator_index=to_signed32(field_int(f, 7)),
            signature=field_bytes(f, 8),
        )

    def validate_basic(self) -> None:
        """vote.go:167-200."""
        if not is_vote_type_valid(self.type):
            raise ValueError("invalid Type")
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round < 0:
            raise ValueError("negative Round")
        self.block_id.validate_basic()
        if not self.block_id.is_zero() and not self.block_id.is_complete():
            raise ValueError(f"blockID must be either empty or complete, got: {self.block_id}")
        if len(self.validator_address) != tmhash.TRUNCATED_SIZE:
            raise ValueError("expected ValidatorAddress size")
        if self.validator_index < 0:
            raise ValueError("negative ValidatorIndex")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > MAX_SIGNATURE_SIZE:
            raise ValueError("signature is too big")

    def is_absent(self) -> bool:
        return False


def vote_from_commit_sig(
    cs: CommitSig, commit_block_id: BlockID, height: int, round_: int, idx: int
) -> Optional[Vote]:
    """Commit.GetVote (types/block.go:803-815)."""
    if cs.is_absent():
        return None
    return Vote(
        type=PRECOMMIT_TYPE,
        height=height,
        round=round_,
        block_id=cs.block_id(commit_block_id),
        timestamp=cs.timestamp,
        validator_address=cs.validator_address,
        validator_index=idx,
        signature=cs.signature,
    )
