"""ConsensusParams — on-chain consensus parameters.

Reference parity: types/params.go + proto/tendermint/types/params.pb.go.
HashConsensusParams hashes only HashedParams{BlockMaxBytes, BlockMaxGas}
(params.go HashConsensusParams) — kept bit-exact.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import List, Optional

from ..crypto import tmhash
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, field_repeated_bytes, to_signed64

MAX_BLOCK_SIZE_BYTES = 104857600  # 100MB (types/params.go:21)

ABCI_PUBKEY_TYPE_ED25519 = "ed25519"
ABCI_PUBKEY_TYPE_SECP256K1 = "secp256k1"
ABCI_PUBKEY_TYPE_SR25519 = "sr25519"

HOUR_NS = 3600 * 10**9


@dataclass(frozen=True)
class BlockParams:
    max_bytes: int = 22020096  # 21MB
    max_gas: int = -1

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.max_bytes)
        w.write_varint(2, self.max_gas)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "BlockParams":
        f = decode_message(data)
        return cls(
            max_bytes=to_signed64(field_int(f, 1)),
            max_gas=to_signed64(field_int(f, 2)),
        )


@dataclass(frozen=True)
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * HOUR_NS  # stdduration on the wire
    max_bytes: int = 1048576  # 1MB

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.max_age_num_blocks)
        dur = ProtoWriter()
        dur.write_varint(1, self.max_age_duration_ns // 10**9)
        dur.write_varint(2, self.max_age_duration_ns % 10**9)
        w.write_message(2, dur.bytes(), always=True)
        w.write_varint(3, self.max_bytes)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "EvidenceParams":
        f = decode_message(data)
        d = decode_message(field_bytes(f, 2))
        ns = to_signed64(field_int(d, 1)) * 10**9 + to_signed64(field_int(d, 2))
        return cls(
            max_age_num_blocks=to_signed64(field_int(f, 1)),
            max_age_duration_ns=ns,
            max_bytes=to_signed64(field_int(f, 3)),
        )


@dataclass(frozen=True)
class ValidatorParams:
    pub_key_types: tuple = (ABCI_PUBKEY_TYPE_ED25519,)

    def encode(self) -> bytes:
        w = ProtoWriter()
        for t in self.pub_key_types:
            w.write_string(1, t, always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorParams":
        f = decode_message(data)
        return cls(pub_key_types=tuple(raw.decode() for raw in field_repeated_bytes(f, 1)))

    def is_valid_pubkey_type(self, t: str) -> bool:
        return t in self.pub_key_types


@dataclass(frozen=True)
class VersionParams:
    app_version: int = 0

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_varint(1, self.app_version)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "VersionParams":
        f = decode_message(data)
        return cls(app_version=field_int(f, 1))


@dataclass(frozen=True)
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)

    def hash_consensus_params(self) -> bytes:
        """params.go HashConsensusParams: SHA256 of proto HashedParams
        {1 block_max_bytes, 2 block_max_gas}."""
        w = ProtoWriter()
        w.write_varint(1, self.block.max_bytes)
        w.write_varint(2, self.block.max_gas)
        return tmhash.sum_sha256(w.bytes())

    def validate_consensus_params(self) -> None:
        """params.go:129-170."""
        if self.block.max_bytes <= 0:
            raise ValueError(f"block.MaxBytes must be greater than 0. Got {self.block.max_bytes}")
        if self.block.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError(
                f"block.MaxBytes is too big. {self.block.max_bytes} > {MAX_BLOCK_SIZE_BYTES}"
            )
        if self.block.max_gas < -1:
            raise ValueError(f"block.MaxGas must be greater or equal to -1. Got {self.block.max_gas}")
        if self.evidence.max_age_num_blocks <= 0:
            raise ValueError("evidence.MaxAgeNumBlocks must be greater than 0")
        if self.evidence.max_age_duration_ns <= 0:
            raise ValueError("evidence.MaxAgeDuration must be greater than 0")
        if self.evidence.max_bytes > self.block.max_bytes:
            raise ValueError("evidence.MaxBytes is greater than block.MaxBytes")
        if self.evidence.max_bytes < 0:
            raise ValueError("evidence.MaxBytes must be non negative")
        if not self.validator.pub_key_types:
            raise ValueError("len(validator.PubKeyTypes) must be greater than 0")
        for t in self.validator.pub_key_types:
            if t not in (
                ABCI_PUBKEY_TYPE_ED25519,
                ABCI_PUBKEY_TYPE_SECP256K1,
                ABCI_PUBKEY_TYPE_SR25519,
            ):
                raise ValueError(f"unknown pubkey type {t}")

    def update_consensus_params(self, updates: Optional["ConsensusParams"]) -> "ConsensusParams":
        """params.go UpdateConsensusParams: nil sub-messages keep current."""
        if updates is None:
            return self
        return updates

    def update_from_proto_subset(
        self,
        block: Optional[BlockParams],
        evidence: Optional[EvidenceParams],
        validator: Optional[ValidatorParams],
        version: Optional[VersionParams],
    ) -> "ConsensusParams":
        res = self
        if block is not None:
            res = replace(res, block=block)
        if evidence is not None:
            res = replace(res, evidence=evidence)
        if validator is not None:
            res = replace(res, validator=validator)
        if version is not None:
            res = replace(res, version=version)
        return res

    def encode(self) -> bytes:
        w = ProtoWriter()
        w.write_message(1, self.block.encode(), always=True)
        w.write_message(2, self.evidence.encode(), always=True)
        w.write_message(3, self.validator.encode(), always=True)
        w.write_message(4, self.version.encode(), always=True)
        return w.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "ConsensusParams":
        f = decode_message(data)
        return cls(
            block=BlockParams.decode(field_bytes(f, 1)),
            evidence=EvidenceParams.decode(field_bytes(f, 2)),
            validator=ValidatorParams.decode(field_bytes(f, 3)),
            version=VersionParams.decode(field_bytes(f, 4)),
        )

    @classmethod
    def decode_update_subset(cls, data: bytes):
        """Decode an ABCI ConsensusParams update where absent sub-messages
        mean 'no change' — returns the 4-tuple of Optionals."""
        f = decode_message(data)
        return (
            BlockParams.decode(field_bytes(f, 1)) if 1 in f else None,
            EvidenceParams.decode(field_bytes(f, 2)) if 2 in f else None,
            ValidatorParams.decode(field_bytes(f, 3)) if 3 in f else None,
            VersionParams.decode(field_bytes(f, 4)) if 4 in f else None,
        )


def default_consensus_params() -> ConsensusParams:
    return ConsensusParams()
