"""GenesisDoc — the chain's origin document (genesis.json).

Reference parity: types/genesis.go. JSON layout matches the reference's
libs/json type-tagged encoding: pub keys serialize as
{"type": "tendermint/PubKeyEd25519", "value": "<base64>"}.
"""

from __future__ import annotations

import base64
import json
from dataclasses import dataclass, field
from typing import Any, List, Optional

from ..crypto import PubKey
from ..crypto import ed25519 as _ed25519
from ..crypto import secp256k1 as _secp256k1
from ..crypto import sr25519 as _sr25519
from ..wire.canonical import Timestamp
from .params import ConsensusParams, default_consensus_params
from .validator_set import Validator

MAX_CHAIN_ID_LEN = 50  # types/genesis.go:23


_KEY_NAME_TO_CLS = {
    _ed25519.PUB_KEY_NAME: (_ed25519.PubKey, _ed25519.KEY_TYPE),
    _secp256k1.PUB_KEY_NAME: (_secp256k1.PubKey, _secp256k1.KEY_TYPE),
    _sr25519.PUB_KEY_NAME: (_sr25519.PubKey, _sr25519.KEY_TYPE),
}
_KEY_TYPE_TO_NAME = {
    _ed25519.KEY_TYPE: _ed25519.PUB_KEY_NAME,
    _secp256k1.KEY_TYPE: _secp256k1.PUB_KEY_NAME,
    _sr25519.KEY_TYPE: _sr25519.PUB_KEY_NAME,
}


def pubkey_to_json(pk: PubKey) -> dict:
    return {
        "type": _KEY_TYPE_TO_NAME[pk.type()],
        "value": base64.b64encode(pk.bytes()).decode(),
    }


def pubkey_from_json(obj: dict) -> PubKey:
    cls, _ = _KEY_NAME_TO_CLS[obj["type"]]
    return cls(base64.b64decode(obj["value"]))


@dataclass
class GenesisValidator:
    """types/genesis.go:36-42."""

    address: bytes
    pub_key: PubKey
    power: int
    name: str = ""


@dataclass
class GenesisDoc:
    """types/genesis.go:44-55."""

    chain_id: str
    genesis_time: Timestamp = field(default_factory=lambda: Timestamp(0, 0))
    initial_height: int = 1
    consensus_params: Optional[ConsensusParams] = None
    validators: List[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: Any = None

    def validate_and_complete(self) -> None:
        """types/genesis.go:89-136."""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(f"chain_id in genesis doc is too long (max: {MAX_CHAIN_ID_LEN})")
        if self.initial_height < 0:
            raise ValueError(f"initial_height cannot be negative (got {self.initial_height})")
        if self.initial_height == 0:
            self.initial_height = 1
        if self.consensus_params is None:
            self.consensus_params = default_consensus_params()
        else:
            self.consensus_params.validate_consensus_params()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(f"genesis file cannot contain validators with no voting power: {v}")
            if v.address and v.pub_key.address() != v.address:
                raise ValueError(f"incorrect address for validator {i} in the genesis file")
            if not v.address:
                v.address = v.pub_key.address()

    def validator_hash(self) -> bytes:
        from .validator_set import ValidatorSet

        vals = [Validator.new(v.pub_key, v.power) for v in self.validators]
        return ValidatorSet.new(vals).hash()

    # -- JSON -----------------------------------------------------------

    def to_json(self) -> str:
        obj = {
            "genesis_time": _time_to_rfc3339(self.genesis_time),
            "chain_id": self.chain_id,
            "initial_height": str(self.initial_height),
            "consensus_params": _params_to_json(self.consensus_params),
            "validators": [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": pubkey_to_json(v.pub_key),
                    "power": str(v.power),
                    "name": v.name,
                }
                for v in self.validators
            ],
            "app_hash": self.app_hash.hex().upper(),
        }
        if self.app_state is not None:
            obj["app_state"] = self.app_state
        return json.dumps(obj, indent=2)

    @classmethod
    def from_json(cls, data: str) -> "GenesisDoc":
        obj = json.loads(data)
        doc = cls(
            chain_id=obj["chain_id"],
            genesis_time=_time_from_rfc3339(obj.get("genesis_time", "1970-01-01T00:00:00Z")),
            initial_height=int(obj.get("initial_height", "1") or 1),
            consensus_params=_params_from_json(obj.get("consensus_params")),
            validators=[
                GenesisValidator(
                    address=bytes.fromhex(v.get("address", "")),
                    pub_key=pubkey_from_json(v["pub_key"]),
                    power=int(v["power"]),
                    name=v.get("name", ""),
                )
                for v in obj.get("validators") or []
            ],
            app_hash=bytes.fromhex(obj.get("app_hash", "")),
            app_state=obj.get("app_state"),
        )
        doc.validate_and_complete()
        return doc

    def save_as(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.to_json())

    @classmethod
    def from_file(cls, path: str) -> "GenesisDoc":
        with open(path) as fh:
            return cls.from_json(fh.read())


def _time_to_rfc3339(ts: Timestamp) -> str:
    import datetime

    base = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    dt = base + datetime.timedelta(seconds=ts.seconds)
    s = dt.strftime("%Y-%m-%dT%H:%M:%S")
    if ts.nanos:
        s += f".{ts.nanos:09d}".rstrip("0")
    return s + "Z"


def _time_from_rfc3339(s: str) -> Timestamp:
    import datetime

    s = s.rstrip("Z")
    nanos = 0
    if "." in s:
        s, frac = s.split(".")
        nanos = int(frac.ljust(9, "0")[:9])
    dt = datetime.datetime.fromisoformat(s).replace(tzinfo=datetime.timezone.utc)
    return Timestamp(seconds=int(dt.timestamp()), nanos=nanos)


def _params_to_json(p: Optional[ConsensusParams]) -> Optional[dict]:
    if p is None:
        return None
    return {
        "block": {"max_bytes": str(p.block.max_bytes), "max_gas": str(p.block.max_gas)},
        "evidence": {
            "max_age_num_blocks": str(p.evidence.max_age_num_blocks),
            "max_age_duration": str(p.evidence.max_age_duration_ns),
            "max_bytes": str(p.evidence.max_bytes),
        },
        "validator": {"pub_key_types": list(p.validator.pub_key_types)},
        "version": {"app_version": str(p.version.app_version)},
    }


def _params_from_json(obj: Optional[dict]) -> Optional[ConsensusParams]:
    if obj is None:
        return None
    from .params import BlockParams, EvidenceParams, ValidatorParams, VersionParams

    return ConsensusParams(
        block=BlockParams(
            max_bytes=int(obj["block"]["max_bytes"]),
            max_gas=int(obj["block"]["max_gas"]),
        ),
        evidence=EvidenceParams(
            max_age_num_blocks=int(obj["evidence"]["max_age_num_blocks"]),
            max_age_duration_ns=int(obj["evidence"]["max_age_duration"]),
            max_bytes=int(obj["evidence"].get("max_bytes", "1048576")),
        ),
        validator=ValidatorParams(pub_key_types=tuple(obj["validator"]["pub_key_types"])),
        version=VersionParams(app_version=int(obj.get("version", {}).get("app_version", "0"))),
    )
