"""Soak harness (ISSUE 16): all four QoS tiers against ONE verifier.

`SoakDriver` runs a single cluster for a configurable VIRTUAL duration
and drives combined load through one shared `AsyncBatchVerifier`:

- **consensus** — the cluster commits heights normally (stepped
  consensus on the virtual clock); per-height commit latency is
  harvested from `HeightTimeline` rings in deterministic virtual time.
  A "commit echo" additionally re-verifies each freshly committed
  height's commit through the shared engine at `PRIORITY_CONSENSUS`,
  so the consensus lane carries real device traffic.
- **light** — request fleets verify the cluster's OWN recent headers
  against a height-1 trusted anchor through `LightVerifyService`
  (shared epoch-cache coupling with every other lane).
- **ingress** — signed-tx floods through an `IngressAccumulator`,
  timed per burst with a hard admission timeout, running straight
  through a mid-soak partition/heal fault.
- **replay** — a node crashed early rejoins via `CatchupDriver`
  (optionally from 1000+ heights behind with `catchup_at_height`),
  its ReplayEngine injected with the SAME shared verifier.
- **bls_agg** (ISSUE 20) — an aggregated-commit echo probe: a
  pre-signed BLS12-381 `AggregatedCommit` rides the same shared
  verifier each tick at `PRIORITY_CONSENSUS`, exercising the full
  prepare → AggBlock → fused-pairing-launch → conclude seam under
  mixed load, with its own wall-latency SLO budget.

A `TelemetrySampler` snapshots the gauge/counter surfaces on a SimClock
cadence; declarative `SLOBudget`s (consensus commit p99, light verdict
p99, ingress admission p99, replay heights/s floor) are evaluated at
the end — any breach, devcheck violation, or invariant failure makes
the run conclusively NOT ok, with the flight-recorder tail attached.

Determinism contract (simnet-determinism lint applies to this module):
every driver tick rides `SimClock.call_later`, so the event ORDER —
and therefore fingerprint and `schedule_digest()` — is a pure function
of (seed, config). Wall-clock latencies (`time.perf_counter`) are
measured INSIDE callbacks and feed only the wall SLO budgets; in a
healthy run no wall reading changes what gets scheduled. The only
wall-dependent branch is the fail-fast abort on an admission/verdict
TIMEOUT — which only fires when the run is already conclusively
failing its SLO.

Env knobs (all optional; config fields win when passed explicitly):
TM_TPU_SOAK_DURATION, TM_TPU_SOAK_NODES, TM_TPU_SOAK_SEED,
TM_TPU_SOAK_SAMPLE_S, TM_TPU_SOAK_WARMUP_S, TM_TPU_SOAK_TX_BURST,
TM_TPU_SOAK_LIGHT_FLEET, TM_TPU_SOAK_INGRESS_TIMEOUT_S,
TM_TPU_SOAK_CATCHUP_AT_HEIGHT, TM_TPU_SOAK_CONSENSUS_P99_MS,
TM_TPU_SOAK_LIGHT_P99_MS, TM_TPU_SOAK_INGRESS_P99_MS,
TM_TPU_SOAK_REPLAY_HPS, TM_TPU_SOAK_MAX_WALL_S,
TM_TPU_SOAK_BLS_P99_MS, TM_TPU_SOAK_BLS_COMMITTEE.
"""

from __future__ import annotations

import os
import time  # perf_counter only — wall latency; virtual time is SimClock's
from concurrent import futures as _cfut
from dataclasses import dataclass
from typing import List, Optional

from ..observability import timeseries as _ts
from .faults import Fault
from .harness import Cluster

SCHEMA_VERSION = 1


def _env_f(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    return float(raw) if raw else float(default)


def _env_i(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    return int(raw) if raw else int(default)


@dataclass
class SoakConfig:
    """Everything a soak run depends on, in one replayable record."""

    # run shape
    duration_s: float = 30.0          # virtual
    n_nodes: int = 4
    seed: int = 0
    warmup_s: float = 2.0             # samples before t0+warmup skip SLOs
    max_wall_s: Optional[float] = 600.0
    fail_fast: bool = True
    # telemetry
    sample_every_s: float = 1.0
    sample_capacity: int = 4096
    slo_window_s: float = 5.0
    # consensus lane (timeline harvest + commit echo)
    harvest_every_s: float = 1.0
    echo_every_s: float = 0.5
    echo_max_per_tick: int = 4
    echo_timeout_s: float = 60.0
    # light lane
    light_every_s: float = 1.0
    light_fleet: int = 3
    light_timeout_s: float = 60.0
    # ingress lane
    tx_every_s: float = 0.5
    tx_burst: int = 6
    tx_senders: int = 4
    ingress_timeout_s: float = 15.0
    # replay lane (crash + catch-up)
    catchup_crash_at_s: float = 1.0
    catchup_at_height: Optional[int] = None  # hold replay until tip >= this
    catchup_window: Optional[int] = None
    catchup_interval: float = 0.05
    # partition/heal across the tx flood (partition_at_s <= 0 disables)
    partition_at_s: float = 6.0
    partition_heal_s: float = 3.0
    # bls aggregated-commit echo probe (ISSUE 20; committee <= 0 disables)
    bls_echo_every_s: float = 1.0
    bls_committee: int = 4
    bls_echo_timeout_s: float = 60.0
    # SLO budgets
    consensus_commit_p99_ms: float = 15000.0  # VIRTUAL ms (partition stall fits)
    light_verdict_p99_ms: float = 30000.0     # wall
    ingress_admission_p99_ms: float = 10000.0  # wall
    replay_min_heights_per_s: float = 10.0    # virtual heights/s
    bls_echo_p99_ms: float = 30000.0          # wall

    @classmethod
    def from_env(cls, **overrides) -> "SoakConfig":
        cfg = cls(
            duration_s=_env_f("TM_TPU_SOAK_DURATION", cls.duration_s),
            n_nodes=_env_i("TM_TPU_SOAK_NODES", cls.n_nodes),
            seed=_env_i("TM_TPU_SOAK_SEED", cls.seed),
            warmup_s=_env_f("TM_TPU_SOAK_WARMUP_S", cls.warmup_s),
            sample_every_s=_env_f("TM_TPU_SOAK_SAMPLE_S", cls.sample_every_s),
            tx_burst=_env_i("TM_TPU_SOAK_TX_BURST", cls.tx_burst),
            light_fleet=_env_i("TM_TPU_SOAK_LIGHT_FLEET", cls.light_fleet),
            ingress_timeout_s=_env_f("TM_TPU_SOAK_INGRESS_TIMEOUT_S",
                                     cls.ingress_timeout_s),
            consensus_commit_p99_ms=_env_f("TM_TPU_SOAK_CONSENSUS_P99_MS",
                                           cls.consensus_commit_p99_ms),
            light_verdict_p99_ms=_env_f("TM_TPU_SOAK_LIGHT_P99_MS",
                                        cls.light_verdict_p99_ms),
            ingress_admission_p99_ms=_env_f("TM_TPU_SOAK_INGRESS_P99_MS",
                                            cls.ingress_admission_p99_ms),
            replay_min_heights_per_s=_env_f("TM_TPU_SOAK_REPLAY_HPS",
                                            cls.replay_min_heights_per_s),
            bls_echo_p99_ms=_env_f("TM_TPU_SOAK_BLS_P99_MS",
                                   cls.bls_echo_p99_ms),
            bls_committee=_env_i("TM_TPU_SOAK_BLS_COMMITTEE",
                                 cls.bls_committee),
            max_wall_s=_env_f("TM_TPU_SOAK_MAX_WALL_S", cls.max_wall_s),
        )
        gap = os.environ.get("TM_TPU_SOAK_CATCHUP_AT_HEIGHT", "")
        if gap:
            cfg.catchup_at_height = int(gap)
        for k, v in overrides.items():
            if not hasattr(cfg, k):
                raise TypeError(f"unknown SoakConfig field {k!r}")
            setattr(cfg, k, v)
        return cfg


class SoakDriver:
    """One cluster, all four workloads, one shared verifier.

    The caller OWNS the verifier (constructs it, closes it after
    `run()`); the driver owns the cluster, the light service, and the
    ingress accumulator, and tears those down in run()'s finally.
    """

    def __init__(self, verifier, config: Optional[SoakConfig] = None):
        from .catchup import CatchupDriver

        self.cfg = cfg = config or SoakConfig.from_env()
        self.v = verifier
        self._catchup_node = cfg.n_nodes - 1
        faults = [Fault(kind="crash", at_time=cfg.catchup_crash_at_s,
                        node=self._catchup_node)]
        if cfg.partition_at_s > 0:
            # split WITHOUT a quorum on either side (the catch-up node is
            # already crashed): commits stall for partition_heal_s, then
            # heal — the degradation the consensus SLO must absorb and
            # the ingress lane must ride through
            half = max(cfg.n_nodes // 2, 1)
            faults.append(Fault(
                kind="partition", at_time=cfg.partition_at_s,
                groups=[list(range(half)), list(range(half, cfg.n_nodes))],
                duration=cfg.partition_heal_s,
            ))
        self.cluster = Cluster(n_nodes=cfg.n_nodes, seed=cfg.seed,
                               faults=faults, vote_ingress=True,
                               sig_memo=True)
        self.catchup = CatchupDriver(
            self.cluster, self._catchup_node, window=cfg.catchup_window,
            interval=cfg.catchup_interval,
            start_after=cfg.catchup_crash_at_s + 0.5,
            start_at_height=cfg.catchup_at_height, verifier=verifier,
        )
        self._rec = _ts.LatencyRecorder()
        self.sampler = _ts.TelemetrySampler(
            self.cluster.clock, cadence_s=cfg.sample_every_s,
            capacity=cfg.sample_capacity,
        )
        lanes = verifier.lane_counts
        self.sampler.add_source(
            "verify_lane_consensus", lambda: lanes().get("consensus", 0))
        self.sampler.add_source(
            "verify_lane_replay", lambda: lanes().get("replay", 0))
        self.sampler.add_source(
            "verify_lane_ingress", lambda: lanes().get("ingress", 0))
        pool = getattr(verifier, "_pool", None)
        if pool is not None:
            self.sampler.add_source(
                "pool_in_flight",
                lambda: pool.stats().get("in_flight", 0))
        # lane services — built in run() (they spawn threads)
        self._svc = None
        self._acc = None
        self._privs: list = []
        # driver state
        self._finished = False
        self._measure_from = float("inf")
        self._abort_reason: Optional[str] = None
        self._tl_seen = 0
        self._echo_next = 2
        self._light_anchor = None
        self._tx_nonce = 0
        self._bls = None          # built lazily on first bls tick
        # lane counters (all surfaced in the result record)
        self.echo_submitted = 0
        self.echo_errors = 0
        self.bls_echoes = 0
        self.bls_echo_errors = 0
        self.light_verdicts = 0
        self.light_rejects = 0
        self.light_timeouts = 0
        self.ingress_admitted = 0
        self.ingress_rejects = 0
        self.ingress_timeouts = 0
        self.ingress_errors = 0

    # -- shared helpers ----------------------------------------------------

    def _lead(self):
        """Most advanced live node — the store every lane reads from."""
        best = None
        for n in self.cluster.nodes:
            if n.crashed or n.bstore is None:
                continue
            if best is None or n.height() > best.height():
                best = n
        return best

    def _record(self, lane: str, t_v: float, ms: float,
                t_w: float = 0.0, always: bool = False) -> None:
        """Warmup-gated sample: pre-measurement samples (first dispatch
        compiles kernels) stay out of the SLO math — except timeouts
        (`always`), which are conclusive whenever they happen."""
        if always or t_v >= self._measure_from:
            self._rec.record(lane, t_v, ms, t_w)

    def _abort(self, reason: str) -> None:
        if self._abort_reason is None:
            self._abort_reason = reason

    def _live(self) -> bool:
        return not (self._finished or self.cluster._stopped)

    # -- consensus lane ----------------------------------------------------

    def _harvest(self) -> None:
        """Pull newly applied heights out of the lead node's
        HeightTimeline ring (bounded — harvest must outpace the ring)."""
        node = self._lead()
        if node is None or node.cs is None:
            return
        top = self._tl_seen
        for tl in node.cs.height_timelines:
            d = tl.to_dict()
            if d["height"] <= self._tl_seen or d.get("total_s") is None:
                continue
            self._record("consensus", d["t_applied"], d["total_s"] * 1e3)
            top = max(top, d["height"])
        self._tl_seen = top

    def _harvest_tick(self) -> None:
        if not self._live():
            return
        self._harvest()
        self.cluster.clock.call_later(self.cfg.harvest_every_s,
                                      self._harvest_tick)

    def _echo_tick(self) -> None:
        """Re-verify freshly committed commits through the shared engine
        at PRIORITY_CONSENSUS — the consensus lane's device traffic."""
        if not self._live():
            return
        from ..ops import pipeline as _pl

        c, cfg = self.cluster, self.cfg
        node = self._lead()
        if node is not None and node.cs is not None:
            tip = node.height()
            lo = max(self._echo_next, tip - cfg.echo_max_per_tick + 1, 2)
            t_v, t_w = c.clock.time(), time.perf_counter()
            futs = []
            for h in range(lo, tip + 1):
                commit = node.bstore.load_block_commit(h)
                if commit is None:
                    continue
                try:
                    vals = node.cs.committed_state.validators
                    needed = vals.total_voting_power() * 2 // 3
                    entries, _ = _pl.commit_entries(
                        c.chain_id, vals, commit, needed)
                    futs.append(self.v.submit(
                        entries, priority=_pl.PRIORITY_CONSENSUS))
                    self.echo_submitted += 1
                except Exception:  # noqa: BLE001 — echo must not kill the run
                    self.echo_errors += 1
            if tip >= lo:
                self._echo_next = tip + 1
            for f in futs:
                try:
                    f.result(timeout=cfg.echo_timeout_s)
                    self._record("consensus_echo", t_v,
                                 (time.perf_counter() - t_w) * 1e3, t_w)
                except Exception:  # noqa: BLE001
                    self.echo_errors += 1
        c.clock.call_later(cfg.echo_every_s, self._echo_tick)

    # -- bls aggregation lane (ISSUE 20) -----------------------------------

    def _bls_setup(self) -> dict:
        """One-time probe state: a BLS12-381 committee, one height-1
        AggregatedCommit signed by every member. Signing (hash-to-G2 +
        cofactor clearing) is pure-python-slow, so it happens ONCE; each
        tick then re-verifies the same aggregate — host prep, the
        masked-apk point sum, and the fused pairing launch all run per
        tick, exactly like a validator re-checking gossiped commits."""
        from ..crypto import bls12381 as _bls
        from ..libs.bits import BitArray
        from ..ops import epoch_cache as _epoch
        from ..types.block import AggregatedCommit, BlockID, PartSetHeader
        from ..types.validator_set import Validator, ValidatorSet

        cfg = self.cfg
        privs = [
            _bls.PrivKey((cfg.seed * 7919 + i + 1).to_bytes(32, "big"))
            for i in range(cfg.bls_committee)
        ]
        vals = [Validator.new(p.pub_key(), 100) for p in privs]
        vset = ValidatorSet(validators=vals, proposer=vals[0])
        _epoch.note_valset(vset)
        bid = BlockID(
            hash=b"\x14" * 32,
            part_set_header=PartSetHeader(total=1, hash=b"\x14" * 32))
        signers = BitArray(len(vals))
        for i in range(len(vals)):
            signers.set_index(i, True)
        probe = AggregatedCommit(height=1, round=0, block_id=bid,
                                 signers=signers)
        msg = probe.sign_bytes(self.cluster.chain_id)
        sig = _bls.aggregate([p.sign(msg) for p in privs])
        return {
            "vset": vset,
            "bid": bid,
            "agg": AggregatedCommit(height=1, round=0, block_id=bid,
                                    signature=sig, signers=signers),
        }

    def _bls_tick(self) -> None:
        """Aggregated-commit echo: the pre-signed AggregatedCommit rides
        the shared verifier at PRIORITY_CONSENSUS through the fused
        multi-pairing lane (k_hint above BLS_DEVICE_THRESHOLD keeps it
        off the synchronous oracle path)."""
        if not self._live():
            return
        import numpy as _np

        from ..ops import pipeline as _pl
        from ..types import validation as _val

        c, cfg = self.cluster, self.cfg
        if self._bls is None:
            self._bls = self._bls_setup()
        st = self._bls
        t_v, t_w = c.clock.time(), time.perf_counter()
        try:
            blk, conc = _val.prepare_aggregated_commit(
                c.chain_id, st["vset"], st["bid"], 1, st["agg"], k_hint=4)
            fut = self.v.submit(blk, priority=_pl.PRIORITY_CONSENSUS)
            conc(_np.asarray(fut.result(timeout=cfg.bls_echo_timeout_s)))
            self._record("bls_agg", t_v,
                         (time.perf_counter() - t_w) * 1e3, t_w)
            self.bls_echoes += 1
        except _cfut.TimeoutError:
            self.bls_echo_errors += 1
            self._record("bls_agg", t_v, cfg.bls_echo_timeout_s * 1e3,
                         t_w, always=True)
        except Exception:  # noqa: BLE001 — probe must not kill the run
            self.bls_echo_errors += 1
        c.clock.call_later(cfg.bls_echo_every_s, self._bls_tick)

    # -- light lane --------------------------------------------------------

    def _light_tick(self) -> None:
        if not self._live():
            return
        from ..light import batch as _lb
        from ..types.block import SignedHeader

        c, cfg = self.cluster, self.cfg
        node = self._lead()
        if node is not None and node.cs is not None and node.height() >= 3:
            if self._light_anchor is None:
                blk1 = node.bstore.load_block(1)
                com1 = node.bstore.load_block_commit(1)
                if blk1 is not None and com1 is not None:
                    self._light_anchor = SignedHeader(header=blk1.header,
                                                      commit=com1)
            anchor = self._light_anchor
            if anchor is not None:
                vals = node.cs.committed_state.validators
                tip = node.height()
                reqs = []
                for k in range(cfg.light_fleet):
                    h = tip - 1 - k  # commit FOR h is stored once h+1 lands
                    if h <= 1:
                        break
                    blk = node.bstore.load_block(h)
                    com = node.bstore.load_block_commit(h)
                    if blk is None or com is None:
                        continue
                    reqs.append(_lb.HeaderRequest(
                        trusted_header=anchor, trusted_vals=vals,
                        untrusted_header=SignedHeader(header=blk.header,
                                                      commit=com),
                        untrusted_vals=vals, trusting_period=1e9,
                    ))
                if reqs:
                    from ..wire.canonical import Timestamp

                    t_v, t_w = c.clock.time(), time.perf_counter()
                    now = Timestamp(seconds=int(t_v) + 5, nanos=0)
                    try:
                        res = self._svc.submit_many(reqs, now=now).results(
                            timeout=cfg.light_timeout_s)
                        ms = (time.perf_counter() - t_w) * 1e3
                        for r in res:
                            self.light_verdicts += 1
                            if not r.get("ok"):
                                self.light_rejects += 1
                            self._record("light", t_v, ms, t_w)
                    except TimeoutError:
                        self.light_timeouts += 1
                        for _ in reqs:
                            self._record("light", t_v,
                                         cfg.light_timeout_s * 1e3, t_w,
                                         always=True)
                        if cfg.fail_fast:
                            self._abort("light verdict timed out")
        c.clock.call_later(cfg.light_every_s, self._light_tick)

    # -- ingress lane ------------------------------------------------------

    def _tx_tick(self) -> None:
        if not self._live():
            return
        from ..mempool import ingress as _ing

        c, cfg = self.cluster, self.cfg
        t_v, t_w = c.clock.time(), time.perf_counter()
        futs = []
        for i in range(cfg.tx_burst):
            n = self._tx_nonce + i
            priv = self._privs[n % len(self._privs)]
            raw = _ing.make_signed_tx(priv, b"soak-%d" % n, n)
            futs.append(self._acc.submit(_ing.parse_signed_tx(raw)))
        self._tx_nonce += cfg.tx_burst
        self._acc.flush_now()
        deadline = t_w + cfg.ingress_timeout_s
        timeouts = 0
        for f in futs:
            try:
                ok = f.result(
                    timeout=max(deadline - time.perf_counter(), 0.001))
                self._record("ingress", t_v,
                             (time.perf_counter() - t_w) * 1e3, t_w)
                self.ingress_admitted += 1
                if not ok:
                    self.ingress_rejects += 1
            except _cfut.TimeoutError:
                timeouts += 1
                self._record("ingress", t_v, cfg.ingress_timeout_s * 1e3,
                             t_w, always=True)
            except Exception:  # noqa: BLE001 — dispatch/shutdown error
                self.ingress_errors += 1
        if timeouts:
            self.ingress_timeouts += timeouts
            if cfg.fail_fast:
                self._abort(
                    f"ingress admission timed out ({timeouts} tx in one "
                    f"burst after {cfg.ingress_timeout_s:.1f}s)")
        c.clock.call_later(cfg.tx_every_s, self._tx_tick)

    # -- SLO budgets -------------------------------------------------------

    def budgets(self) -> List[_ts.SLOBudget]:
        cfg = self.cfg
        return [
            _ts.SLOBudget(
                "consensus_commit_p99_ms", "consensus",
                _ts.KIND_P99_MS_MAX, cfg.consensus_commit_p99_ms,
                min_samples=3,
                description="per-height commit latency from HeightTimeline "
                            "rings (virtual ms)"),
            _ts.SLOBudget(
                "light_verdict_p99_ms", "light",
                _ts.KIND_P99_MS_MAX, cfg.light_verdict_p99_ms,
                min_samples=3,
                description="light-client fleet verdict wall latency"),
            _ts.SLOBudget(
                "ingress_admission_p99_ms", "ingress",
                _ts.KIND_P99_MS_MAX, cfg.ingress_admission_p99_ms,
                min_samples=3,
                description="signed-tx admission wall latency through the "
                            "accumulator"),
            _ts.SLOBudget(
                "replay_heights_per_s", "replay",
                _ts.KIND_RATE_MIN, cfg.replay_min_heights_per_s,
                description="catch-up replay throughput in virtual "
                            "heights/s"),
        ] + ([
            _ts.SLOBudget(
                "bls_agg_p99_ms", "bls_agg",
                _ts.KIND_P99_MS_MAX, cfg.bls_echo_p99_ms,
                min_samples=3,
                description="aggregated-commit echo wall latency through "
                            "the fused BLS pairing lane"),
        ] if cfg.bls_committee > 0 else [])

    # -- the run -----------------------------------------------------------

    def _replay_rate(self) -> Optional[float]:
        s = self.catchup.summary()
        began = s.get("replay_began_at_virtual_s")
        if began is None:
            return None  # replay never started — an SLO breach, correctly
        end = s.get("rejoined_at_virtual_s") or self.cluster.clock.time()
        if end <= began:
            return None
        return s["heights_applied"] / (end - began)

    def run(self) -> dict:
        from ..libs import devcheck as _dc
        from ..libs import metrics as _metrics
        from ..light.service import LightVerifyService
        from ..mempool.ingress import IngressAccumulator
        from ..crypto import ed25519
        from ..observability import trace as _tr

        cfg, c = self.cfg, self.cluster
        wall0 = time.perf_counter()
        self._svc = LightVerifyService(verifier=self.v)
        self._acc = IngressAccumulator(verifier=self.v,
                                       max_batch=max(cfg.tx_burst, 8),
                                       window_ms=2.0)
        self._privs = [
            ed25519.gen_priv_key(
                (cfg.seed * 1009 + i + 1).to_bytes(32, "little"))
            for i in range(cfg.tx_senders)
        ]
        try:
            c.start()
            t0 = c.clock.time()
            self._measure_from = t0 + cfg.warmup_s
            self.sampler.start()
            c.clock.call_later(cfg.harvest_every_s, self._harvest_tick)
            c.clock.call_later(cfg.echo_every_s, self._echo_tick)
            c.clock.call_later(cfg.light_every_s, self._light_tick)
            c.clock.call_later(cfg.tx_every_s, self._tx_tick)
            if cfg.bls_committee > 0:
                c.clock.call_later(cfg.bls_echo_every_s, self._bls_tick)
            c.clock.run_until(
                predicate=((lambda: self._abort_reason is not None)
                           if cfg.fail_fast else None),
                deadline=t0 + cfg.duration_s,
                max_wall_s=cfg.max_wall_s,
            )
            self._finished = True
            self.sampler.stop()
            self._harvest()  # tail heights still in the ring
            wall_budget_hit = bool(c.clock.wall_budget_hit)
            violations = c.check_invariants()
            rate = self._replay_rate()
            dc_rep = _dc.report()
            dc_viol = list(dc_rep.get("violations") or [])
            results = _ts.evaluate_slos(
                self.budgets(), self._rec,
                rates={"replay": rate} if rate is not None else {},
                window_s=cfg.slo_window_s,
                span_events=_tr.TRACER.events(),
            )
            verdict = _ts.slo_verdict(results)
            ok = (verdict["ok"] and not violations and not dc_viol
                  and self._abort_reason is None and not wall_budget_hit)
            if self._abort_reason is not None:
                reason = self._abort_reason
            elif violations:
                reason = f"{len(violations)} invariant violation(s)"
            elif dc_viol:
                reason = f"{len(dc_viol)} devcheck violation(s)"
            elif not verdict["ok"]:
                reason = "SLO breach: " + ", ".join(
                    b["slo"] for b in verdict["breaches"])
            elif wall_budget_hit:
                reason = "wall budget exhausted"
            else:
                reason = ""
            lane_pcts = {}
            for lane in self._rec.lanes():
                ls = self._rec.latencies(lane)
                if ls:
                    lane_pcts[lane] = {
                        "count": len(ls),
                        "p50_ms": round(_ts.percentile(ls, 0.50), 3),
                        "p99_ms": round(_ts.percentile(ls, 0.99), 3),
                        "max_ms": round(max(ls), 3),
                    }
            try:
                engine = _metrics.ops_stats()
            except Exception:  # noqa: BLE001 — stats must not fail the run
                engine = None
            result = {
                "schema_version": SCHEMA_VERSION,
                "kind": "soak",
                "ok": ok,
                "reason": reason,
                "seed": cfg.seed,
                "n_nodes": cfg.n_nodes,
                "duration_s": cfg.duration_s,
                "t_start_virtual_s": t0,
                "virtual_s": round(c.clock.time() - t0, 6),
                "wall_s": round(time.perf_counter() - wall0, 3),
                "wall_budget_hit": wall_budget_hit,
                "events_run": c.clock.events_run,
                "heights": c.heights(),
                "fingerprint": c.fingerprint(),
                "schedule_digest": c.network.schedule_digest(),
                "violations": violations,
                "slo": verdict,
                "lane_percentiles": lane_pcts,
                "windows": {
                    lane: _ts.window_stats(self._rec.samples(lane),
                                           cfg.slo_window_s)
                    for lane in self._rec.lanes()
                },
                "gauges": {
                    name: [[round(t, 6), v] for t, v in pts]
                    for name, pts in self.sampler.series().items()
                },
                "sampler_ticks": self.sampler.ticks,
                "lane_counts": self.v.lane_counts(),
                "catchup": [d.summary() for d in c.catchup_drivers],
                "replay_heights_per_s": (round(rate, 3)
                                         if rate is not None else None),
                "counters": {
                    "echo_submitted": self.echo_submitted,
                    "echo_errors": self.echo_errors,
                    "bls_echoes": self.bls_echoes,
                    "bls_echo_errors": self.bls_echo_errors,
                    "light_verdicts": self.light_verdicts,
                    "light_rejects": self.light_rejects,
                    "light_timeouts": self.light_timeouts,
                    "ingress_admitted": self.ingress_admitted,
                    "ingress_rejects": self.ingress_rejects,
                    "ingress_timeouts": self.ingress_timeouts,
                    "ingress_errors": self.ingress_errors,
                },
                "light_service": self._svc.stats(),
                "ingress_accumulator": self._acc.stats(),
                "verify_engine": engine,
                "devcheck": dc_rep if dc_rep.get("enabled") else None,
                "faults_applied": list(c.faults_applied),
            }
            if not ok:
                result["flight_recorder"] = c.flight_recorder_dump()
            return result
        finally:
            self._finished = True
            self.sampler.stop()
            try:
                self._svc.close()
            except Exception:  # noqa: BLE001
                pass
            try:
                self._acc.close(timeout=2.0)
            except Exception:  # noqa: BLE001
                pass
            c.stop()


def run_soak(verifier, config: Optional[SoakConfig] = None) -> dict:
    """One-call soak: build the driver, run it, return the record."""
    return SoakDriver(verifier, config).run()
