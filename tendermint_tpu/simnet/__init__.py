"""tendermint_tpu.simnet — deterministic in-process cluster simulation.

A seeded, discrete-event simulator that drives N REAL consensus nodes
(consensus.state + reactor + wal + the crypto.batch verify path) over a
virtual network with fault injection — partitions, crashes + WAL
restarts, clock skew, byzantine equivocation — and live safety-invariant
checking. Same seed ⇒ byte-identical run (see harness.Cluster.fingerprint).

    from tendermint_tpu.simnet import Cluster, LinkConfig, smoke_schedule
    rep = Cluster(n_nodes=4, seed=7, faults=smoke_schedule(4)).run_to_height(10)
    assert rep.ok, rep.violations

CLI: tools/simnet_run.py.
"""

from .clock import NodeClock, SimClock, VirtualTimer  # noqa: F401
from .faults import (  # noqa: F401
    Fault,
    crash_restart_schedule,
    parse_faults,
    partition_heal_schedule,
    rotation_schedule,
    smoke_schedule,
)
from .catchup import CatchupDriver  # noqa: F401
from .harness import Cluster, SimNode, SimReport  # noqa: F401
from .search import SearchResult, search_schedules, shrink_schedule  # noqa: F401
from .transport import LinkConfig, SimNetwork, SimRouter  # noqa: F401
