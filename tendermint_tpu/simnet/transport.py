"""Simulated network — the consensus reactors' p2p seam, virtualized.

SimRouter duck-types the surface of p2p.router.Router that
consensus.reactor.ConsensusReactor actually uses (open_channel /
subscribe_peer_updates + Channel.send/broadcast), so reactors run
UNMODIFIED on top of it. Instead of sockets and per-peer threads, every
send becomes a delivery event on the shared SimClock, subject to the
link's fault model:

  latency + jitter        base one-way delay, seeded-PRNG jitter
  drop / duplicate        per-message probabilities
  reorder                 extra random delay on a coin flip (overtaking)
  bandwidth_bps           per-link serialization: a big block part queues
                          behind earlier bytes (next-free-time cursor)
  partitions              group masks: cross-group messages vanish
  down nodes              crashed nodes receive (and send) nothing

Every delivery is folded into a running `schedule digest` so two runs can
be compared for *event-order* identity, independent of what the chain
committed (the determinism tests' second axis).
"""

from __future__ import annotations

import hashlib
import queue
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

try:
    from ..p2p.transport import Envelope
except ModuleNotFoundError:  # no OpenSSL wheel and no TM_TPU_PUREPY_CRYPTO:
    # the p2p package deliberately hard-fails (crypto/ed25519 policy), but
    # simnet's scheduler/transport layer is pure Python — carry a
    # structurally identical envelope so clock/network simulation (and its
    # tier-1 tests) stay importable; reactors only ever duck-type it.
    from dataclasses import dataclass as _dc

    @_dc
    class Envelope:  # type: ignore[no-redef]
        from_id: str = ""
        to_id: str = ""
        channel_id: int = 0
        message: bytes = b""
        broadcast: bool = False

from .clock import SimClock


@dataclass
class LinkConfig:
    """Per-directed-link fault/latency model. All randomness comes from
    the simulation's single seeded PRNG."""

    latency_s: float = 0.005
    jitter_s: float = 0.0
    drop: float = 0.0  # P(message silently lost)
    duplicate: float = 0.0  # P(message delivered twice)
    reorder: float = 0.0  # P(extra delay — lets later sends overtake)
    reorder_extra_s: float = 0.05
    bandwidth_bps: Optional[float] = None  # None = infinite


class SimChannel:
    """Reactor-facing handle on one wire channel (p2p.router.Channel
    surface). receive() exists for API parity but simnet delivers
    synchronously via the reactor's handle_envelope — in_q stays empty."""

    def __init__(self, router: "SimRouter", desc):
        self._router = router
        self.desc = desc
        self.in_q: "queue.Queue[Envelope]" = queue.Queue()

    def send(self, to_id: str, message: bytes) -> bool:
        return self._router._route_out(
            Envelope(to_id=to_id, channel_id=self.desc.id, message=message)
        )

    def broadcast(self, message: bytes) -> None:
        self._router._route_out(
            Envelope(channel_id=self.desc.id, message=message, broadcast=True)
        )

    def receive(self, timeout: Optional[float] = None):
        return self.in_q.get(timeout=timeout)

    def try_receive(self) -> Optional[Envelope]:
        try:
            return self.in_q.get_nowait()
        except queue.Empty:
            return None


class SimRouter:
    """The node-local endpoint: what ConsensusReactor binds to."""

    def __init__(self, network: "SimNetwork", node_id: str):
        self.node_id = node_id
        self._network = network
        self._channels: Dict[int, SimChannel] = {}
        network._register(node_id, self)

    def open_channel(self, desc) -> SimChannel:
        if desc.id in self._channels:
            raise ValueError(f"channel {desc.id} already open")
        ch = SimChannel(self, desc)
        self._channels[desc.id] = ch
        return ch

    def subscribe_peer_updates(self) -> "queue.Queue":
        # simnet drives peer membership through reactor.add_peer/remove_peer
        return queue.Queue()

    def connected(self) -> List[str]:
        return self._network.peers_of(self.node_id)

    def _route_out(self, env: Envelope) -> bool:
        return self._network.route(self.node_id, env)


class SimNetwork:
    """All links + fault state; schedules deliveries on the SimClock."""

    def __init__(self, clock: SimClock, default_link: Optional[LinkConfig] = None):
        self._clock = clock
        self._rng = clock.rng
        self._default_link = default_link or LinkConfig()
        self._routers: Dict[str, SimRouter] = {}
        self._receivers: Dict[str, Callable[[Envelope], None]] = {}
        self._links: Dict[Tuple[str, str], LinkConfig] = {}
        self._link_busy_until: Dict[Tuple[str, str], float] = {}
        self._partition: Optional[Dict[str, int]] = None  # node -> group
        self._down: set = set()
        # counters + order digest
        self.sent = 0
        self.delivered = 0
        self.dropped = 0
        self.duplicated = 0
        # per-channel delivery counts: at 100+ nodes the p2p volume is
        # dominated by per-vote HasVote chatter — the breakdown shows
        # where a slow big-cluster run's events actually go
        self.delivered_by_channel: Dict[int, int] = {}
        self._digest = hashlib.sha256()
        # causal tracing (ISSUE 10): node_id -> per-node SpanTracer. When
        # set (harness.Cluster with tracing on), every scheduled delivery
        # gets a flow id from the clock's deterministic counter; the send
        # records a "gossip.send" start on the sender's tracer and the
        # delivery wraps the receiver in a "net.deliver" step span with
        # the flow id parked on the receiver tracer, so consensus-side
        # spans can finish the chain
        self._tracers: Dict[str, object] = {}

    def set_tracers(self, tracers: Dict[str, object]) -> None:
        self._tracers = dict(tracers or {})

    # -- wiring ----------------------------------------------------------

    def _register(self, node_id: str, router: SimRouter) -> None:
        self._routers[node_id] = router

    def set_receiver(self, node_id: str, fn: Callable[[Envelope], None]) -> None:
        """fn is invoked synchronously at (virtual) delivery time; the
        harness points it at the node's reactor.handle_envelope."""
        self._receivers[node_id] = fn

    def set_link(self, from_id: str, to_id: str, cfg: LinkConfig) -> None:
        self._links[(from_id, to_id)] = cfg

    def link(self, from_id: str, to_id: str) -> LinkConfig:
        return self._links.get((from_id, to_id), self._default_link)

    def peers_of(self, node_id: str) -> List[str]:
        return [n for n in self._routers if n != node_id and n not in self._down]

    # -- fault state -------------------------------------------------------

    def set_partition(self, groups: List[List[str]]) -> None:
        """Nodes in different groups cannot exchange messages; nodes in no
        group are isolated from everyone."""
        mask: Dict[str, int] = {}
        for gi, group in enumerate(groups):
            for n in group:
                mask[n] = gi
        self._partition = mask

    def heal_partition(self) -> None:
        self._partition = None

    def set_down(self, node_id: str, down: bool = True) -> None:
        if down:
            self._down.add(node_id)
        else:
            self._down.discard(node_id)

    def _blocked(self, a: str, b: str) -> bool:
        if a in self._down or b in self._down:
            return True
        if self._partition is None:
            return False
        ga = self._partition.get(a)
        gb = self._partition.get(b)
        return ga is None or gb is None or ga != gb

    # -- routing -----------------------------------------------------------

    def route(self, from_id: str, env: Envelope) -> bool:
        if from_id in self._down:
            return False
        if env.broadcast:
            targets = [n for n in self._routers if n != from_id]
        else:
            targets = [env.to_id] if env.to_id in self._routers else []
        any_scheduled = False
        for to in targets:
            if self._schedule_one(from_id, to, env):
                any_scheduled = True
        return any_scheduled

    def _schedule_one(self, from_id: str, to_id: str, env: Envelope) -> bool:
        self.sent += 1
        if self._blocked(from_id, to_id):
            self.dropped += 1
            return False
        cfg = self.link(from_id, to_id)
        if cfg.drop > 0.0 and self._rng.random() < cfg.drop:
            self.dropped += 1
            return False
        copies = 1
        if cfg.duplicate > 0.0 and self._rng.random() < cfg.duplicate:
            copies = 2
            self.duplicated += 1
        now = self._clock.time()
        sender_tr = self._tracers.get(from_id)
        for _ in range(copies):
            delay = cfg.latency_s
            if cfg.jitter_s > 0.0:
                delay += self._rng.random() * cfg.jitter_s
            if cfg.reorder > 0.0 and self._rng.random() < cfg.reorder:
                delay += self._rng.random() * cfg.reorder_extra_s
            if cfg.bandwidth_bps:
                key = (from_id, to_id)
                free = max(self._link_busy_until.get(key, now), now)
                tx = len(env.message) / cfg.bandwidth_bps
                self._link_busy_until[key] = free + tx
                delay += (free - now) + tx
            # flow id per scheduled COPY (a duplicate is its own causal
            # chain); allocated unconditionally so tracing never perturbs
            # the deterministic counter stream
            fid = self._clock.next_flow()
            if sender_tr is not None and sender_tr.enabled:
                sender_tr.flow_point(
                    "gossip.send", fid, "s", to=to_id, ch=env.channel_id,
                    bytes=len(env.message),
                )
            delivery = Envelope(
                from_id=from_id,
                to_id=to_id,
                channel_id=env.channel_id,
                message=env.message,
            )
            self._clock.call_later(
                delay, lambda d=delivery, f=fid: self._deliver(d, f)
            )
        return True

    def _deliver(self, env: Envelope, flow: Optional[int] = None) -> None:
        # partitions/crashes also eat messages already in flight
        if self._blocked(env.from_id, env.to_id):
            self.dropped += 1
            return
        recv = self._receivers.get(env.to_id)
        if recv is None:
            self.dropped += 1
            return
        self.delivered += 1
        ch = env.channel_id
        self.delivered_by_channel[ch] = self.delivered_by_channel.get(ch, 0) + 1
        self._digest.update(
            b"%d|%s|%s|%d|%d;"
            % (
                int(self._clock.time() * 1e9),
                env.from_id.encode(),
                env.to_id.encode(),
                env.channel_id,
                len(env.message),
            )
        )
        tr = self._tracers.get(env.to_id)
        if tr is not None and tr.enabled:
            # step the flow through the delivery and park the id on the
            # receiver's tracer: spans opened while the reactor handles
            # this envelope (consensus.verify_dispatch) finish the chain
            with tr.span("net.deliver", flow=flow, flow_phase="t",
                         frm=env.from_id, ch=env.channel_id,
                         bytes=len(env.message)):
                tr.flow = flow
                try:
                    recv(env)
                finally:
                    tr.flow = None
            return
        recv(env)

    def schedule_digest(self) -> str:
        """Digest of the delivery order so far: (time, from, to, channel,
        size) per delivered message. Two runs with the same seed must
        match; different seeds must (overwhelmingly) differ."""
        return self._digest.hexdigest()

    def stats(self) -> dict:
        return {
            "sent": self.sent,
            "delivered": self.delivered,
            "dropped": self.dropped,
            "duplicated": self.duplicated,
            "by_channel": {
                "0x%02x" % ch: n
                for ch, n in sorted(self.delivered_by_channel.items())
            },
        }
