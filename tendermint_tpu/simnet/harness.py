"""Cluster builder + run driver + invariant checkers.

Drives N REAL consensus nodes — consensus.state.ConsensusState +
consensus.reactor.ConsensusReactor + consensus.wal.WAL + the crypto.batch
verify path — single-threaded over a virtual network and a virtual clock.
Nothing is mocked below the transport: proposals, block parts, votes and
commits flow through the same code a production node runs; only threads,
sockets and the wall clock are replaced by the SimClock event loop.

Determinism contract: a run is a pure function of
(seed, n_nodes, link config, fault schedule, consensus config, txs).
`fingerprint()` digests the committed chain; `SimNetwork.schedule_digest`
digests the delivery order. Same seed ⇒ both identical; different seed ⇒
the schedule digest differs (and usually the fingerprint too, through
vote timestamps).

Crash model: a crashed node loses everything in memory; its WAL file,
block/state/app stores (the "disk") and its privval last-sign-state
survive. Restart rebuilds the node from those — the real WAL-replay
recovery path — and the invariant sweep then requires its chain to
reconverge with the cluster.

Invariants (Tendermint safety, checked live at every commit):
  agreement       every node that commits height h commits the same block
  quorum          every stored commit carries >2/3 of voting power
  monotonicity    a node's committed height never goes backwards
  convergence     after the run, every node's chain is a prefix of the
                  agreed canonical chain (covers WAL-replay recovery)
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import time as _wall
from dataclasses import dataclass, field as _field
from typing import Dict, List, Optional

from ..observability import trace as _trace
from .clock import NodeClock, SimClock
from .faults import Fault, make_double_sign_prevote
from .transport import LinkConfig, SimNetwork, SimRouter

CHAIN_ID = "simnet-chain"
GENESIS_SECONDS = 1_700_000_000


def _default_config():
    from ..config import ConsensusConfig

    return ConsensusConfig(
        timeout_propose_ms=400,
        timeout_propose_delta_ms=100,
        timeout_prevote_ms=200,
        timeout_prevote_delta_ms=100,
        timeout_precommit_ms=200,
        timeout_precommit_delta_ms=100,
        timeout_commit_ms=100,
        skip_timeout_commit=False,
    )


@dataclass
class SimReport:
    ok: bool
    reason: str
    height: int
    heights: List[int]
    fingerprint: str
    schedule_digest: str
    violations: List[str]
    seed: int
    virtual_s: float
    wall_s: float
    events_run: int
    net: dict
    faults_applied: List[str] = _field(default_factory=list)
    n_validators: int = 0
    valset_changes: List[int] = _field(default_factory=list)
    epoch_cache: dict = _field(default_factory=dict)
    # flight recorder (ISSUE 10): the last-K HeightTimeline dicts from the
    # most-advanced live node (virtual-clock timestamps — deterministic),
    # and — ONLY when an invariant broke — a flight_recorder dump carrying
    # every node's recent timelines plus the merged trace tail, so
    # "invariant broke at h=37" arrives with its own evidence attached
    height_timelines: List[dict] = _field(default_factory=list)
    flight_recorder: Optional[dict] = None
    # chain-replay catch-up (ISSUE 14): one summary dict per registered
    # CatchupDriver — replayed-range hit rate, fetch/drop counts and the
    # rejoin point, all virtual-clock-derived (deterministic)
    catchup: Optional[List[dict]] = None
    # the run ended because the REAL-time budget expired, not because the
    # virtual deadline passed or an invariant broke — machine-speed
    # dependent, so schedule search treats such a run as INCONCLUSIVE
    # rather than a bug (a wedge is detected deterministically by the
    # virtual deadline as long as the wall budget exceeds the time needed
    # to burn it)
    wall_budget_hit: bool = False

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class _SigMemo:
    """Process-wide ed25519 verify memo for LARGE clusters: in a
    single-process simulation every node re-verifies the same (pub, msg,
    sig) triples — at 100 nodes that is ~99 redundant pure-Python curve
    evaluations per vote. Verification is a deterministic pure function,
    so memoizing the VERDICT (true and false alike) changes no observable
    behavior, only the wall clock. Installed around crypto.ed25519.
    verify_zip215_fast for the duration of a run; bounded by wholesale
    clear (entries are tiny and a run's unique-signature count is far
    below the cap)."""

    def __init__(self, real, cap: int = 1 << 17):
        self.real = real
        self.cap = cap
        self.cache: Dict[tuple, bool] = {}

    def __call__(self, pub: bytes, msg: bytes, sig: bytes) -> bool:
        key = (pub, msg, sig)
        v = self.cache.get(key)
        if v is None:
            v = self.real(pub, msg, sig)
            if len(self.cache) >= self.cap:
                self.cache.clear()
            self.cache[key] = v
        return v


class SimNode:
    """One simulated validator: persistent 'disk' + rebuildable runtime."""

    def __init__(self, cluster: "Cluster", idx: int):
        from ..crypto import ed25519
        from ..db import MemDB
        from ..privval import FilePV

        self.cluster = cluster
        self.idx = idx
        self.node_id = f"sim{idx}"
        self.sk = ed25519.gen_priv_key(bytes([idx + 1]) * 32)
        # The "disk": survives crashes. The FilePV instance doubles as the
        # persisted last-sign-state file (double-sign protection must hold
        # across a crash/restart, privval file.go).
        self.pv = FilePV(self.sk)
        self.app_db = MemDB()
        self.state_db = MemDB()
        self.block_db = MemDB()
        self.wal_path = os.path.join(cluster.base_dir, f"node{idx}", "cs.wal")
        os.makedirs(os.path.dirname(self.wal_path), exist_ok=True)
        self.node_clock = NodeClock(cluster.clock)
        # per-node tracer on the SHARED virtual clock (ISSUE 10): every
        # node's spans land on one timebase, stamped with the node id, so
        # the cluster exports ONE merged trace with a pid per node.
        # Survives crash/restart (the runtime is rebuilt, the trace isn't)
        self.tracer = _trace.SpanTracer(
            capacity=int(os.environ.get("TM_TPU_SIMNET_TRACE_BUFFER")
                         or "8192"),
            node=self.node_id,
            now=cluster.clock.time,
            epoch=cluster.clock.time(),
        )

        self.crashed = False
        self.byzantine = False
        self.cs = None
        self.reactor = None
        self.router: Optional[SimRouter] = None
        self.bstore = None
        self.sstore = None
        self.mp = None
        self._pump_pending = False
        self._gossip_timer = None
        self._last_maj23 = float("-inf")
        self._last_committed = 0
        self.restarts = 0

    # -- build/teardown --------------------------------------------------

    def build(self, genesis: bool) -> None:
        """Construct the runtime (ConsensusState + reactor) from the
        persistent stores; `genesis=False` is the restart path."""
        from ..abci import LocalClient
        from ..abci.kvstore import PersistentKVStoreApplication
        from ..consensus import ConsensusState, WAL
        from ..consensus.reactor import ConsensusReactor
        from ..eventbus import EventBus
        from ..mempool import TxMempool
        from ..state import make_genesis_state
        from ..state.execution import BlockExecutor
        from ..state.store import StateStore
        from ..store import BlockStore

        c = self.cluster
        # the persistent kvstore variant: "val:<b64 pub>!<power>" txs come
        # back as EndBlock validator updates, so val_join/val_leave/
        # val_power faults rotate the ACTIVE set through the real
        # state.execution update path
        app = PersistentKVStoreApplication(db=self.app_db)
        sstore = StateStore(self.state_db)
        if genesis:
            state = make_genesis_state(c.genesis_doc)
            sstore.save(state)
        else:
            state = sstore.load()
            if state is None:  # crashed before the first state save
                state = make_genesis_state(c.genesis_doc)
        self.sstore = sstore
        self.bstore = BlockStore(self.block_db)
        mp = TxMempool(LocalClient(app))
        self.mp = mp
        if genesis:
            for tx in c.txs_for(self.idx):
                mp.check_tx(tx)
        bus = EventBus()
        ex = BlockExecutor(
            sstore, LocalClient(app), mempool=mp, block_store=self.bstore,
            event_bus=bus,
        )
        self.cs = ConsensusState(
            c.config,
            state,
            ex,
            self.bstore,
            mempool=mp,
            event_bus=bus,
            wal=WAL(self.wal_path),
            priv_validator=self.pv,
            clock=self.node_clock,
            tracer=self.tracer,
        )
        self.cs.on_enqueue = self._on_enqueue
        self.cs._height_events.append(self._on_commit)
        if self.byzantine:
            self.cs.do_prevote_override = make_double_sign_prevote(
                self.sk, c.chain_id
            )
        self.router = SimRouter(c.network, self.node_id)
        self.reactor = ConsensusReactor(
            self.cs, self.router, block_store=self.bstore, rng=c.clock.rng
        )
        c.network.set_receiver(self.node_id, self.reactor.handle_envelope)

    def start(self) -> None:
        self.crashed = False
        self._pump_pending = False
        for peer in self.cluster.nodes:
            if peer is self or peer.crashed:
                continue
            self.reactor.add_peer(peer.node_id)
            peer.reactor.add_peer(self.node_id)
        self.cs.start_stepped()
        if self.cluster.vote_ingress:
            # AFTER start_stepped: WAL replay (inside build) must ride
            # the sequential path; live peer votes window from here on
            self.cs.attach_vote_ingress(stepped=True)
        self._schedule_gossip()

    def crash(self) -> None:
        """SIGKILL-equivalent: drop the runtime, keep the disk."""
        if self.crashed:
            return
        self.crashed = True
        if self._gossip_timer is not None:
            # a tick scheduled before the crash must not survive into a
            # fast restart — it would re-arm and double the gossip chain
            self._gossip_timer.cancel()
            self._gossip_timer = None
        self.cluster.network.set_down(self.node_id, True)
        for peer in self.cluster.nodes:
            if peer is not self and peer.reactor is not None:
                peer.reactor.remove_peer(self.node_id)
        self.cs.stop_stepped()
        self.cs = None
        self.reactor = None

    def restart(self) -> None:
        if not self.crashed:
            return
        self.restarts += 1
        self.cluster.network.set_down(self.node_id, False)
        self.build(genesis=False)
        self.start()

    # -- event-loop plumbing ---------------------------------------------

    def _on_enqueue(self) -> None:
        if self._pump_pending or self.crashed:
            return
        self._pump_pending = True
        self.cluster.clock.call_later(0.0, self._pump)

    def _pump(self) -> None:
        self._pump_pending = False
        if self.crashed or self.cs is None:
            return
        self.cs.process_pending()

    def _schedule_gossip(self) -> None:
        # the reactor's OWN cadence (ConsensusReactor.GOSSIP_INTERVAL) so
        # the sim always validates the production timing regime; small
        # per-node phase offset so sweeps interleave rather than all
        # landing on identical timestamps
        self._gossip_timer = self.cluster.clock.call_later(
            self.reactor.GOSSIP_INTERVAL + self.idx * 0.003, self._gossip_tick
        )

    def _gossip_tick(self) -> None:
        if self.crashed or self.reactor is None:
            return
        now = self.cluster.clock.time()
        query = now - self._last_maj23 >= self.reactor.QUERY_MAJ23_INTERVAL
        if query:
            self._last_maj23 = now
        try:
            self.reactor.gossip_once(query)
        except Exception:  # noqa: BLE001 — gossip must never kill the sim
            pass
        self._gossip_timer = self.cluster.clock.call_later(
            self.reactor.GOSSIP_INTERVAL, self._gossip_tick
        )

    def _on_commit(self, height: int) -> None:
        self.cluster._node_committed(self, height)

    def height(self) -> int:
        return self.bstore.height() if self.bstore is not None else 0


class Cluster:
    """N-node simulated cluster over one SimClock."""

    def __init__(
        self,
        n_nodes: int = 4,
        seed: int = 0,
        link: Optional[LinkConfig] = None,
        faults: Optional[List[Fault]] = None,
        config=None,
        txs_per_node: int = 0,
        base_dir: Optional[str] = None,
        chain_id: str = CHAIN_ID,
        n_validators: Optional[int] = None,
        sig_memo: Optional[bool] = None,
        tracing: Optional[bool] = None,
        vote_ingress: Optional[bool] = None,
    ):
        from ..types import Timestamp
        from ..types.genesis import GenesisDoc, GenesisValidator

        self.seed = seed
        self.chain_id = chain_id
        self.faults = list(faults or [])
        for f in self.faults:  # validate before any filesystem side effects
            f.validate(n_nodes)
        if n_validators is None:
            n_validators = n_nodes
        if not 1 <= n_validators <= n_nodes:
            raise ValueError(f"n_validators must be in 1..{n_nodes}")
        # nodes [0, n_validators) are genesis validators; the rest are
        # standby FULL nodes — they run the complete consensus state
        # machine (track rounds, fetch parts, commit blocks) but hold no
        # voting power until a val_join fault rotates them in
        self.n_validators = n_validators
        self.clock = SimClock(seed=seed)
        self.network = SimNetwork(self.clock, default_link=link)
        self.config = config or _default_config()
        self.txs_per_node = txs_per_node
        self._owns_base_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="simnet-")
        self._fault_fired = [False] * len(self.faults)
        self.violations: List[str] = []
        self.faults_applied: List[str] = []
        self._canonical: Dict[int, bytes] = {}
        self._started = False
        self._stopped = False
        # memoize ed25519 verification verdicts across nodes — pure
        # wall-clock relief for big clusters (see _SigMemo); default on
        # from 12 nodes up
        self._sig_memo_wanted = n_nodes >= 12 if sig_memo is None else sig_memo
        self._sig_memo: Optional[_SigMemo] = None
        # live-vote ingress (ISSUE 15): stepped accumulators — votes
        # window on each node and flush deterministically when its pump
        # drains, so runs stay replay-exact. Default follows the env knob.
        if vote_ingress is None:
            vote_ingress = bool(os.environ.get("TM_TPU_SIMNET_VOTE_INGRESS"))
        self.vote_ingress = bool(vote_ingress)
        # (height, fault) for fired val_* faults that must change the set
        self._rotations_fired: List[tuple] = []
        self._epoch_stats0 = self._epoch_stats()
        # nodes whose crash fault promises a restart (restart_after or an
        # explicit restart fault) — run_to_height waits for these, while a
        # crash-stop node is simply excluded from the liveness target
        self._pending_restarts: set = set()
        # CatchupDrivers (simnet/catchup.py) register here; run_to_height
        # folds their summaries into SimReport.catchup
        self.catchup_drivers: List = []

        # cluster tracing (ISSUE 10): None follows the process tracer's
        # enabled flag at start() time (tools/simnet_run.py --trace turns
        # that on), True/False forces it. The flow-id counter runs either
        # way, so tracing cannot perturb replay exactness.
        self._tracing = tracing

        self.nodes = [SimNode(self, i) for i in range(n_nodes)]
        self.network.set_tracers({n.node_id: n.tracer for n in self.nodes})
        self.genesis_doc = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Timestamp(seconds=GENESIS_SECONDS),
            validators=[
                GenesisValidator(address=b"", pub_key=n.sk.pub_key(), power=10)
                for n in self.nodes[:n_validators]
            ],
        )
        # trigger-less double_sign faults are byzantine from genesis and
        # must be flagged before build(); triggered ones are installed on
        # the live node when they fire (_apply_fault)
        for f in self.faults:
            if f.kind == "double_sign" and f.at_height is None and f.at_time is None:
                self.nodes[f.node].byzantine = True
        for n in self.nodes:
            n.build(genesis=True)

    def txs_for(self, idx: int) -> List[bytes]:
        return [
            b"k%d_%d=v%d" % (idx, j, j) for j in range(self.txs_per_node)
        ]

    # -- lifecycle -------------------------------------------------------

    @staticmethod
    def _epoch_stats() -> dict:
        from ..ops import epoch_cache as _epoch

        return _epoch.stats()

    def _install_sig_memo(self) -> None:
        from ..crypto import ed25519 as _ed

        if self._sig_memo_wanted and not isinstance(
            _ed.verify_zip215_fast, _SigMemo
        ):
            self._sig_memo = _SigMemo(_ed.verify_zip215_fast)
            _ed.verify_zip215_fast = self._sig_memo

    def _remove_sig_memo(self) -> None:
        from ..crypto import ed25519 as _ed

        if self._sig_memo is not None and _ed.verify_zip215_fast is self._sig_memo:
            _ed.verify_zip215_fast = self._sig_memo.real
        self._sig_memo = None

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._install_sig_memo()
        # start from a COLD epoch cache: the cache is process-wide, so a
        # previous same-process run (e.g. the replay-exactness second
        # pass) would otherwise leave this run's epochs pre-warmed —
        # breaking both the cold-registration invariant and the
        # run-to-run identity of cache behavior
        from ..ops import epoch_cache as _epoch

        c = _epoch.cache()
        if c is not None:
            c.clear()
        self._epoch_stats0 = self._epoch_stats()
        tracing = (
            _trace.TRACER.enabled if self._tracing is None else self._tracing
        )
        for n in self.nodes:
            n.tracer.configure(enabled=tracing)
        for n in self.nodes:
            n.start()
        for i, f in enumerate(self.faults):
            if f.at_time is not None:
                self.clock.call_later(
                    f.at_time, lambda i=i: self._apply_fault(i)
                )
            elif f.at_height is None and f.kind == "double_sign":
                self._apply_fault(i)  # active from genesis; record it

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self._remove_sig_memo()
        for n in self.nodes:
            if not n.crashed and n.cs is not None:
                n.cs.stop_stepped()
        if self._owns_base_dir:
            import shutil

            shutil.rmtree(self.base_dir, ignore_errors=True)

    # -- faults ----------------------------------------------------------

    def _node_committed(self, node: SimNode, height: int) -> None:
        """Per-commit hook: live invariants + height-triggered faults."""
        # monotonicity
        if height <= node._last_committed:
            self.violations.append(
                f"monotonicity: node {node.idx} committed h{height} after "
                f"h{node._last_committed}"
            )
        node._last_committed = height
        blk = node.bstore.load_block(height)
        bh = bytes(blk.hash()) if blk is not None else b"?"
        # agreement
        prev = self._canonical.setdefault(height, bh)
        if prev != bh:
            self.violations.append(
                f"agreement: node {node.idx} committed {bh.hex()[:16]} at "
                f"h{height}, cluster committed {prev.hex()[:16]}"
            )
        # quorum (+2/3 voting power on the stored seen commit)
        seen = node.bstore.load_seen_commit()
        if seen is not None and seen.height == height:
            bad = self.commit_quorum_violation(seen, node.idx, node=node)
            if bad is not None:
                self.violations.append(bad)
        # height-triggered faults
        for i, f in enumerate(self.faults):
            if not self._fault_fired[i] and f.at_height is not None and height >= f.at_height:
                self._apply_fault(i)

    def _apply_fault(self, i: int) -> None:
        if self._fault_fired[i]:
            return
        self._fault_fired[i] = True
        f = self.faults[i]
        t = self.clock.time()
        if f.kind == "partition":
            groups = [[self.nodes[j].node_id for j in g] for g in f.groups]
            self.network.set_partition(groups)
            # a real partition eventually severs the TCP links: peers see
            # each other go "down" and forget round state (router would
            # emit PeerUpdate down) — heal redelivers "up" + fresh NRS
            self._for_cross_group_pairs(f.groups, lambda a, b: (
                a.reactor.remove_peer(b.node_id) if a.reactor else None
            ))
            self.faults_applied.append(f"t={t:.2f} partition {f.groups}")
            if f.duration is not None:
                self.clock.call_later(f.duration, self._heal)
        elif f.kind == "heal":
            self._heal()
        elif f.kind == "crash":
            node = self.nodes[f.node]
            node.crash()
            self.faults_applied.append(f"t={t:.2f} crash node {f.node}")
            will_restart = f.restart_after is not None or any(
                g.kind == "restart" and g.node == f.node and not self._fault_fired[j]
                for j, g in enumerate(self.faults)
            )
            if will_restart:
                self._pending_restarts.add(f.node)
            if f.restart_after is not None:
                self.clock.call_later(
                    f.restart_after, lambda n=node: self._restart(n)
                )
        elif f.kind == "restart":
            self._restart(self.nodes[f.node])
        elif f.kind == "clock_skew":
            self.nodes[f.node].node_clock.skew = f.skew
            self.faults_applied.append(
                f"t={t:.2f} clock_skew node {f.node} {f.skew:+.3f}s"
            )
        elif f.kind == "double_sign":
            node = self.nodes[f.node]
            node.byzantine = True  # restarts rebuild with the override
            if node.cs is not None and node.cs.do_prevote_override is None:
                node.cs.do_prevote_override = make_double_sign_prevote(
                    node.sk, self.chain_id
                )
            self.faults_applied.append(f"t={t:.2f} double_sign node {f.node}")
        elif f.kind in ("val_join", "val_leave", "val_power"):
            power = 0 if f.kind == "val_leave" else int(f.power)
            self._inject_validator_update(i, f.node, power)
            self.faults_applied.append(
                f"t={t:.2f} {f.kind} node {f.node} power {power}"
            )

    def _inject_validator_update(self, fault_idx: int, node_idx: int, power: int) -> None:
        """Route a validator-set change through the REAL update path: a
        "val:<b64 pub>!<power>!<nonce>" tx is fed to every live node's
        mempool; whichever proposer wins next reaps it, the kvstore app
        echoes it from EndBlock, and state.execution.update_state rotates
        next_validators via ValidatorSet._update_with_change_set — which
        structurally invalidates the set's hash()/ed25519 columns, keying
        a fresh epoch for the device cache. The nonce keeps a rejoin at a
        previous power distinct for the mempools' seen-tx caches."""
        from ..abci.kvstore import make_validator_tx

        target = self.nodes[node_idx]
        tx = make_validator_tx(
            target.sk.pub_key().bytes(), power, nonce=fault_idx
        )
        injected = 0
        for n in self.nodes:
            if n.crashed or n.mp is None:
                continue
            try:
                n.mp.check_tx(tx)
                injected += 1
            except Exception:  # noqa: BLE001 — dup/full pools must not kill a run
                pass
        # only a rotation that can actually land AND changes the set is
        # held to the churn invariant (check_invariants)
        if injected and self._rotation_changes_set(target, power):
            self._rotations_fired.append(
                (self._max_committed(), self.faults[fault_idx].kind, node_idx)
            )

    def _rotation_changes_set(self, target: "SimNode", power: int) -> bool:
        """Would (target, power) actually alter the CURRENT next-validator
        set? A no-op update (joining at the power it already has) never
        obliges a hash change. Read from the most-advanced live node —
        a lagging node's stale next_validators could misclassify an
        already-applied update as set-changing."""
        pub = target.sk.pub_key().bytes()
        best = None
        for n in self.nodes:
            if n.crashed or n.cs is None:
                continue
            if best is None or n.height() > best.height():
                best = n
        if best is None:
            return False
        vals = best.cs._state.next_validators
        for v in vals.validators:
            if v.pub_key.bytes() == pub:
                return v.voting_power != power
        return power > 0  # not in the set: joins iff power > 0

    def _max_committed(self) -> int:
        return max(self._canonical) if self._canonical else 0

    def _for_cross_group_pairs(self, groups, fn) -> None:
        group_of = {}
        for gi, g in enumerate(groups):
            for j in g:
                group_of[j] = gi
        for a in self.nodes:
            for b in self.nodes:
                if a is b:
                    continue
                if group_of.get(a.idx) != group_of.get(b.idx):
                    fn(a, b)

    def commit_quorum_violation(
        self, commit, node_idx: int = -1, node: Optional[SimNode] = None
    ) -> Optional[str]:
        """None if `commit` carries > 2/3 of the voting power of the set
        that SIGNED it, else the violation record (also the
        _node_committed live check). Under validator-set churn the
        per-height set comes from the node's state store (the same
        checkpoints verify_commit uses); genesis powers are the fallback
        for callers without a node (static-set shortcut)."""
        powers = None
        if node is not None and node.sstore is not None:
            try:
                vals = node.sstore.load_validators(commit.height)
                powers = [v.voting_power for v in vals.validators]
            except KeyError:  # pre-checkpoint heights only — any other
                powers = None  # store fault must surface, not silently
                # fall back to (possibly wrong) genesis powers
        if powers is None:
            powers = [v.power for v in self.genesis_doc.validators]
        total = sum(powers)
        power = sum(
            powers[i]
            for i, cs_ in enumerate(commit.signatures)
            if i < len(powers) and cs_.for_block()
        )
        if 3 * power <= 2 * total:
            return (
                f"quorum: node {node_idx} stored commit at h{commit.height} "
                f"with {power}/{total} voting power"
            )
        return None

    def _heal(self) -> None:
        self.network.heal_partition()
        # "reconnect": every live pair re-exchanges peer-up + NewRoundStep,
        # exactly what the router's dial/accept path would do
        for a in self.nodes:
            for b in self.nodes:
                if a is b or a.crashed or b.crashed or a.reactor is None:
                    continue
                a.reactor.add_peer(b.node_id)
        self.faults_applied.append(f"t={self.clock.time():.2f} heal")

    def _restart(self, node: SimNode) -> None:
        node.restart()
        self._pending_restarts.discard(node.idx)
        self.faults_applied.append(
            f"t={self.clock.time():.2f} restart node {node.idx}"
        )

    # -- observation -----------------------------------------------------

    def heights(self) -> List[int]:
        return [n.height() for n in self.nodes]

    def min_live_height(self) -> int:
        live = [n.height() for n in self.nodes if not n.crashed]
        return min(live) if live else 0

    def export_merged_trace(self, include_process: bool = False) -> dict:
        """ONE Chrome-trace document for the whole cluster (ISSUE 10):
        every node's virtual-clock tracer (pid per node, process_name
        metadata), flow ids preserved so a vote's gossip-send → deliver →
        verify-dispatch chain is clickable in Perfetto across node
        boundaries. All node tracers read the SAME virtual clock, so the
        merged timeline is coherent; the process-wide WALL-clock tracer
        (driver/pipeline spans) uses an incomparable timebase and is only
        appended — as a clearly-labeled separate process — on explicit
        `include_process=True`."""
        docs = []
        labels = []
        if include_process:
            docs.append(_trace.TRACER.export_chrome())
            labels.append("driver (wall-clock)")
        for n in self.nodes:
            docs.append(n.tracer.export_chrome())
            labels.append(n.node_id)
        return _trace.merge_traces(docs, labels)

    def _timeline_ring(self, node: "SimNode", last: Optional[int] = None
                       ) -> List[dict]:
        if node.cs is None:
            return []
        ring = [tl.to_dict() for tl in node.cs.height_timelines]
        return ring[-last:] if last else ring

    def height_timelines(self) -> List[dict]:
        """The last-K HeightTimeline dicts of the most-advanced live node
        — the SimReport ring. Virtual-clock timestamps: deterministic
        under replay."""
        best = None
        for n in self.nodes:
            if n.cs is None:
                continue
            if best is None or n.height() > best.height():
                best = n
        return self._timeline_ring(best) if best is not None else []

    def flight_recorder_dump(self, trace_tail: int = 512,
                             timelines_per_node: int = 8) -> dict:
        """The automatic invariant-failure attachment: every live node's
        recent height timelines plus the merged trace's tail — enough to
        answer "what was each node doing when it broke" without re-running
        the schedule."""
        timelines = {
            n.node_id: self._timeline_ring(n, timelines_per_node)
            for n in self.nodes
            if n.cs is not None
        }
        doc = self.export_merged_trace()
        evs = doc.get("traceEvents", [])
        meta = [e for e in evs if e.get("ph") == "M"]
        rest = [e for e in evs if e.get("ph") != "M"]
        return {
            "height_timelines": timelines,
            "tracing": any(n.tracer.enabled for n in self.nodes),
            "trace_events_total": len(rest),
            "trace_tail": {
                "traceEvents": meta + rest[-trace_tail:],
                "displayTimeUnit": "ms",
            },
        }

    def fingerprint(self) -> str:
        """seed → ordered digest of the committed canonical chain. Two
        same-seed runs must match byte-for-byte (replay exactness)."""
        h = hashlib.sha256()
        h.update(b"seed=%d;" % self.seed)
        for height in sorted(self._canonical):
            h.update(b"%d:" % height)
            h.update(self._canonical[height])
            h.update(b";")
        return h.hexdigest()

    def _valset_hash_walk(self) -> tuple:
        """One pass over the longest live node's committed headers:
        (change_heights, distinct_hash_count). A rotation cycling BACK to
        an earlier membership re-uses its content-derived hash, so the
        distinct count can be smaller than changes+1 — the epoch-cache
        invariant must compare against distinct sets, not change events.
        The FINAL height's valset is excluded from the distinct count:
        height h's commit is only batch-verified when block h+1 carries
        it, so a rotation landing exactly at the last committed height
        can never have cold-registered within the run."""
        best = None
        for n in self.nodes:
            if n.bstore is not None and (best is None or n.height() > best.height()):
                best = n
        if best is None:
            return [], 0
        changes: List[int] = []
        seen: set = set()
        prev = None
        top = best.height()
        for h in range(max(best.bstore.base(), 1), top + 1):
            # meta is enough: the header carries validators_hash and a
            # full load_block would reassemble every part + tx per height
            meta = best.bstore.load_block_meta(h)
            if meta is None:
                continue
            vh = bytes(meta.header.validators_hash)
            if h < top:
                seen.add(vh)
            if prev is not None and vh != prev:
                changes.append(h)
            prev = vh
        return changes, len(seen)

    def valset_change_heights(self) -> List[int]:
        """Heights whose committed header carries a validators_hash
        different from the previous height's — the chain-visible trace of
        every rotation."""
        return self._valset_hash_walk()[0]

    def epoch_cache_delta(self) -> dict:
        """Cache movement attributable to this run (counter deltas since
        Cluster construction) + the live cache state."""
        now = self._epoch_stats()
        d = {
            k: now[k] - self._epoch_stats0.get(k, 0)
            for k in ("hits", "misses", "evictions")
        }
        d["enabled"] = now["enabled"]
        d["depth"] = now["depth"]
        d["entries"] = now["entries"]
        return d

    def check_invariants(self, _walk=None) -> List[str]:
        """Final sweep: every node's whole chain must be a prefix of the
        canonical chain (convergence after crash/partition recovery);
        under churn, every effective rotation must surface as a
        validators_hash change, and — when the device epoch cache is on —
        the cache counters must actually move through the cold/warm/evict
        cycle the rotations imply. `_walk` is an optional precomputed
        `_valset_hash_walk()` result so run_to_height scans the chain
        once for both the invariants and the report."""
        out = list(self.violations)
        for n in self.nodes:
            if n.bstore is None:
                continue
            for height in range(max(n.bstore.base(), 1), n.height() + 1):
                blk = n.bstore.load_block(height)
                if blk is None:
                    continue
                bh = bytes(blk.hash())
                want = self._canonical.get(height)
                if want is not None and want != bh:
                    out.append(
                        f"convergence: node {n.idx} has {bh.hex()[:16]} at "
                        f"h{height}, canonical {want.hex()[:16]}"
                    )
        # churn: a set-changing rotation injected at height h lands in a
        # block within a couple of heights and takes effect two later
        # (update_state next_validators plumbing) — if the chain ran on
        # long enough, the validators_hash MUST have moved in (h, h+6]
        if self._rotations_fired:
            changes, distinct = (
                _walk if _walk is not None else self._valset_hash_walk()
            )
        else:
            changes, distinct = [], 0
        max_h = self._max_committed()
        for inj_h, kind, node_idx in self._rotations_fired:
            if max_h < inj_h + 6:
                continue  # run ended before the rotation could land
            if not any(inj_h < ch <= inj_h + 6 for ch in changes):
                out.append(
                    f"rotation: {kind} node {node_idx} injected at h{inj_h} "
                    f"never changed validators_hash by h{inj_h + 6} "
                    f"(changes at {changes})"
                )
        if self._rotations_fired and changes:
            ec = self.epoch_cache_delta()
            # counters only move through the batch-verify path (note_valset);
            # commits below BATCH_VERIFY_THRESHOLD sigs (tiny valsets) ride
            # the single-sig path, so "enabled but untouched" proves nothing
            if ec["enabled"] and ec["misses"] + ec["hits"] > 0:
                # every DISTINCT valset must have cold-registered once
                # (a rotation cycling back to an earlier membership
                # re-uses its content hash — counted once); the LRU must
                # have evicted what its depth cannot hold
                if ec["misses"] < distinct:
                    out.append(
                        f"epoch-cache: {distinct} distinct valsets committed "
                        f"but only {ec['misses']} cold registrations"
                    )
                if ec["hits"] == 0:
                    out.append(
                        "epoch-cache: warm re-verifications recorded no hits"
                    )
                expect_evict = distinct - ec["depth"]
                if expect_evict > 0 and ec["evictions"] < expect_evict:
                    out.append(
                        f"epoch-cache: {distinct} epochs through depth "
                        f"{ec['depth']} implies >= {expect_evict} evictions, "
                        f"saw {ec['evictions']}"
                    )
        return out

    # -- the driver ------------------------------------------------------

    def run_to_height(
        self, target: int, max_virtual_s: float = 600.0,
        max_wall_s: Optional[float] = None,
    ) -> SimReport:
        """Run the event loop until every live node commits `target` (and
        every crash-faulted node has restarted), then report.
        `max_wall_s` bounds REAL time — the guard rail for 100+-node
        clusters and search sweeps."""
        wall0 = _wall.monotonic()
        t0 = self.clock.time()
        self.start()

        def done() -> bool:
            any_live = False
            for n in self.nodes:
                if n.crashed:
                    if n.idx in self._pending_restarts:
                        return False  # a promised restart hasn't run yet
                    continue  # crash-stop: excluded from the target
                any_live = True
                if n.height() < target:
                    return False
            return any_live

        reached = self.clock.run_until(
            predicate=done, deadline=t0 + max_virtual_s,
            max_wall_s=max_wall_s,
        )
        walk = self._valset_hash_walk() if self._rotations_fired else ([], 0)
        violations = self.check_invariants(_walk=walk)
        # classification comes from the event loop's OWN exit reason — an
        # elapsed-time heuristic would misread a virtual-deadline exit
        # (a real, deterministic wedge) as a wall cutoff whenever the
        # post-run invariant walk pushed total elapsed past the budget
        wall_hit = self.clock.wall_budget_hit
        reason = "ok"
        if not reached:
            budget = f"{max_virtual_s}s virtual"
            if wall_hit:
                budget = f"{max_wall_s}s wall"
            reason = (
                f"height {target} not reached within {budget}"
                f" (heights={self.heights()})"
            )
        elif violations:
            reason = f"{len(violations)} invariant violation(s)"
        return SimReport(
            ok=reached and not violations,
            reason=reason,
            height=self.min_live_height(),
            heights=self.heights(),
            fingerprint=self.fingerprint(),
            schedule_digest=self.network.schedule_digest(),
            violations=violations,
            seed=self.seed,
            virtual_s=self.clock.time() - t0,
            wall_s=_wall.monotonic() - wall0,
            events_run=self.clock.events_run,
            net=self.network.stats(),
            faults_applied=list(self.faults_applied),
            n_validators=self.n_validators,
            valset_changes=walk[0],
            epoch_cache=self.epoch_cache_delta(),
            wall_budget_hit=wall_hit,
            height_timelines=self.height_timelines(),
            catchup=(
                [d.summary() for d in self.catchup_drivers]
                if self.catchup_drivers else None
            ),
            # the flight recorder rides ONLY on invariant failures — a
            # green run keeps the report lean
            flight_recorder=(
                self.flight_recorder_dump() if violations else None
            ),
        )
