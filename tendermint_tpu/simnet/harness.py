"""Cluster builder + run driver + invariant checkers.

Drives N REAL consensus nodes — consensus.state.ConsensusState +
consensus.reactor.ConsensusReactor + consensus.wal.WAL + the crypto.batch
verify path — single-threaded over a virtual network and a virtual clock.
Nothing is mocked below the transport: proposals, block parts, votes and
commits flow through the same code a production node runs; only threads,
sockets and the wall clock are replaced by the SimClock event loop.

Determinism contract: a run is a pure function of
(seed, n_nodes, link config, fault schedule, consensus config, txs).
`fingerprint()` digests the committed chain; `SimNetwork.schedule_digest`
digests the delivery order. Same seed ⇒ both identical; different seed ⇒
the schedule digest differs (and usually the fingerprint too, through
vote timestamps).

Crash model: a crashed node loses everything in memory; its WAL file,
block/state/app stores (the "disk") and its privval last-sign-state
survive. Restart rebuilds the node from those — the real WAL-replay
recovery path — and the invariant sweep then requires its chain to
reconverge with the cluster.

Invariants (Tendermint safety, checked live at every commit):
  agreement       every node that commits height h commits the same block
  quorum          every stored commit carries >2/3 of voting power
  monotonicity    a node's committed height never goes backwards
  convergence     after the run, every node's chain is a prefix of the
                  agreed canonical chain (covers WAL-replay recovery)
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import time as _wall
from dataclasses import dataclass, field as _field
from typing import Dict, List, Optional

from .clock import NodeClock, SimClock
from .faults import Fault, make_double_sign_prevote
from .transport import LinkConfig, SimNetwork, SimRouter

CHAIN_ID = "simnet-chain"
GENESIS_SECONDS = 1_700_000_000


def _default_config():
    from ..config import ConsensusConfig

    return ConsensusConfig(
        timeout_propose_ms=400,
        timeout_propose_delta_ms=100,
        timeout_prevote_ms=200,
        timeout_prevote_delta_ms=100,
        timeout_precommit_ms=200,
        timeout_precommit_delta_ms=100,
        timeout_commit_ms=100,
        skip_timeout_commit=False,
    )


@dataclass
class SimReport:
    ok: bool
    reason: str
    height: int
    heights: List[int]
    fingerprint: str
    schedule_digest: str
    violations: List[str]
    seed: int
    virtual_s: float
    wall_s: float
    events_run: int
    net: dict
    faults_applied: List[str] = _field(default_factory=list)

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class SimNode:
    """One simulated validator: persistent 'disk' + rebuildable runtime."""

    def __init__(self, cluster: "Cluster", idx: int):
        from ..crypto import ed25519
        from ..db import MemDB
        from ..privval import FilePV

        self.cluster = cluster
        self.idx = idx
        self.node_id = f"sim{idx}"
        self.sk = ed25519.gen_priv_key(bytes([idx + 1]) * 32)
        # The "disk": survives crashes. The FilePV instance doubles as the
        # persisted last-sign-state file (double-sign protection must hold
        # across a crash/restart, privval file.go).
        self.pv = FilePV(self.sk)
        self.app_db = MemDB()
        self.state_db = MemDB()
        self.block_db = MemDB()
        self.wal_path = os.path.join(cluster.base_dir, f"node{idx}", "cs.wal")
        os.makedirs(os.path.dirname(self.wal_path), exist_ok=True)
        self.node_clock = NodeClock(cluster.clock)

        self.crashed = False
        self.byzantine = False
        self.cs = None
        self.reactor = None
        self.router: Optional[SimRouter] = None
        self.bstore = None
        self._pump_pending = False
        self._gossip_timer = None
        self._last_maj23 = float("-inf")
        self._last_committed = 0
        self.restarts = 0

    # -- build/teardown --------------------------------------------------

    def build(self, genesis: bool) -> None:
        """Construct the runtime (ConsensusState + reactor) from the
        persistent stores; `genesis=False` is the restart path."""
        from ..abci import KVStoreApplication, LocalClient
        from ..consensus import ConsensusState, WAL
        from ..consensus.reactor import ConsensusReactor
        from ..eventbus import EventBus
        from ..mempool import TxMempool
        from ..state import make_genesis_state
        from ..state.execution import BlockExecutor
        from ..state.store import StateStore
        from ..store import BlockStore

        c = self.cluster
        app = KVStoreApplication(db=self.app_db)
        sstore = StateStore(self.state_db)
        if genesis:
            state = make_genesis_state(c.genesis_doc)
            sstore.save(state)
        else:
            state = sstore.load()
            if state is None:  # crashed before the first state save
                state = make_genesis_state(c.genesis_doc)
        self.bstore = BlockStore(self.block_db)
        mp = TxMempool(LocalClient(app))
        if genesis:
            for tx in c.txs_for(self.idx):
                mp.check_tx(tx)
        bus = EventBus()
        ex = BlockExecutor(
            sstore, LocalClient(app), mempool=mp, block_store=self.bstore,
            event_bus=bus,
        )
        self.cs = ConsensusState(
            c.config,
            state,
            ex,
            self.bstore,
            mempool=mp,
            event_bus=bus,
            wal=WAL(self.wal_path),
            priv_validator=self.pv,
            clock=self.node_clock,
        )
        self.cs.on_enqueue = self._on_enqueue
        self.cs._height_events.append(self._on_commit)
        if self.byzantine:
            self.cs.do_prevote_override = make_double_sign_prevote(
                self.sk, c.chain_id
            )
        self.router = SimRouter(c.network, self.node_id)
        self.reactor = ConsensusReactor(
            self.cs, self.router, block_store=self.bstore, rng=c.clock.rng
        )
        c.network.set_receiver(self.node_id, self.reactor.handle_envelope)

    def start(self) -> None:
        self.crashed = False
        self._pump_pending = False
        for peer in self.cluster.nodes:
            if peer is self or peer.crashed:
                continue
            self.reactor.add_peer(peer.node_id)
            peer.reactor.add_peer(self.node_id)
        self.cs.start_stepped()
        self._schedule_gossip()

    def crash(self) -> None:
        """SIGKILL-equivalent: drop the runtime, keep the disk."""
        if self.crashed:
            return
        self.crashed = True
        if self._gossip_timer is not None:
            # a tick scheduled before the crash must not survive into a
            # fast restart — it would re-arm and double the gossip chain
            self._gossip_timer.cancel()
            self._gossip_timer = None
        self.cluster.network.set_down(self.node_id, True)
        for peer in self.cluster.nodes:
            if peer is not self and peer.reactor is not None:
                peer.reactor.remove_peer(self.node_id)
        self.cs.stop_stepped()
        self.cs = None
        self.reactor = None

    def restart(self) -> None:
        if not self.crashed:
            return
        self.restarts += 1
        self.cluster.network.set_down(self.node_id, False)
        self.build(genesis=False)
        self.start()

    # -- event-loop plumbing ---------------------------------------------

    def _on_enqueue(self) -> None:
        if self._pump_pending or self.crashed:
            return
        self._pump_pending = True
        self.cluster.clock.call_later(0.0, self._pump)

    def _pump(self) -> None:
        self._pump_pending = False
        if self.crashed or self.cs is None:
            return
        self.cs.process_pending()

    def _schedule_gossip(self) -> None:
        # the reactor's OWN cadence (ConsensusReactor.GOSSIP_INTERVAL) so
        # the sim always validates the production timing regime; small
        # per-node phase offset so sweeps interleave rather than all
        # landing on identical timestamps
        self._gossip_timer = self.cluster.clock.call_later(
            self.reactor.GOSSIP_INTERVAL + self.idx * 0.003, self._gossip_tick
        )

    def _gossip_tick(self) -> None:
        if self.crashed or self.reactor is None:
            return
        now = self.cluster.clock.time()
        query = now - self._last_maj23 >= self.reactor.QUERY_MAJ23_INTERVAL
        if query:
            self._last_maj23 = now
        try:
            self.reactor.gossip_once(query)
        except Exception:  # noqa: BLE001 — gossip must never kill the sim
            pass
        self._gossip_timer = self.cluster.clock.call_later(
            self.reactor.GOSSIP_INTERVAL, self._gossip_tick
        )

    def _on_commit(self, height: int) -> None:
        self.cluster._node_committed(self, height)

    def height(self) -> int:
        return self.bstore.height() if self.bstore is not None else 0


class Cluster:
    """N-node simulated cluster over one SimClock."""

    def __init__(
        self,
        n_nodes: int = 4,
        seed: int = 0,
        link: Optional[LinkConfig] = None,
        faults: Optional[List[Fault]] = None,
        config=None,
        txs_per_node: int = 0,
        base_dir: Optional[str] = None,
        chain_id: str = CHAIN_ID,
    ):
        from ..types import Timestamp
        from ..types.genesis import GenesisDoc, GenesisValidator

        self.seed = seed
        self.chain_id = chain_id
        self.faults = list(faults or [])
        for f in self.faults:  # validate before any filesystem side effects
            f.validate(n_nodes)
        self.clock = SimClock(seed=seed)
        self.network = SimNetwork(self.clock, default_link=link)
        self.config = config or _default_config()
        self.txs_per_node = txs_per_node
        self._owns_base_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="simnet-")
        self._fault_fired = [False] * len(self.faults)
        self.violations: List[str] = []
        self.faults_applied: List[str] = []
        self._canonical: Dict[int, bytes] = {}
        self._started = False
        self._stopped = False
        # nodes whose crash fault promises a restart (restart_after or an
        # explicit restart fault) — run_to_height waits for these, while a
        # crash-stop node is simply excluded from the liveness target
        self._pending_restarts: set = set()

        self.nodes = [SimNode(self, i) for i in range(n_nodes)]
        self.genesis_doc = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Timestamp(seconds=GENESIS_SECONDS),
            validators=[
                GenesisValidator(address=b"", pub_key=n.sk.pub_key(), power=10)
                for n in self.nodes
            ],
        )
        # trigger-less double_sign faults are byzantine from genesis and
        # must be flagged before build(); triggered ones are installed on
        # the live node when they fire (_apply_fault)
        for f in self.faults:
            if f.kind == "double_sign" and f.at_height is None and f.at_time is None:
                self.nodes[f.node].byzantine = True
        for n in self.nodes:
            n.build(genesis=True)

    def txs_for(self, idx: int) -> List[bytes]:
        return [
            b"k%d_%d=v%d" % (idx, j, j) for j in range(self.txs_per_node)
        ]

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for n in self.nodes:
            n.start()
        for i, f in enumerate(self.faults):
            if f.at_time is not None:
                self.clock.call_later(
                    f.at_time, lambda i=i: self._apply_fault(i)
                )
            elif f.at_height is None and f.kind == "double_sign":
                self._apply_fault(i)  # active from genesis; record it

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        for n in self.nodes:
            if not n.crashed and n.cs is not None:
                n.cs.stop_stepped()
        if self._owns_base_dir:
            import shutil

            shutil.rmtree(self.base_dir, ignore_errors=True)

    # -- faults ----------------------------------------------------------

    def _node_committed(self, node: SimNode, height: int) -> None:
        """Per-commit hook: live invariants + height-triggered faults."""
        # monotonicity
        if height <= node._last_committed:
            self.violations.append(
                f"monotonicity: node {node.idx} committed h{height} after "
                f"h{node._last_committed}"
            )
        node._last_committed = height
        blk = node.bstore.load_block(height)
        bh = bytes(blk.hash()) if blk is not None else b"?"
        # agreement
        prev = self._canonical.setdefault(height, bh)
        if prev != bh:
            self.violations.append(
                f"agreement: node {node.idx} committed {bh.hex()[:16]} at "
                f"h{height}, cluster committed {prev.hex()[:16]}"
            )
        # quorum (+2/3 voting power on the stored seen commit)
        seen = node.bstore.load_seen_commit()
        if seen is not None and seen.height == height:
            bad = self.commit_quorum_violation(seen, node.idx)
            if bad is not None:
                self.violations.append(bad)
        # height-triggered faults
        for i, f in enumerate(self.faults):
            if not self._fault_fired[i] and f.at_height is not None and height >= f.at_height:
                self._apply_fault(i)

    def _apply_fault(self, i: int) -> None:
        if self._fault_fired[i]:
            return
        self._fault_fired[i] = True
        f = self.faults[i]
        t = self.clock.time()
        if f.kind == "partition":
            groups = [[self.nodes[j].node_id for j in g] for g in f.groups]
            self.network.set_partition(groups)
            # a real partition eventually severs the TCP links: peers see
            # each other go "down" and forget round state (router would
            # emit PeerUpdate down) — heal redelivers "up" + fresh NRS
            self._for_cross_group_pairs(f.groups, lambda a, b: (
                a.reactor.remove_peer(b.node_id) if a.reactor else None
            ))
            self.faults_applied.append(f"t={t:.2f} partition {f.groups}")
            if f.duration is not None:
                self.clock.call_later(f.duration, self._heal)
        elif f.kind == "heal":
            self._heal()
        elif f.kind == "crash":
            node = self.nodes[f.node]
            node.crash()
            self.faults_applied.append(f"t={t:.2f} crash node {f.node}")
            will_restart = f.restart_after is not None or any(
                g.kind == "restart" and g.node == f.node and not self._fault_fired[j]
                for j, g in enumerate(self.faults)
            )
            if will_restart:
                self._pending_restarts.add(f.node)
            if f.restart_after is not None:
                self.clock.call_later(
                    f.restart_after, lambda n=node: self._restart(n)
                )
        elif f.kind == "restart":
            self._restart(self.nodes[f.node])
        elif f.kind == "clock_skew":
            self.nodes[f.node].node_clock.skew = f.skew
            self.faults_applied.append(
                f"t={t:.2f} clock_skew node {f.node} {f.skew:+.3f}s"
            )
        elif f.kind == "double_sign":
            node = self.nodes[f.node]
            node.byzantine = True  # restarts rebuild with the override
            if node.cs is not None and node.cs.do_prevote_override is None:
                node.cs.do_prevote_override = make_double_sign_prevote(
                    node.sk, self.chain_id
                )
            self.faults_applied.append(f"t={t:.2f} double_sign node {f.node}")

    def _for_cross_group_pairs(self, groups, fn) -> None:
        group_of = {}
        for gi, g in enumerate(groups):
            for j in g:
                group_of[j] = gi
        for a in self.nodes:
            for b in self.nodes:
                if a is b:
                    continue
                if group_of.get(a.idx) != group_of.get(b.idx):
                    fn(a, b)

    def commit_quorum_violation(self, commit, node_idx: int = -1) -> Optional[str]:
        """None if `commit` carries > 2/3 of the genesis voting power,
        else the violation record (also the _node_committed live check)."""
        vals = self.genesis_doc.validators
        total = sum(v.power for v in vals)
        power = sum(
            vals[i].power
            for i, cs_ in enumerate(commit.signatures)
            if i < len(vals) and cs_.for_block()
        )
        if 3 * power <= 2 * total:
            return (
                f"quorum: node {node_idx} stored commit at h{commit.height} "
                f"with {power}/{total} voting power"
            )
        return None

    def _heal(self) -> None:
        self.network.heal_partition()
        # "reconnect": every live pair re-exchanges peer-up + NewRoundStep,
        # exactly what the router's dial/accept path would do
        for a in self.nodes:
            for b in self.nodes:
                if a is b or a.crashed or b.crashed or a.reactor is None:
                    continue
                a.reactor.add_peer(b.node_id)
        self.faults_applied.append(f"t={self.clock.time():.2f} heal")

    def _restart(self, node: SimNode) -> None:
        node.restart()
        self._pending_restarts.discard(node.idx)
        self.faults_applied.append(
            f"t={self.clock.time():.2f} restart node {node.idx}"
        )

    # -- observation -----------------------------------------------------

    def heights(self) -> List[int]:
        return [n.height() for n in self.nodes]

    def min_live_height(self) -> int:
        live = [n.height() for n in self.nodes if not n.crashed]
        return min(live) if live else 0

    def fingerprint(self) -> str:
        """seed → ordered digest of the committed canonical chain. Two
        same-seed runs must match byte-for-byte (replay exactness)."""
        h = hashlib.sha256()
        h.update(b"seed=%d;" % self.seed)
        for height in sorted(self._canonical):
            h.update(b"%d:" % height)
            h.update(self._canonical[height])
            h.update(b";")
        return h.hexdigest()

    def check_invariants(self) -> List[str]:
        """Final sweep: every node's whole chain must be a prefix of the
        canonical chain (convergence after crash/partition recovery)."""
        out = list(self.violations)
        for n in self.nodes:
            if n.bstore is None:
                continue
            for height in range(max(n.bstore.base(), 1), n.height() + 1):
                blk = n.bstore.load_block(height)
                if blk is None:
                    continue
                bh = bytes(blk.hash())
                want = self._canonical.get(height)
                if want is not None and want != bh:
                    out.append(
                        f"convergence: node {n.idx} has {bh.hex()[:16]} at "
                        f"h{height}, canonical {want.hex()[:16]}"
                    )
        return out

    # -- the driver ------------------------------------------------------

    def run_to_height(
        self, target: int, max_virtual_s: float = 600.0
    ) -> SimReport:
        """Run the event loop until every live node commits `target` (and
        every crash-faulted node has restarted), then report."""
        wall0 = _wall.monotonic()
        t0 = self.clock.time()
        self.start()

        def done() -> bool:
            any_live = False
            for n in self.nodes:
                if n.crashed:
                    if n.idx in self._pending_restarts:
                        return False  # a promised restart hasn't run yet
                    continue  # crash-stop: excluded from the target
                any_live = True
                if n.height() < target:
                    return False
            return any_live

        reached = self.clock.run_until(
            predicate=done, deadline=t0 + max_virtual_s
        )
        violations = self.check_invariants()
        reason = "ok"
        if not reached:
            reason = (
                f"height {target} not reached within {max_virtual_s}s virtual"
                f" (heights={self.heights()})"
            )
        elif violations:
            reason = f"{len(violations)} invariant violation(s)"
        return SimReport(
            ok=reached and not violations,
            reason=reason,
            height=self.min_live_height(),
            heights=self.heights(),
            fingerprint=self.fingerprint(),
            schedule_digest=self.network.schedule_digest(),
            violations=violations,
            seed=self.seed,
            virtual_s=self.clock.time() - t0,
            wall_s=_wall.monotonic() - wall0,
            events_run=self.clock.events_run,
            net=self.network.stats(),
            faults_applied=list(self.faults_applied),
        )
