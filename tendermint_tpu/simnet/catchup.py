"""Crash-rejoin catch-up through the ReplayEngine (ISSUE 14).

The scenario ROADMAP item 3 names: a node that crashed early rejoins a
mature network hundreds or thousands of heights behind and must replay
the gap. `CatchupDriver` runs that replay LIVE inside the simulation —
consensus keeps committing on the virtual clock while the driver chases
the tip — through the same `blocksync.replay.ReplayEngine` the real
blocksync reactor uses: epoch-cut range packing, device superbatches at
`PRIORITY_REPLAY`, per-height sequential fallback.

The crashed node stays crashed for consensus (no votes, no gossip, its
links stay down); the driver rebuilds only the STORAGE half of
`SimNode.build` (state store, block store, block executor — no
ConsensusState) and advances it. Each scheduled step issues one
range-fetch "request" against a live peer's block store; with
probability `drop` the response is lost (the lossy-link model applied
to the blocksync request path) and the same range is simply re-requested
next step. Within `rejoin_gap` of the tip the driver restarts the node,
which rebuilds from the now-advanced stores and rejoins consensus.

Determinism contract (simnet-determinism lint applies here): every step
rides `SimClock.call_later`, randomness comes from a `random.Random`
seeded from the cluster seed, and the engine runs with the synchronous
writer — same seed ⇒ byte-identical catch-up trajectory, fingerprint
and `summary()` dict.
"""

from __future__ import annotations

import random
from typing import List, Optional


class CatchupDriver:
    """Catch one crashed SimNode up to the live tip, then rejoin it.

    Construct AFTER the cluster (registers itself on
    `cluster.catchup_drivers`, which `run_to_height` folds into
    `SimReport.catchup`); the first step fires `start_after` virtual
    seconds into the run, so schedule it past the crash fault.
    """

    def __init__(self, cluster, node_idx: int, *, window: Optional[int] = None,
                 drop: float = 0.0, interval: float = 0.05,
                 rejoin_gap: int = 2, start_after: float = 1.0,
                 start_at_height: Optional[int] = None, verifier=None):
        from ..blocksync.replay import ReplayEngine

        self.cluster = cluster
        self.node = cluster.nodes[node_idx]
        self.rng = random.Random(cluster.seed * 1_000_003 + node_idx + 0xCA7)
        # verifier: injected AsyncBatchVerifier (the soak harness passes
        # its shared engine so replay traffic rides the same QoS queue as
        # every other lane); None keeps the shared_verifier() default
        self.engine = ReplayEngine(window=window, synchronous=True,
                                   verifier=verifier)
        self.drop = float(drop)
        self.interval = float(interval)
        self.rejoin_gap = int(rejoin_gap)
        # hold the first fetch until the live tip reaches this height —
        # how the "rejoins N heights behind" scenario builds its gap
        # (the node crashes early; replay begins once the gap exists)
        self.start_at_height = start_at_height
        self.behind_at_start: Optional[int] = None
        # virtual timestamp of the first real replay step — the soak
        # harness divides heights_applied by (rejoined_at - this) for
        # its replay heights/s SLO floor (ISSUE 16)
        self.replay_began_at: Optional[float] = None
        self.steps = 0
        self.fetches = 0          # blocks actually read from a peer store
        self.dropped_requests = 0  # range requests lost to the link model
        self.start_height: Optional[int] = None
        self.rejoined_at: Optional[float] = None
        self.failed: List[tuple] = []  # (height, error) per failed range
        self.done = False
        self._stats: Optional[dict] = None
        self._state = None
        self._bstore = None
        self._ex = None
        cluster.catchup_drivers.append(self)
        cluster.clock.call_later(start_after, self._step)

    # -- storage-only runtime (SimNode.build minus consensus) ------------

    def _ensure_runtime(self) -> bool:
        if self._state is not None:
            return True
        node = self.node
        if not node.crashed:
            return False  # not crashed (yet): nothing to catch up
        from ..abci import LocalClient
        from ..abci.kvstore import PersistentKVStoreApplication
        from ..state import make_genesis_state
        from ..state.execution import BlockExecutor
        from ..state.store import StateStore
        from ..store import BlockStore

        app = PersistentKVStoreApplication(db=node.app_db)
        sstore = StateStore(node.state_db)
        state = sstore.load()
        if state is None:  # crashed before the first state save
            state = make_genesis_state(self.cluster.genesis_doc)
        self._state = state
        self._bstore = BlockStore(node.block_db)
        self._ex = BlockExecutor(sstore, LocalClient(app),
                                 block_store=self._bstore)
        self.start_height = state.last_block_height
        return True

    def _save(self, block, parts, seen_commit) -> None:
        # a crash between save and apply leaves the store one block ahead
        # of state; re-saving that height on resume would double-write
        if block.header.height > self._bstore.height():
            self._bstore.save_block(block, parts, seen_commit)

    def _apply(self, block_id, block):
        self._state = self._ex.apply_block(self._state, block_id, block)
        return self._state

    # -- the fetch/replay loop -------------------------------------------

    def _live_tip(self):
        best = None
        for n in self.cluster.nodes:
            if n is self.node or n.crashed or n.bstore is None:
                continue
            if best is None or n.height() > best.height():
                best = n
        return best

    def _fetch_run(self, peer, h0: int) -> list:
        """One blocksync range request: up to window+1 consecutive blocks
        from `peer`'s store starting at h0. Lost with probability `drop`
        (whole response — one request per range), retried next step."""
        if self.rng.random() < self.drop:
            self.dropped_requests += 1
            return []
        run = []
        top = peer.height()
        for h in range(h0, min(h0 + self.engine.window + 1, top + 1)):
            block = peer.bstore.load_block(h)
            if block is None:
                break
            run.append(block)
            self.fetches += 1
        return run

    def _step(self) -> None:
        c = self.cluster
        if self.done or c._stopped:
            return
        self.steps += 1
        if not self.node.crashed and self._state is None:
            # never crashed / externally restarted: nothing to drive
            c.clock.call_later(self.interval, self._step)
            return
        if self._ensure_runtime():
            peer = self._live_tip()
            if peer is not None and self.behind_at_start is None:
                if (self.start_at_height is not None
                        and peer.height() < self.start_at_height):
                    # gap still building: check back on a coarse cadence
                    c.clock.call_later(max(self.interval, 1.0), self._step)
                    return
                self.behind_at_start = (
                    peer.height() - self._state.last_block_height
                )
                self.replay_began_at = c.clock.time()
            if peer is not None:
                mine = self._state.last_block_height
                if (peer.height() - mine <= self.rejoin_gap
                        and mine > (self.start_height or 0)):
                    self._rejoin(mine)
                    return
                run = self._fetch_run(peer, mine + 1)
                if len(run) >= 2:
                    self._state, out = self.engine.replay_blocks(
                        self._state, run, self._save, self._apply,
                        should_stop=lambda: c._stopped,
                    )
                    if out.failed_height is not None:
                        self.failed.append((out.failed_height, out.error))
        c.clock.call_later(self.interval, self._step)

    def _rejoin(self, height: int) -> None:
        c = self.cluster
        self._stats = dict(self.engine.stats())
        self.engine.close()
        self._state = self._bstore = self._ex = None
        self.rejoined_at = c.clock.time()
        self.done = True
        c.faults_applied.append(
            f"t={self.rejoined_at:.2f} catchup rejoin node "
            f"{self.node.idx} at h{height}"
        )
        self.node.restart()

    # -- reporting --------------------------------------------------------

    def summary(self) -> dict:
        s = self._stats if self._stats is not None else self.engine.stats()
        return {
            "node": self.node.idx,
            "start_height": self.start_height,
            "behind_at_start": self.behind_at_start,
            "heights_applied": s["heights_applied"],
            "ranges": s["ranges"],
            "range_heights": s["range_heights"],
            "sequential_heights": s["sequential_heights"],
            "fallback_ranges": s["fallback_ranges"],
            "sigs_submitted": s["sigs_submitted"],
            "hit_rate": round(s["hit_rate"], 4),
            "window": s["window"],
            "steps": self.steps,
            "fetches": self.fetches,
            "dropped_requests": self.dropped_requests,
            "rejoined": self.rejoined_at is not None,
            "rejoined_at_virtual_s": self.rejoined_at,
            "replay_began_at_virtual_s": self.replay_began_at,
            "failed": list(self.failed),
        }
