"""Property-based fault-schedule search + delta-debug shrinking.

The simnet analog of a property-based tester: seeded generators produce
random-but-liveness-safe fault schedules (every partition heals, every
crash restarts, at most f nodes are byzantine), a cluster runs each one,
and the Tendermint safety/liveness invariants are the property. Any
failing (seed, generator) pair is deterministic — the pair IS the repro —
and the failing schedule is then shrunk like a property-based
counterexample: drop one fault at a time, re-run, keep the failure, until
no single removal preserves it. The minimal schedule is emitted as a JSON
regression scenario (tests/scenarios/) that `tools/simnet_run.py
--scenario` replays forever after.

Generator RNGs are seeded with `random.Random(f"{generator}:{seed}")`
(string seeding is PYTHONHASHSEED-independent), so a sweep's schedules —
and through the cluster seed, its runs — are byte-stable across processes
and machines.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
from dataclasses import dataclass, field as _field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .faults import Fault, rotation_schedule
from .harness import Cluster
from .transport import LinkConfig


# ---------------------------------------------------------------------------
# Schedule generators: random interleavings that are liveness-SAFE by
# construction, so "target height not reached" is a bug, not bad luck.
# ---------------------------------------------------------------------------


def _f_budget(n_validators: int) -> int:
    """Max simultaneously-untrusted validators: f in n >= 3f + 1."""
    return max((n_validators - 1) // 3, 0)


def _gen_mixed(rng: random.Random, n_nodes: int, n_validators: int):
    """Random interleavings of partition / crash / clock-skew /
    double-sign over a (possibly) lossy link."""
    link = LinkConfig(
        latency_s=0.005,
        jitter_s=rng.choice([0.0, 0.01, 0.02]),
        drop=rng.choice([0.0, 0.02, 0.05]),
        duplicate=rng.choice([0.0, 0.02]),
        reorder=rng.choice([0.0, 0.05]),
    )
    faults: List[Fault] = []
    budget = _f_budget(n_validators)
    crashed: set = set()
    byzantine: set = set()
    for _ in range(rng.randint(1, 4)):
        kind = rng.choice(("partition", "crash", "clock_skew", "double_sign"))
        h = rng.randint(2, 7)
        if kind == "partition":
            # bias toward EVEN splits: a quorum-less partition forces
            # round divergence on both sides, historically the richest
            # failure soil (both PR-3 gossip bugs needed it)
            cut = n_nodes // 2 if rng.random() < 0.5 else rng.randint(1, n_nodes - 1)
            ids = list(range(n_nodes))
            rng.shuffle(ids)
            faults.append(
                Fault(
                    kind="partition", at_height=h,
                    groups=[sorted(ids[:cut]), sorted(ids[cut:])],
                    duration=rng.uniform(1.0, 4.0),
                )
            )
        elif kind == "crash":
            # every crash restarts; at most f validators crash per
            # schedule (conservative — restarts would allow more) while
            # standby full nodes (>= n_validators) crash freely
            val_crashes = sum(1 for i in crashed if i < n_validators)
            pool = [
                i for i in range(n_nodes)
                if i not in crashed
                and (i >= n_validators or val_crashes < budget)
            ]
            if not pool:
                continue
            node = rng.choice(pool)
            crashed.add(node)
            faults.append(
                Fault(
                    kind="crash", at_height=h, node=node,
                    restart_after=rng.uniform(0.5, 2.0),
                )
            )
        elif kind == "clock_skew":
            faults.append(
                Fault(
                    kind="clock_skew", at_height=h,
                    node=rng.randrange(n_nodes),
                    skew=rng.choice([-0.4, 0.3, 0.8]),
                )
            )
        else:  # double_sign
            if len(byzantine) >= budget:
                continue
            pool = [i for i in range(n_validators) if i not in byzantine]
            if not pool:
                continue
            node = rng.choice(pool)
            byzantine.add(node)
            faults.append(Fault(kind="double_sign", at_height=h, node=node))
    if not faults:  # degenerate draw: at least exercise a partition+heal
        faults.append(
            Fault(
                kind="partition", at_height=3,
                groups=[[0], list(range(1, n_nodes))], duration=1.5,
            )
        )
    return faults, link


def _gen_churn(rng: random.Random, n_nodes: int, n_validators: int):
    """Validator-set rotation under mild loss, plus one disturbance —
    the epoch-cache-churn shape (ISSUE 6 tentpole leg a)."""
    link = LinkConfig(
        latency_s=0.005,
        jitter_s=rng.choice([0.0, 0.01]),
        drop=rng.choice([0.0, 0.02]),
    )
    faults = rotation_schedule(
        n_nodes, n_validators,
        every=rng.choice([3, 4, 5]), start=rng.randint(2, 4), until=10,
    )
    roll = rng.random()
    if roll < 0.4:
        half = n_nodes // 2
        faults.append(
            Fault(
                kind="partition", at_height=rng.randint(4, 7),
                groups=[list(range(half)), list(range(half, n_nodes))],
                duration=rng.uniform(1.0, 2.5),
            )
        )
    elif roll < 0.8:
        faults.append(
            Fault(
                kind="crash", at_height=rng.randint(4, 7),
                node=rng.randrange(n_nodes),
                restart_after=rng.uniform(0.5, 1.5),
            )
        )
    return faults, link


GENERATORS: Dict[str, Callable] = {
    "mixed": _gen_mixed,
    "churn": _gen_churn,
}


# ---------------------------------------------------------------------------
# Running, searching, shrinking
# ---------------------------------------------------------------------------


@dataclass
class SearchResult:
    """One sweep's outcome: every run's verdict + every (shrunk) failure.
    `failure` is the first one (the common stop-on-failure case);
    `failures` carries ALL of them when the sweep keeps searching."""

    runs: List[dict] = _field(default_factory=list)
    failures: List[dict] = _field(default_factory=list)

    @property
    def failure(self) -> Optional[dict]:
        return self.failures[0] if self.failures else None

    @property
    def ok(self) -> bool:
        return not self.failures

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "runs": self.runs,
            "failure": self.failure,
            "failures": self.failures,
        }


def run_schedule(
    faults: Sequence[Fault],
    seed: int,
    n_nodes: int,
    n_validators: Optional[int] = None,
    link: Optional[LinkConfig] = None,
    height: int = 12,
    max_virtual_s: float = 300.0,
    max_wall_s: Optional[float] = 120.0,
):
    """One deterministic cluster run of `faults`; returns the SimReport."""
    c = Cluster(
        n_nodes=n_nodes,
        seed=seed,
        link=link,
        faults=list(faults),
        n_validators=n_validators,
    )
    try:
        return c.run_to_height(
            height, max_virtual_s=max_virtual_s, max_wall_s=max_wall_s
        )
    finally:
        c.stop()


def shrink_schedule(
    faults: Sequence[Fault],
    still_fails: Callable[[List[Fault]], bool],
    max_runs: int = 48,
) -> Tuple[List[Fault], int]:
    """Delta-debug a failing schedule to a minimal one: drop one fault at
    a time, re-run, keep the failure; restart the scan after every
    successful removal until a fixpoint (no single removal preserves the
    failure) or the run budget is spent. Returns (minimal, runs_used)."""
    cur = list(faults)
    runs = 0
    changed = True
    while changed and runs < max_runs:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1 :]
            runs += 1
            if still_fails(cand):
                cur = cand
                changed = True
                break
            if runs >= max_runs:
                break
    return cur, runs


def search_schedules(
    seeds: Sequence[int],
    generators: Sequence[str] = ("mixed", "churn"),
    n_nodes: int = 8,
    n_validators: Optional[int] = None,
    height: int = 12,
    max_virtual_s: float = 300.0,
    max_wall_s: Optional[float] = 120.0,
    shrink: bool = True,
    shrink_budget: int = 48,
    scenario_dir: Optional[str] = None,
    stop_on_failure: bool = True,
    progress: Optional[Callable[[str], None]] = None,
) -> SearchResult:
    """Explore seeds x generators until an invariant breaks (or the grid
    is exhausted). The first failure is shrunk to a minimal schedule and
    — when `scenario_dir` is given — emitted as a JSON regression
    scenario."""
    res = SearchResult()
    for gen_name in generators:
        gen = GENERATORS[gen_name]
        for seed in seeds:
            rng = random.Random(f"{gen_name}:{seed}")
            faults, link = gen(rng, n_nodes, n_validators or n_nodes)
            rep = run_schedule(
                faults, seed, n_nodes, n_validators, link,
                height, max_virtual_s, max_wall_s,
            )
            # a run cut off by the REAL-time budget is machine-speed
            # dependent: classify it INCONCLUSIVE, never a bug — a wedge
            # is detected deterministically by the virtual deadline, and
            # the wall budget only bounds how much CPU a wedged run may
            # burn to get there
            inconclusive = (not rep.ok) and rep.wall_budget_hit
            rec = {
                "generator": gen_name,
                "seed": seed,
                "ok": rep.ok,
                "inconclusive": inconclusive,
                "reason": rep.reason,
                "height": rep.height,
                "fingerprint": rep.fingerprint,
                "faults": [f.to_dict() for f in faults],
                "wall_s": round(rep.wall_s, 3),
            }
            res.runs.append(rec)
            if progress is not None:
                tag = "ok" if rep.ok else (
                    "INCONCLUSIVE (wall budget)" if inconclusive else "FAIL"
                )
                progress(f"{gen_name}:{seed} {tag} h={rep.height} ({rep.reason})")
            if rep.ok or inconclusive:
                continue

            def _fails(cand: List[Fault]) -> bool:
                r = run_schedule(
                    cand, seed, n_nodes, n_validators, link,
                    height, max_virtual_s, max_wall_s,
                )
                # an inconclusive candidate run does NOT count as still-
                # failing (conservative: the fault under test is kept)
                return not r.ok and not r.wall_budget_hit

            minimal, shrink_runs = (
                shrink_schedule(faults, _fails, shrink_budget)
                if shrink
                else (list(faults), 0)
            )
            failure = {
                "generator": gen_name,
                "seed": seed,
                "reason": rep.reason,
                "violations": rep.violations,
                # the run's own evidence (ISSUE 10): per-node height
                # timelines + merged trace tail, captured at failure time
                "flight_recorder": rep.flight_recorder,
                "schedule": [f.to_dict() for f in faults],
                "minimal": [f.to_dict() for f in minimal],
                "shrink_runs": shrink_runs,
                "link": dataclasses.asdict(link),
                "n_nodes": n_nodes,
                "n_validators": n_validators or n_nodes,
                "height": height,
            }
            if scenario_dir:
                failure["scenario_path"] = emit_scenario(
                    scenario_dir, failure
                )
            res.failures.append(failure)
            if stop_on_failure:
                return res
    return res


# ---------------------------------------------------------------------------
# Regression scenarios: every bug the search finds becomes a replayable file
# ---------------------------------------------------------------------------


def emit_scenario(dir_path: str, failure: dict) -> str:
    """Write a failing (shrunk) schedule as a self-contained scenario:
    `tools/simnet_run.py --scenario <path>` replays it, and the file is
    meant to be committed under tests/scenarios/."""
    os.makedirs(dir_path, exist_ok=True)
    stem = f"search_{failure['generator']}_seed{failure['seed']}"
    path = os.path.join(dir_path, stem + ".json")
    suffix = 1
    while os.path.exists(path):
        # never clobber a committed regression scenario: a later search
        # failing on the same (generator, seed) is a DIFFERENT bug
        suffix += 1
        path = os.path.join(dir_path, f"{stem}-{suffix}.json")
    # provenance: if a bug-injection seam was active during the search,
    # record it — re-running the described search WITHOUT the seam is
    # green, and a scenario file that cannot name the bug it guards
    # against is unmaintainable
    injected = sorted(
        k for k in os.environ if k.startswith("TM_TPU_GOSSIP_BUG_")
        and os.environ[k]
    )
    desc = (
        "minimal failing schedule found by simnet search "
        f"(generator={failure['generator']}, seed={failure['seed']}"
        + (f", injected bug seam: {', '.join(injected)}" if injected else "")
        + f"): {failure['reason']}"
    )
    doc = {
        "description": desc,
        "found_with_injected_bugs": injected,
        "seed": failure["seed"],
        "n_nodes": failure["n_nodes"],
        "n_validators": failure["n_validators"],
        "height": failure["height"],
        "link": failure["link"],
        "faults": failure["minimal"],
        "expect": "ok",  # replays must PASS once the bug is fixed
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def load_scenario(path: str) -> dict:
    """Parse a scenario file into run_schedule kwargs."""
    with open(path) as fh:
        doc = json.load(fh)
    from .faults import parse_faults

    return {
        "faults": parse_faults(doc["faults"]),
        "seed": int(doc["seed"]),
        "n_nodes": int(doc["n_nodes"]),
        "n_validators": int(doc.get("n_validators") or doc["n_nodes"]),
        "link": LinkConfig(**doc.get("link", {})),
        "height": int(doc.get("height", 12)),
    }
