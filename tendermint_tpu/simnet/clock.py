"""Virtual clock + deterministic discrete-event scheduler.

The heart of simnet: a single-threaded event loop over virtual time. All
consensus timeouts (consensus.ticker.TimeoutTicker), message deliveries
(simnet.transport.SimNetwork) and fault triggers are events on one heap,
ordered by (virtual_time, seq) — seq is the scheduling order, so ties
break stably and a run is a pure function of (seed, topology, schedule).

One seeded PRNG lives here and is the ONLY source of randomness in a
simulation (latency jitter, drop/duplicate decisions): same seed ⇒ same
draws in the same order ⇒ byte-identical runs.
"""

from __future__ import annotations

import heapq
import random
import time as _wall
from typing import Callable, Optional

# Virtual epoch: after the test-genesis times used across the repo
# (1_700_000_000) so block-time monotonicity vs genesis holds at height 1.
DEFAULT_START = 1_700_000_100.0


class VirtualTimer:
    """Handle returned by call_later/call_at; duck-compatible with
    threading.Timer for the consensus ticker's cancel path."""

    __slots__ = ("when", "seq", "fn", "cancelled")

    def __init__(self, when: float, seq: int, fn: Callable[[], None]):
        self.when = when
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: "VirtualTimer") -> bool:
        return (self.when, self.seq) < (other.when, other.seq)


class SimClock:
    """Virtual time + event heap + the simulation's seeded PRNG."""

    def __init__(self, seed: int = 0, start: float = DEFAULT_START):
        self._t = float(start)
        self._heap: list = []
        self._seq = 0
        self.seed = seed
        self.rng = random.Random(seed)
        self.events_run = 0
        # flow-id allocator for causal tracing (ISSUE 10): envelope
        # send→deliver correlation ids. Deliberately NOT the PRNG and not
        # gated on tracing — allocation order is part of the simulation's
        # deterministic state, so tracing on/off cannot change a run
        self._flow = 0
        # True when the LAST run_until call exited because its max_wall_s
        # budget expired (vs predicate/deadline/heap-drain) — lets callers
        # classify a wall cutoff without re-deriving it from elapsed time
        self.wall_budget_hit = False

    # -- time source (ConsensusState/NodeClock read side) ----------------

    def time(self) -> float:
        return self._t

    def next_flow(self) -> int:
        """Next envelope flow (correlation) id — deterministic counter."""
        self._flow += 1
        return self._flow

    # -- scheduling ------------------------------------------------------

    def call_later(self, delay: float, fn: Callable[[], None]) -> VirtualTimer:
        return self.call_at(self._t + max(float(delay), 0.0), fn)

    def call_at(self, when: float, fn: Callable[[], None]) -> VirtualTimer:
        if when < self._t:
            when = self._t
        self._seq += 1
        t = VirtualTimer(when, self._seq, fn)
        heapq.heappush(self._heap, t)
        return t

    def cancel(self, timer: VirtualTimer) -> None:
        timer.cancel()

    def pending(self) -> int:
        return sum(1 for t in self._heap if not t.cancelled)

    # -- the loop --------------------------------------------------------

    def run_until(
        self,
        predicate: Optional[Callable[[], bool]] = None,
        deadline: Optional[float] = None,
        max_events: Optional[int] = None,
        max_wall_s: Optional[float] = None,
    ) -> bool:
        """Run events in order until `predicate()` is true (checked after
        each event), virtual `deadline` passes, the heap drains, or
        `max_events` fire. Returns predicate status (True also when no
        predicate was given and the loop ended for another reason).

        `max_wall_s` bounds REAL elapsed time (checked every 1024 events
        so the clock read never dominates tiny events) — the guard rail
        for 100+-node clusters and schedule-search sweeps, where a
        wedged scenario must cost a bounded slice of the budget instead
        of grinding the virtual deadline event by event."""
        n = 0
        self.wall_budget_hit = False
        wall_deadline = (
            _wall.monotonic() + max_wall_s if max_wall_s is not None else None
        )
        if predicate is not None and predicate():
            return True
        while self._heap:
            if max_events is not None and n >= max_events:
                return predicate() if predicate is not None else False
            if wall_deadline is not None and (n & 1023) == 1023:
                if _wall.monotonic() > wall_deadline:
                    self.wall_budget_hit = True
                    return predicate() if predicate is not None else False
            t = heapq.heappop(self._heap)
            if t.cancelled:
                continue
            if deadline is not None and t.when > deadline:
                heapq.heappush(self._heap, t)  # leave it for a later run
                self._t = deadline
                return predicate() if predicate is not None else True
            self._t = t.when
            n += 1
            self.events_run += 1
            t.fn()  # may schedule more events / read self.rng
            if predicate is not None and predicate():
                return True
        return predicate() if predicate is not None else True

    def run_for(self, dt: float) -> None:
        self.run_until(deadline=self._t + dt)


class NodeClock:
    """Per-node view of the shared SimClock with an adjustable skew —
    clock-skew faults shift what a node *reads* as "now" (vote/proposal
    timestamps, round start times) without touching timer durations,
    exactly the failure mode of a drifting wall clock."""

    def __init__(self, base: SimClock, skew: float = 0.0):
        self._base = base
        self.skew = skew

    def time(self) -> float:
        return self._base.time() + self.skew

    def call_later(self, delay: float, fn: Callable[[], None]) -> VirtualTimer:
        return self._base.call_later(delay, fn)

    def call_at(self, when: float, fn: Callable[[], None]) -> VirtualTimer:
        return self._base.call_at(when - self.skew, fn)

    def cancel(self, timer: VirtualTimer) -> None:
        self._base.cancel(timer)
