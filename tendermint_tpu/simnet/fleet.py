"""Simnet scenario: a 100-node cluster's verification on one shared fleet.

ISSUE 18 acceptance scenario, deterministic by construction: N nodes on
one SimClock submit EntryBlock verify requests — through the REAL fleet
wire format (fleet.client.LoopbackSession → fleet.server.
LoopbackFleetHost, exercising encode → framing → parse both ways) — to
one shared fleet host, at all three QoS tiers. Mid-run the fleet host
is killed: every node degrades to LOCAL verification with the same
checker, no stall, zero lost requests; if a revive is scheduled, later
requests ride the fleet again.

Replay exactness (the simnet contract): the only randomness is the
SimClock's seeded PRNG, events run single-threaded in (time, seq)
order, and the report carries two fingerprints —

* ``verdict_fingerprint`` — verdicts alone, in delivery order. The
  same for a fleet run (crash included) and an ``all_local=True`` run
  of the same seed: graceful degradation may move WHERE a verdict is
  computed, never WHAT it is.
* ``run_fingerprint`` — verdicts + computation source + priorities.
  Byte-identical across two runs of the same seed and parameters.

The signature scheme is a deterministic stand-in (sig = doubled
sha256(pub||msg)), cheap enough for 100 nodes in a unit test; parity
with the real ed25519 path is covered by tests/test_fleet.py.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional

import numpy as np

from ..fleet.client import FleetUnavailable, LoopbackSession
from ..fleet.server import LoopbackFleetHost
from ..ops.entry_block import EntryBlock
from .clock import SimClock

_FORGE_RATE = 0.08  # per-signature forge probability (seeded PRNG)


def _pub(node: int, val: int) -> bytes:
    return hashlib.sha256(b"fleet-pub:%d:%d" % (node, val)).digest()


def _sign(pub: bytes, msg: bytes) -> bytes:
    h = hashlib.sha256(pub + msg).digest()
    return h + h


def check_block(block: EntryBlock, _priority: int = 0) -> np.ndarray:
    """The scenario's verifier — used identically by the fleet host and
    by every node's local fallback, so a verdict is a pure function of
    the block no matter where it is computed."""
    out = np.zeros(len(block), dtype=bool)
    for i in range(len(block)):
        pub, msg, sig = block.entry(i)
        out[i] = sig == _sign(pub, msg)
    return out


def _build_block(rng, node: int, req: int, sigs: int) -> EntryBlock:
    pub = np.zeros((sigs, 32), dtype=np.uint8)
    sig = np.zeros((sigs, 64), dtype=np.uint8)
    msgs: List[bytes] = []
    offsets = np.zeros(sigs + 1, dtype=np.int64)
    val_idx = np.zeros(sigs, dtype=np.int32)
    for s in range(sigs):
        p = _pub(node, s)
        m = b"fleet-msg:%d:%d:%d" % (node, req, s)
        good = _sign(p, m)
        forged = rng.random() < _FORGE_RATE
        sg = _sign(p, m + b"!forged") if forged else good
        pub[s] = np.frombuffer(p, dtype=np.uint8)
        sig[s] = np.frombuffer(sg, dtype=np.uint8)
        msgs.append(m)
        offsets[s + 1] = offsets[s] + len(m)
        val_idx[s] = s
    # epoch metadata rides the wire: nodes in the same epoch bucket
    # produce same-key blocks — the cross-node coalescing hook
    epoch_key = b"fleet-epoch:%d" % (req % 3)
    return EntryBlock(pub, sig, b"".join(msgs), offsets,
                      val_idx=val_idx, epoch_key=epoch_key)


def run_fleet_scenario(
    seed: int = 0,
    n_nodes: int = 100,
    reqs_per_node: int = 6,
    sigs_per_req: int = 8,
    kill_at: Optional[float] = None,
    revive_at: Optional[float] = None,
    span_s: float = 10.0,
    all_local: bool = False,
) -> dict:
    """Run the shared-fleet scenario; returns the report dict.

    ``kill_at`` / ``revive_at`` are virtual seconds from scenario start.
    ``all_local=True`` runs the identical schedule with every node
    verifying locally — the parity baseline for verdict_fingerprint.
    """
    clock = SimClock(seed=seed)
    start = clock.time()
    host = LoopbackFleetHost(check_block)
    sessions = [LoopbackSession(host, name="node-%03d" % i)
                for i in range(n_nodes)]

    verdict_h = hashlib.sha256()
    run_h = hashlib.sha256()
    report = {
        "seed": seed,
        "n_nodes": n_nodes,
        "requests": 0,
        "sigs": 0,
        "invalid_sigs": 0,
        "fleet_verdicts": 0,
        "fallback_verdicts": 0,
        "stalled_requests": 0,
    }

    def _deliver(node: int, source: str, priority: int,
                 verdicts: np.ndarray) -> None:
        vb = np.asarray(verdicts, dtype=np.uint8).tobytes()
        verdict_h.update(vb)
        run_h.update(b"%d:%s:%d:" % (node, source.encode(), priority) + vb)
        report["requests"] += 1
        report["sigs"] += len(vb)
        report["invalid_sigs"] += int(len(vb) - int(np.sum(verdicts)))
        if source == "fleet":
            report["fleet_verdicts"] += 1
        else:
            report["fallback_verdicts"] += 1

    def _submit(node: int, req: int) -> None:
        block = _build_block(clock.rng, node, req, sigs_per_req)
        priority = req % 3  # consensus / replay / ingress round-robin
        if all_local:
            _deliver(node, "local", priority, check_block(block, priority))
            return
        try:
            v = sessions[node].submit_block(block, priority=priority,
                                            flow=clock.next_flow())
        except FleetUnavailable:
            # graceful degradation: verify locally with the SAME checker
            # — the verdict cannot differ, only its source does
            _deliver(node, "local", priority, check_block(block, priority))
            return
        _deliver(node, "fleet", priority, v)

    # Schedule: node i's request r fires at a deterministic spread over
    # span_s (request order across nodes interleaves like a real
    # cluster; jitter comes from the seeded PRNG only)
    for i in range(n_nodes):
        for r in range(reqs_per_node):
            when = start + (r + (i + 1) / (n_nodes + 1)) * (
                span_s / max(reqs_per_node, 1)
            ) + clock.rng.random() * 0.010
            clock.call_at(when, lambda i=i, r=r: _submit(i, r))

    if kill_at is not None:
        clock.call_at(start + kill_at, host.kill)
    if revive_at is not None:
        clock.call_at(start + revive_at, host.revive)

    clock.run_until()
    expected = n_nodes * reqs_per_node
    report["stalled_requests"] = expected - report["requests"]
    report["events_run"] = clock.events_run
    report["host"] = {
        "frames_accepted": host.frames_accepted,
        "frames_rejected": host.frames_rejected,
        "sigs": host.sigs,
        "by_priority": dict(host.by_priority),
        "killed": host.killed,
    }
    report["verdict_fingerprint"] = verdict_h.hexdigest()
    report["run_fingerprint"] = run_h.hexdigest()
    return report
