"""Declarative fault schedules for simnet runs.

A schedule is a list of Fault records, each with one trigger — a commit
height (`at_height`: fires when the first correct node commits that
height) or a virtual time offset (`at_time`: seconds after sim start) —
and an optional `duration` after which the inverse action runs
automatically (heal a partition, restart a crashed node).

Kinds:
  partition    split nodes into isolated groups (`groups` of node indices)
  heal         drop the active partition
  crash        kill a node mid-flight: its in-memory state is discarded,
               its WAL/stores survive (the "disk"), in-flight messages to
               and from it vanish
  restart      rebuild a crashed node from its WAL + stores and rejoin
  clock_skew   shift what one node reads as "now" by `skew` seconds
  double_sign  make a node's vote source byzantine: it signs and gossips
               two conflicting prevotes per round (equivocation)
  val_join     promote node `node` (a standby full node) into the active
               validator set with voting power `power` — a validator tx
               rides a block, EndBlock returns the update, and the REAL
               state.execution update path rotates the set two heights on
  val_leave    remove validator `node` from the active set (power-0
               update through the same EndBlock path)
  val_power    change validator `node`'s voting power to `power`

The three val_* kinds all route through ValidatorSet._update_with_change_set,
so each one structurally invalidates ValidatorSet.hash() — a new epoch key
for the device epoch cache (ops/epoch_cache.py) — and drives the cache
through cold→warm→evict cycles under live consensus.

JSON form (tools/simnet_run.py --faults): a list of objects with the
same field names, e.g.
  [{"kind": "partition", "at_height": 5, "groups": [[0, 1], [2, 3]],
    "duration": 2.0},
   {"kind": "crash", "at_height": 8, "node": 2, "restart_after": 1.0},
   {"kind": "val_join", "at_height": 6, "node": 4, "power": 10}]
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, replace as _dc_replace
from typing import List, Optional, Sequence


@dataclass
class Fault:
    kind: str
    at_height: Optional[int] = None
    at_time: Optional[float] = None
    node: Optional[int] = None
    groups: Optional[List[List[int]]] = None
    duration: Optional[float] = None  # partition: heal after
    restart_after: Optional[float] = None  # crash: restart after
    skew: float = 0.0
    power: Optional[int] = None  # val_join/val_power: new voting power

    VALID_KINDS = (
        "partition",
        "heal",
        "crash",
        "restart",
        "clock_skew",
        "double_sign",
        "val_join",
        "val_leave",
        "val_power",
    )
    _NODE_KINDS = (
        "crash", "restart", "clock_skew", "double_sign",
        "val_join", "val_leave", "val_power",
    )

    def validate(self, n_nodes: int) -> None:
        if self.kind not in self.VALID_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.at_height is not None and self.at_time is not None:
            raise ValueError(
                f"{self.kind}: at_height and at_time are mutually exclusive"
            )
        if self.at_height is None and self.at_time is None and self.kind != "double_sign":
            raise ValueError(f"{self.kind}: needs at_height or at_time")
        if self.restart_after is not None and self.kind != "crash":
            raise ValueError(f"{self.kind}: restart_after only applies to crash")
        if self.duration is not None and self.kind != "partition":
            raise ValueError(f"{self.kind}: duration only applies to partition")
        if self.kind == "partition" and not self.groups:
            raise ValueError("partition: needs groups")
        if self.kind in self._NODE_KINDS:
            if self.node is None or not 0 <= self.node < n_nodes:
                raise ValueError(f"{self.kind}: needs node in 0..{n_nodes - 1}")
        if self.kind in ("val_join", "val_power"):
            if self.power is None or self.power <= 0:
                raise ValueError(f"{self.kind}: needs power >= 1")
        elif self.power is not None:
            # val_leave included: leaving IS the power-0 update — an
            # explicit power here would be silently ignored
            raise ValueError(f"{self.kind}: power only applies to val_join/val_power")
        if self.groups:
            for g in self.groups:
                for i in g:
                    if not 0 <= i < n_nodes:
                        raise ValueError(f"partition: node {i} out of range")

    def to_dict(self) -> dict:
        """JSON form: only the fields that differ from the defaults, so
        emitted regression scenarios stay minimal and diff-friendly."""
        out = {"kind": self.kind}
        for name, field_ in self.__dataclass_fields__.items():
            if name == "kind":
                continue
            v = getattr(self, name)
            if v != field_.default:
                out[name] = v
        return out


_KNOWN_FAULT_FIELDS = frozenset(Fault.__dataclass_fields__)


def parse_faults(raw: Sequence[dict]) -> List[Fault]:
    out = []
    for obj in raw:
        extra = set(obj) - _KNOWN_FAULT_FIELDS
        if extra:
            raise ValueError(f"unknown fault fields: {sorted(extra)}")
        out.append(Fault(**obj))
    return out


# -- canned schedules --------------------------------------------------------


def partition_heal_schedule(
    n_nodes: int, at_height: int = 5, duration: float = 3.0
) -> List[Fault]:
    """Split the cluster down the middle (minority/majority for odd n) at
    `at_height`, heal after `duration` virtual seconds. With 4 nodes a
    2/2 split has no quorum on either side — progress must stall, then
    resume on heal."""
    half = n_nodes // 2
    groups = [list(range(half)), list(range(half, n_nodes))]
    return [
        Fault(kind="partition", at_height=at_height, groups=groups, duration=duration)
    ]


def crash_restart_schedule(
    node: int, at_height: int = 8, restart_after: float = 1.0
) -> List[Fault]:
    return [
        Fault(kind="crash", at_height=at_height, node=node, restart_after=restart_after)
    ]


def smoke_schedule(n_nodes: int) -> List[Fault]:
    """The tier-1 smoke run: partition-and-heal, then one crash +
    WAL-restart — the acceptance scenario."""
    return partition_heal_schedule(n_nodes, at_height=3, duration=2.0) + (
        crash_restart_schedule(n_nodes - 1, at_height=6, restart_after=1.0)
    )


def rotation_schedule(
    n_nodes: int,
    n_validators: int,
    every: int = 5,
    start: int = 3,
    until: int = 20,
    power: int = 10,
) -> List[Fault]:
    """Churn the active validator set every `every` heights: at each
    rotation height the next standby full node joins and the oldest
    active validator leaves (both in the same block's EndBlock updates,
    so the active set size stays constant and quorum viability is never
    in question). Validators cycle round-robin through ALL nodes, so a
    long enough run rotates every node through the active set.

    With no standbys (n_validators == n_nodes) rotations degrade to
    power changes — still a structural ValidatorSet.hash() invalidation,
    still a fresh epoch for the device cache."""
    if not 1 <= n_validators <= n_nodes:
        raise ValueError(f"n_validators must be in 1..{n_nodes}")
    active = list(range(n_validators))
    standby = list(range(n_validators, n_nodes))
    out: List[Fault] = []
    bump = 0
    for h in range(start, until + 1, max(every, 1)):
        if standby:
            joiner = standby.pop(0)
            leaver = active.pop(0)
            out.append(Fault(kind="val_join", at_height=h, node=joiner, power=power))
            out.append(Fault(kind="val_leave", at_height=h, node=leaver))
            active.append(joiner)
            standby.append(leaver)
        else:
            # full-validator cluster: rotate powers instead of membership
            bump += 1
            target = active[bump % len(active)]
            out.append(
                Fault(
                    kind="val_power", at_height=h, node=target,
                    power=power + bump,
                )
            )
    return out


# -- byzantine vote source ---------------------------------------------------


def make_double_sign_prevote(priv_key, chain_id: str):
    """A do_prevote_override that equivocates: signs the honest prevote
    AND a conflicting prevote for a fabricated block, gossiping both.
    Bypasses the FilePV last-sign-state on purpose — that guard is
    exactly what a byzantine validator ignores. Correct peers keep one of
    the two (first to arrive) and flag the other as conflicting
    (ErrVoteConflictingVotes → duplicate-vote evidence when an evidence
    pool is wired)."""
    from ..consensus.state import VoteMessage
    from ..types import BlockID
    from ..types.block import PartSetHeader
    from ..types.vote import PREVOTE_TYPE, Vote

    addr = priv_key.pub_key().address()

    def override(cs, height: int, round_: int) -> None:
        rs = cs.rs
        idx, val = rs.validators.get_by_address(addr)
        if val is None:
            return
        if rs.proposal_block is not None and rs.proposal_block_parts is not None:
            honest_bid = BlockID(
                hash=rs.proposal_block.hash(),
                part_set_header=rs.proposal_block_parts.header(),
            )
        else:
            honest_bid = BlockID()  # nil prevote
        fake = hashlib.sha256(b"equivocate|%d|%d" % (height, round_)).digest()
        evil_bid = BlockID(
            hash=fake, part_set_header=PartSetHeader(total=1, hash=fake)
        )
        ts = cs._vote_time()
        for bid in (honest_bid, evil_bid):
            v = Vote(
                type=PREVOTE_TYPE,
                height=height,
                round=round_,
                block_id=bid,
                timestamp=ts,
                validator_address=addr,
                validator_index=idx,
            )
            v = _dc_replace(v, signature=priv_key.sign(v.sign_bytes(chain_id)))
            cs._send_internal(VoteMessage(v))

    return override
