"""Declarative fault schedules for simnet runs.

A schedule is a list of Fault records, each with one trigger — a commit
height (`at_height`: fires when the first correct node commits that
height) or a virtual time offset (`at_time`: seconds after sim start) —
and an optional `duration` after which the inverse action runs
automatically (heal a partition, restart a crashed node).

Kinds:
  partition    split nodes into isolated groups (`groups` of node indices)
  heal         drop the active partition
  crash        kill a node mid-flight: its in-memory state is discarded,
               its WAL/stores survive (the "disk"), in-flight messages to
               and from it vanish
  restart      rebuild a crashed node from its WAL + stores and rejoin
  clock_skew   shift what one node reads as "now" by `skew` seconds
  double_sign  make a node's vote source byzantine: it signs and gossips
               two conflicting prevotes per round (equivocation)

JSON form (tools/simnet_run.py --faults): a list of objects with the
same field names, e.g.
  [{"kind": "partition", "at_height": 5, "groups": [[0, 1], [2, 3]],
    "duration": 2.0},
   {"kind": "crash", "at_height": 8, "node": 2, "restart_after": 1.0}]
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, replace as _dc_replace
from typing import List, Optional, Sequence


@dataclass
class Fault:
    kind: str
    at_height: Optional[int] = None
    at_time: Optional[float] = None
    node: Optional[int] = None
    groups: Optional[List[List[int]]] = None
    duration: Optional[float] = None  # partition: heal after
    restart_after: Optional[float] = None  # crash: restart after
    skew: float = 0.0

    VALID_KINDS = (
        "partition",
        "heal",
        "crash",
        "restart",
        "clock_skew",
        "double_sign",
    )

    def validate(self, n_nodes: int) -> None:
        if self.kind not in self.VALID_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.at_height is None and self.at_time is None and self.kind != "double_sign":
            raise ValueError(f"{self.kind}: needs at_height or at_time")
        if self.kind == "partition" and not self.groups:
            raise ValueError("partition: needs groups")
        if self.kind in ("crash", "restart", "clock_skew", "double_sign"):
            if self.node is None or not 0 <= self.node < n_nodes:
                raise ValueError(f"{self.kind}: needs node in 0..{n_nodes - 1}")
        if self.groups:
            for g in self.groups:
                for i in g:
                    if not 0 <= i < n_nodes:
                        raise ValueError(f"partition: node {i} out of range")


def parse_faults(raw: Sequence[dict]) -> List[Fault]:
    out = []
    for obj in raw:
        known = {f for f in Fault.__dataclass_fields__}
        extra = set(obj) - known
        if extra:
            raise ValueError(f"unknown fault fields: {sorted(extra)}")
        out.append(Fault(**obj))
    return out


# -- canned schedules --------------------------------------------------------


def partition_heal_schedule(
    n_nodes: int, at_height: int = 5, duration: float = 3.0
) -> List[Fault]:
    """Split the cluster down the middle (minority/majority for odd n) at
    `at_height`, heal after `duration` virtual seconds. With 4 nodes a
    2/2 split has no quorum on either side — progress must stall, then
    resume on heal."""
    half = n_nodes // 2
    groups = [list(range(half)), list(range(half, n_nodes))]
    return [
        Fault(kind="partition", at_height=at_height, groups=groups, duration=duration)
    ]


def crash_restart_schedule(
    node: int, at_height: int = 8, restart_after: float = 1.0
) -> List[Fault]:
    return [
        Fault(kind="crash", at_height=at_height, node=node, restart_after=restart_after)
    ]


def smoke_schedule(n_nodes: int) -> List[Fault]:
    """The tier-1 smoke run: partition-and-heal, then one crash +
    WAL-restart — the acceptance scenario."""
    return partition_heal_schedule(n_nodes, at_height=3, duration=2.0) + (
        crash_restart_schedule(n_nodes - 1, at_height=6, restart_after=1.0)
    )


# -- byzantine vote source ---------------------------------------------------


def make_double_sign_prevote(priv_key, chain_id: str):
    """A do_prevote_override that equivocates: signs the honest prevote
    AND a conflicting prevote for a fabricated block, gossiping both.
    Bypasses the FilePV last-sign-state on purpose — that guard is
    exactly what a byzantine validator ignores. Correct peers keep one of
    the two (first to arrive) and flag the other as conflicting
    (ErrVoteConflictingVotes → duplicate-vote evidence when an evidence
    pool is wired)."""
    from ..consensus.state import VoteMessage
    from ..types import BlockID
    from ..types.block import PartSetHeader
    from ..types.vote import PREVOTE_TYPE, Vote

    addr = priv_key.pub_key().address()

    def override(cs, height: int, round_: int) -> None:
        rs = cs.rs
        idx, val = rs.validators.get_by_address(addr)
        if val is None:
            return
        if rs.proposal_block is not None and rs.proposal_block_parts is not None:
            honest_bid = BlockID(
                hash=rs.proposal_block.hash(),
                part_set_header=rs.proposal_block_parts.header(),
            )
        else:
            honest_bid = BlockID()  # nil prevote
        fake = hashlib.sha256(b"equivocate|%d|%d" % (height, round_)).digest()
        evil_bid = BlockID(
            hash=fake, part_set_header=PartSetHeader(total=1, hash=fake)
        )
        ts = cs._vote_time()
        for bid in (honest_bid, evil_bid):
            v = Vote(
                type=PREVOTE_TYPE,
                height=height,
                round=round_,
                block_id=bid,
                timestamp=ts,
                validator_address=addr,
                validator_index=idx,
            )
            v = _dc_replace(v, signature=priv_key.sign(v.sign_bytes(chain_id)))
            cs._send_internal(VoteMessage(v))

    return override
