"""PEX — peer-exchange reactor.

Reference parity: internal/p2p/pex/reactor.go — channel 0x00; periodically
requests peer addresses from connected peers and feeds responses into the
PeerManager's address book; answers requests with its own known peers.

Wire: 1 pex_request{} | 2 pex_response{1 addresses(repeated msg{1 id, 2 addr})}
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from ..wire.proto import ProtoWriter, decode_message, field_bytes
from .conn.mconnection import ChannelDescriptor
from .peermanager import PeerAddress, PeerManager
from .router import Router

PEX_CHANNEL = 0x00
PEX_DESC = ChannelDescriptor(id=PEX_CHANNEL, priority=1, send_queue_capacity=10)

_REQUEST_INTERVAL = 5.0
_MAX_ADDRESSES = 100


def _encode_response(pairs) -> bytes:
    w = ProtoWriter()
    inner = ProtoWriter()
    for node_id, addr in pairs:
        e = ProtoWriter()
        e.write_string(1, node_id)
        e.write_string(2, addr)
        inner.write_message(1, e.bytes(), always=True)
    w.write_message(2, inner.bytes(), always=True)
    return w.bytes()


def _encode_request() -> bytes:
    w = ProtoWriter()
    w.write_message(1, b"", always=True)
    return w.bytes()


class PexReactor:
    def __init__(self, router: Router, peer_manager: PeerManager):
        self._router = router
        self._pm = peer_manager
        self._ch = router.open_channel(PEX_DESC)
        self._stopped = threading.Event()

    def start(self) -> None:
        for fn in (self._recv_loop, self._request_loop):
            threading.Thread(target=fn, daemon=True).start()

    def stop(self) -> None:
        self._stopped.set()

    def _request_loop(self) -> None:
        while not self._stopped.is_set():
            self._ch.broadcast(_encode_request())
            time.sleep(_REQUEST_INTERVAL)

    def _recv_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                env = self._ch.receive(timeout=0.5)
            except queue.Empty:
                continue
            try:
                f = decode_message(env.message)
            except ValueError:
                continue
            if 1 in f:  # request: answer with known addresses
                pairs = []
                for nid in self._pm.peers()[:_MAX_ADDRESSES]:
                    for addr in self._pm.addresses(nid)[:1]:
                        pairs.append((nid, addr))
                self._ch.send(env.from_id, _encode_response(pairs))
            elif 2 in f:  # response: absorb addresses
                inner = decode_message(field_bytes(f, 2))
                from ..wire.proto import field_repeated_bytes
                for raw in field_repeated_bytes(inner, 1):
                    e = decode_message(raw)
                    nid = field_bytes(e, 1).decode()
                    addr = field_bytes(e, 2).decode()
                    if nid and addr:
                        try:
                            self._pm.add_address(PeerAddress(nid, addr))
                        except ValueError:
                            continue
