"""P2P transports: the Connection/Transport abstraction, TCP+MConn
implementation, and the in-memory transport for tests.

Reference parity: internal/p2p/transport.go (interfaces),
transport_mconn.go (TCP + SecretConnection + MConnection),
transport_memory.go (the "multi-node without a network" seam the
reference's reactor tests build on, SURVEY.md §4).
"""

from __future__ import annotations

import queue
import socket
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import PrivKey, PubKey
from .conn.mconnection import ChannelDescriptor, MConnection
from .conn.secret_connection import SecretConnection
from .key import node_id_from_pubkey


@dataclass
class Envelope:
    """router.go:24-38 — a routed message."""

    from_id: str = ""
    to_id: str = ""
    channel_id: int = 0
    message: bytes = b""
    broadcast: bool = False


class Connection:
    """transport.go Connection: handshaken, channel-multiplexed link."""

    def __init__(self):
        self.local_id: str = ""
        self.remote_id: str = ""
        self.remote_pubkey: Optional[PubKey] = None

    def send(self, channel_id: int, msg: bytes) -> bool: ...

    def receive(self, timeout: Optional[float] = None) -> Tuple[int, bytes]: ...

    def close(self) -> None: ...


class _SockStream:
    """Adapt a socket to read/write/close."""

    def __init__(self, sock: socket.socket):
        self._sock = sock

    def read(self, n: int) -> bytes:
        try:
            return self._sock.recv(n)
        except OSError:
            return b""

    def write(self, b: bytes) -> None:
        self._sock.sendall(b)

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class MConnConnection(Connection):
    """transport_mconn.go MConnConnection."""

    _cls_close_mtx = threading.Lock()

    def __init__(
        self,
        sock: socket.socket,
        local_priv: PrivKey,
        channel_descs: List[ChannelDescriptor],
        node_info=None,
    ):
        super().__init__()
        stream = _SockStream(sock)
        sconn = SecretConnection(stream, local_priv)  # handshake happens here
        self.remote_pubkey = sconn.remote_pubkey
        self.remote_id = node_id_from_pubkey(sconn.remote_pubkey)
        self.local_id = node_id_from_pubkey(local_priv.pub_key())
        # NodeInfo exchange (transport_mconn.go Handshake): one frame each
        # way over the encrypted link, before channel routing starts.
        self.remote_node_info = None
        if node_info is not None:
            sconn.write(node_info.encode())
            from ..types.node_info import NodeInfo

            raw = sconn.read_msg()
            self.remote_node_info = NodeInfo.decode(raw)
            if self.remote_node_info.node_id != self.remote_id:
                raise ConnectionError(
                    "peer's node info ID does not match its cryptographic identity"
                )
        self._recv_q: "queue.Queue[Tuple[int, bytes]]" = queue.Queue(maxsize=1000)
        self._err: Optional[Exception] = None
        self._mconn = MConnection(
            sconn,
            channel_descs,
            on_receive=lambda ch, msg: self._recv_q.put((ch, msg)),
            on_error=self._on_error,
        )
        self._mconn.start()

    def _on_error(self, e: Exception) -> None:
        self._err = e
        self._recv_q.put((-1, b""))  # wake receivers

    def send(self, channel_id: int, msg: bytes) -> bool:
        return self._mconn.send(channel_id, msg)

    def receive(self, timeout: Optional[float] = None) -> Tuple[int, bytes]:
        ch, msg = self._recv_q.get(timeout=timeout)
        if ch == -1:
            raise ConnectionError(str(self._err) if self._err else "connection closed")
        return ch, msg

    # set by the accepting transport to release its ConnTracker slot
    on_close = None

    def close(self) -> None:
        self._mconn.stop()
        # atomic single-shot release: concurrent close() (router error path
        # vs node shutdown) must not double-decrement the ConnTracker
        with MConnConnection._cls_close_mtx:
            cb, self.on_close = self.on_close, None
        if cb is not None:
            cb()
        # wake any blocked receiver so the router drops this peer promptly
        try:
            self._recv_q.put_nowait((-1, b""))
        except queue.Full:
            pass


class ConnTracker:
    """internal/p2p/conn_tracker.go: caps concurrent inbound connections
    per source IP (anti-monopolization) — AddConn refuses above the
    per-IP limit; RemoveConn on close."""

    def __init__(self, max_per_ip: int = 8):
        self._max = max_per_ip
        self._mtx = threading.Lock()
        self._by_ip: dict = {}

    def add(self, ip: str) -> bool:
        with self._mtx:
            n = self._by_ip.get(ip, 0)
            if n >= self._max:
                return False
            self._by_ip[ip] = n + 1
            return True

    def remove(self, ip: str) -> None:
        with self._mtx:
            n = self._by_ip.get(ip, 0)
            if n <= 1:
                self._by_ip.pop(ip, None)
            else:
                self._by_ip[ip] = n - 1

    def count(self, ip: str) -> int:
        with self._mtx:
            return self._by_ip.get(ip, 0)


class MConnTransport:
    """transport_mconn.go MConnTransport: TCP listener + dialer."""

    def __init__(
        self,
        local_priv: PrivKey,
        channel_descs: List[ChannelDescriptor],
        node_info=None,
        max_conns_per_ip: int = 8,
    ):
        self._priv = local_priv
        self._descs = channel_descs
        self._node_info = node_info
        self._listener: Optional[socket.socket] = None
        self._accept_q: "queue.Queue[MConnConnection]" = queue.Queue(maxsize=64)
        self._closed = False
        self.listen_addr: str = ""
        self._tracker = ConnTracker(max_conns_per_ip)

    def listen(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host or "127.0.0.1", int(port)))
        self._listener.listen(32)
        h, p = self._listener.getsockname()
        self.listen_addr = f"{h}:{p}"
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            ip = addr[0] if addr else ""
            if not self._tracker.add(ip):
                # conn_tracker.go: per-IP inbound cap exceeded
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            threading.Thread(
                target=self._handshake_accepted, args=(sock, ip), daemon=True
            ).start()

    def _handshake_accepted(self, sock: socket.socket, ip: str) -> None:
        try:
            conn = MConnConnection(sock, self._priv, self._descs, self._node_info)
            conn.on_close = lambda: self._tracker.remove(ip)
            self._accept_q.put(conn)
        except Exception:  # noqa: BLE001 — failed handshakes are dropped
            self._tracker.remove(ip)
            try:
                sock.close()
            except OSError:
                pass

    def accept(self, timeout: Optional[float] = None) -> MConnConnection:
        return self._accept_q.get(timeout=timeout)

    def dial(self, addr: str, timeout: float = 5.0) -> MConnConnection:
        host, _, port = addr.rpartition(":")
        sock = socket.create_connection((host or "127.0.0.1", int(port)), timeout=timeout)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return MConnConnection(sock, self._priv, self._descs, self._node_info)

    def close(self) -> None:
        self._closed = True
        if self._listener is not None:
            self._listener.close()


# ---------------------------------------------------------------------------
# In-memory transport (transport_memory.go)


class _MemoryHub:
    """A namespace of in-memory endpoints (MemoryNetwork)."""

    def __init__(self):
        self._endpoints: Dict[str, "MemoryTransport"] = {}
        self._mtx = threading.Lock()

    def register(self, node_id: str, t: "MemoryTransport") -> None:
        with self._mtx:
            self._endpoints[node_id] = t

    def get(self, node_id: str) -> Optional["MemoryTransport"]:
        with self._mtx:
            return self._endpoints.get(node_id)

    def remove(self, node_id: str) -> None:
        with self._mtx:
            self._endpoints.pop(node_id, None)


class MemoryConnection(Connection):
    def __init__(self, local_id: str, remote_id: str, remote_pubkey, send_q, recv_q):
        super().__init__()
        self.local_id = local_id
        self.remote_id = remote_id
        self.remote_pubkey = remote_pubkey
        self._send_q = send_q
        self._recv_q = recv_q
        self._closed = threading.Event()

    def send(self, channel_id: int, msg: bytes) -> bool:
        if self._closed.is_set():
            return False
        try:
            self._send_q.put((channel_id, msg), timeout=5)
            return True
        except queue.Full:
            return False

    def receive(self, timeout: Optional[float] = None) -> Tuple[int, bytes]:
        ch, msg = self._recv_q.get(timeout=timeout)
        if ch == -1:
            raise ConnectionError("connection closed")
        return ch, msg

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            try:
                self._send_q.put_nowait((-1, b""))
            except queue.Full:
                pass


class MemoryTransport:
    """transport_memory.go:345LoC — instant, reliable, in-process."""

    def __init__(self, hub: _MemoryHub, node_id: str, pubkey):
        self._hub = hub
        self.node_id = node_id
        self.pubkey = pubkey
        self._accept_q: "queue.Queue[MemoryConnection]" = queue.Queue(maxsize=64)
        hub.register(node_id, self)

    def accept(self, timeout: Optional[float] = None) -> MemoryConnection:
        return self._accept_q.get(timeout=timeout)

    def dial(self, remote_id: str, timeout: float = 5.0) -> MemoryConnection:
        remote = self._hub.get(remote_id)
        if remote is None:
            raise ConnectionError(f"no memory endpoint {remote_id}")
        a_to_b: queue.Queue = queue.Queue(maxsize=1000)
        b_to_a: queue.Queue = queue.Queue(maxsize=1000)
        ours = MemoryConnection(self.node_id, remote_id, remote.pubkey, a_to_b, b_to_a)
        theirs = MemoryConnection(remote_id, self.node_id, self.pubkey, b_to_a, a_to_b)
        remote._accept_q.put(theirs)
        return ours

    def close(self) -> None:
        self._hub.remove(self.node_id)


def new_memory_network() -> _MemoryHub:
    return _MemoryHub()
