"""Router — connects reactors to peers via typed channels of envelopes.

Reference parity: internal/p2p/router.go:241 — reactors call open_channel
and get a (send, receive) pair of queues; the router runs accept/dial
loops against the transport, a receive thread per peer fanning envelopes
into channels, and a send path routing envelopes (including broadcast) to
per-peer connections.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .conn.mconnection import ChannelDescriptor
from .peermanager import PeerAddress, PeerManager
from .transport import Connection, Envelope


@dataclass
class PeerUpdate:
    """peerupdates.go: status change delivered to reactors."""

    node_id: str
    status: str  # "up" | "down"


class _PeerQueue:
    """internal/p2p/pqueue.go: per-peer outbound queue with one bounded
    deque per channel, drained highest-priority-first by the peer's send
    thread. A slow peer fills its own deques and drops its own traffic —
    it can never head-of-line-block another peer or starve a
    higher-priority channel (vote gossip) behind bulk data (blocksync)."""

    def __init__(self, descs: Dict[int, ChannelDescriptor]):
        self._mtx = threading.Lock()
        self._ready = threading.Event()
        # highest priority first; stable order for equal priorities
        self._order = sorted(descs.values(), key=lambda d: -d.priority)
        self._qs: Dict[int, collections.deque] = {
            d.id: collections.deque(maxlen=d.send_queue_capacity) for d in descs.values()
        }
        self.dropped = 0
        self.closed = False

    def ensure_channel(self, desc: ChannelDescriptor) -> None:
        with self._mtx:
            if desc.id not in self._qs:
                self._qs[desc.id] = collections.deque(maxlen=desc.send_queue_capacity)
                self._order = sorted(
                    self._order + [desc], key=lambda d: -d.priority
                )

    def put(self, channel_id: int, msg: bytes) -> bool:
        with self._mtx:
            q = self._qs.get(channel_id)
            if q is None or self.closed:
                return False
            if len(q) == q.maxlen:
                self.dropped += 1  # pqueue.go drops on overflow
                return False
            q.append(msg)
        self._ready.set()
        return True

    def pop(self, timeout: float) -> Optional[tuple]:
        """Next (channel_id, msg) by priority, or None on timeout/close."""
        while True:
            with self._mtx:
                if self.closed:
                    return None
                for d in self._order:
                    q = self._qs[d.id]
                    if q:
                        return (d.id, q.popleft())
                self._ready.clear()
            if not self._ready.wait(timeout):
                return None

    def close(self) -> None:
        with self._mtx:
            self.closed = True
        self._ready.set()


class Channel:
    """router.go:58-67 — a reactor's handle on one wire channel."""

    def __init__(self, router: "Router", desc: ChannelDescriptor):
        self._router = router
        self.desc = desc
        self.in_q: "queue.Queue[Envelope]" = queue.Queue(maxsize=1000)

    def send(self, to_id: str, message: bytes) -> bool:
        return self._router._route_out(
            Envelope(to_id=to_id, channel_id=self.desc.id, message=message)
        )

    def broadcast(self, message: bytes) -> None:
        self._router._route_out(
            Envelope(channel_id=self.desc.id, message=message, broadcast=True)
        )

    def receive(self, timeout: Optional[float] = None) -> Envelope:
        return self.in_q.get(timeout=timeout)

    def try_receive(self) -> Optional[Envelope]:
        try:
            return self.in_q.get_nowait()
        except queue.Empty:
            return None


class Router:
    """router.go:241-1000."""

    def __init__(self, transport, peer_manager: PeerManager, node_id: str):
        self._transport = transport
        self._pm = peer_manager
        self.node_id = node_id
        self._channels: Dict[int, Channel] = {}
        self._conns: Dict[str, Connection] = {}
        self._queues: Dict[str, _PeerQueue] = {}
        self._mtx = threading.RLock()
        self._stopped = threading.Event()
        self._peer_update_subs: List["queue.Queue[PeerUpdate]"] = []
        self._threads: List[threading.Thread] = []

    # -- channels -------------------------------------------------------

    def open_channel(self, desc: ChannelDescriptor) -> Channel:
        with self._mtx:
            if desc.id in self._channels:
                raise ValueError(f"channel {desc.id} already open")
            ch = Channel(self, desc)
            self._channels[desc.id] = ch
            for pq in self._queues.values():
                pq.ensure_channel(desc)
            return ch

    def subscribe_peer_updates(self) -> "queue.Queue[PeerUpdate]":
        q: "queue.Queue[PeerUpdate]" = queue.Queue(maxsize=100)
        with self._mtx:
            self._peer_update_subs.append(q)
            # deliver current peers as "up" so late subscribers converge
            for nid in self._conns:
                q.put(PeerUpdate(nid, "up"))
        return q

    def _notify_peer_update(self, upd: PeerUpdate) -> None:
        with self._mtx:
            subs = list(self._peer_update_subs)
        for q in subs:
            try:
                q.put_nowait(upd)
            except queue.Full:
                pass

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        for fn in (self._accept_loop, self._dial_loop, self._evict_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stopped.set()
        with self._mtx:
            conns = list(self._conns.values())
        for c in conns:
            c.close()
        if hasattr(self._transport, "close"):
            self._transport.close()

    # -- connection admission -------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn = self._transport.accept(timeout=0.5)
            except queue.Empty:
                continue
            except (OSError, ConnectionError):
                return
            self._admit(conn, inbound=True)

    def _dial_loop(self) -> None:
        while not self._stopped.is_set():
            addr = self._pm.dial_next()
            if addr is None:
                time.sleep(0.1)
                continue
            try:
                conn = self._transport.dial(addr.address)
            except (OSError, ConnectionError, queue.Empty) as e:
                self._pm.dial_failed(addr.node_id)
                continue
            if conn.remote_id != addr.node_id and addr.node_id:
                # peer identity mismatch (router.go handshake check)
                conn.close()
                self._pm.dial_failed(addr.node_id)
                continue
            self._admit(conn, inbound=False)

    def _admit(self, conn: Connection, inbound: bool) -> None:
        nid = conn.remote_id
        ok = self._pm.accepted(nid) if inbound else self._pm.dialed(nid)
        if not ok:
            conn.close()
            return
        with self._mtx:
            pq = _PeerQueue({c.desc.id: c.desc for c in self._channels.values()})
            self._conns[nid] = conn
            self._queues[nid] = pq
        for fn in (self._receive_peer, self._send_peer):
            # per-connection daemon threads exit with the connection and are
            # deliberately NOT retained: under peer churn a kept list would
            # grow without bound (only the loop threads in start() persist)
            threading.Thread(target=fn, args=(conn,), daemon=True).start()
        self._notify_peer_update(PeerUpdate(nid, "up"))

    def _evict_loop(self) -> None:
        """router.go evictPeers: pump the peer manager's eviction queue;
        also the periodic address-book GC home."""
        last_gc = time.time()
        while not self._stopped.is_set():
            if time.time() - last_gc > 30:
                self._pm.prune_addresses()
                last_gc = time.time()
            nid = self._pm.evict_next()
            if nid is None:
                time.sleep(0.1)
                continue
            if not self.disconnect_peer(nid):
                # connection not registered yet (admit in flight): retry
                self._pm.evict_failed(nid)
                time.sleep(0.05)

    def _drop_peer(self, conn: Connection, err: Optional[Exception]) -> None:
        nid = conn.remote_id
        with self._mtx:
            if self._conns.get(nid) is conn:
                del self._conns[nid]
                pq = self._queues.pop(nid, None)
                if pq is not None:
                    pq.close()
        conn.close()
        self._pm.disconnected(nid)
        if err is not None:
            self._pm.errored(nid, err)
        self._notify_peer_update(PeerUpdate(nid, "down"))

    # -- routing --------------------------------------------------------

    def _receive_peer(self, conn: Connection) -> None:
        """router.go:905-989 receivePeer."""
        while not self._stopped.is_set():
            try:
                channel_id, msg = conn.receive(timeout=1.0)
            except queue.Empty:
                continue
            except (ConnectionError, OSError, ValueError) as e:
                self._drop_peer(conn, e)
                return
            ch = self._channels.get(channel_id)
            if ch is None:
                continue
            env = Envelope(from_id=conn.remote_id, channel_id=channel_id, message=msg)
            try:
                ch.in_q.put(env, timeout=5)
            except queue.Full:
                pass  # drop under backpressure (router.go pqueue drop)

    def _send_peer(self, conn: Connection) -> None:
        """router.go:855-903 sendPeer: drain this peer's priority queue
        onto its connection; a stalled connection only blocks this peer."""
        nid = conn.remote_id
        with self._mtx:
            pq = self._queues.get(nid)
        if pq is None:
            return
        while not self._stopped.is_set():
            item = pq.pop(timeout=0.5)
            if item is None:
                if pq.closed:
                    return
                continue
            channel_id, msg = item
            try:
                conn.send(channel_id, msg)
            except (ConnectionError, OSError) as e:
                self._drop_peer(conn, e)
                return

    def _route_out(self, env: Envelope) -> bool:
        with self._mtx:
            if env.broadcast:
                queues = list(self._queues.values())
            else:
                q = self._queues.get(env.to_id)
                queues = [q] if q is not None else []
        ok = bool(queues)
        for q in queues:
            if not q.put(env.channel_id, env.message):
                ok = False  # per-peer per-channel overflow drop (pqueue.go)
        return ok

    def connected(self) -> List[str]:
        with self._mtx:
            return list(self._conns)

    def disconnect_peer(self, node_id: str) -> bool:
        """Sever a peer connection (evictions, test perturbations); the
        peer manager will redial persistent peers. Returns False when no
        connection is registered for the node."""
        with self._mtx:
            conn = self._conns.get(node_id)
        if conn is None:
            return False
        self._drop_peer(conn, None)
        return True
