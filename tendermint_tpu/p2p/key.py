"""Node identity keys.

Reference parity: types/node_key.go, types/node_id.go — NodeID is the hex
of the ed25519 address (first 20 bytes of SHA256(pubkey)).
"""

from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass

from ..crypto import PrivKey, PubKey, ed25519


def node_id_from_pubkey(pub: PubKey) -> str:
    return pub.address().hex()


def validate_node_id(node_id: str) -> None:
    if len(node_id) != 40:
        raise ValueError(f"invalid node ID length {len(node_id)}")
    bytes.fromhex(node_id)  # raises on non-hex


@dataclass
class NodeKey:
    priv_key: PrivKey

    @property
    def node_id(self) -> str:
        return node_id_from_pubkey(self.priv_key.pub_key())

    @property
    def pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    @classmethod
    def generate(cls, seed: bytes | None = None) -> "NodeKey":
        return cls(priv_key=ed25519.gen_priv_key(seed))

    @classmethod
    def load_or_generate(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            with open(path) as fh:
                obj = json.load(fh)
            return cls(priv_key=ed25519.PrivKey(base64.b64decode(obj["priv_key"]["value"])))
        nk = cls.generate()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            json.dump(
                {
                    "id": nk.node_id,
                    "priv_key": {
                        "type": ed25519.PRIV_KEY_NAME,
                        "value": base64.b64encode(nk.priv_key.bytes()).decode(),
                    },
                },
                fh,
                indent=2,
            )
        return nk
