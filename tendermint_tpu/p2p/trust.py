"""Peer trust metric — PID-style reliability scoring with faded memories.

Reference parity: internal/p2p/trust/ (metric.go, store.go; the math is
specified in the reference's ADR-006). A metric blends three components:

  trust = P_weight * proportional + I_weight * history + weighted_derivative

- proportional: good/(good+bad) for the CURRENT interval (1.0 when empty)
- history (integral): weighted mean of past interval values, newer
  intervals weighted by 0.8^i ("optimistic" weights), with logarithmic
  "faded memories" so a 14-day window needs only ~log2(intervals) slots
- derivative: (proportional - history), counted only when NEGATIVE
  (gamma1=0, gamma2=1) so sudden misbehavior bites immediately while
  improvement must be earned through history

This build drives interval advancement explicitly (advance()) or by
elapsed wall-time (tick()), instead of a goroutine+ticker; the math is
interval-count-based either way, so scores match the reference for the
same event/interval sequence.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Dict, List, Optional

# metric.go:16-25
DERIVATIVE_GAMMA1 = 0.0  # weight when current behavior >= history
DERIVATIVE_GAMMA2 = 1.0  # weight when current behavior < history
HISTORY_DATA_WEIGHT = 0.8

# config.go DefaultConfig
DEFAULT_PROPORTIONAL_WEIGHT = 0.4
DEFAULT_INTEGRAL_WEIGHT = 0.6
DEFAULT_TRACKING_WINDOW_S = 14 * 24 * 60 * 60.0  # 14 days
DEFAULT_INTERVAL_S = 60.0


def _interval_to_history_offset(interval: int) -> int:
    """metric.go:407: the ith interval lives at history index
    floor(log2(i)) from the end (2^m intervals in m slots)."""
    return int(math.floor(math.log2(interval)))


class TrustMetric:
    """metric.go Metric."""

    def __init__(
        self,
        proportional_weight: float = DEFAULT_PROPORTIONAL_WEIGHT,
        integral_weight: float = DEFAULT_INTEGRAL_WEIGHT,
        tracking_window_s: float = DEFAULT_TRACKING_WINDOW_S,
        interval_s: float = DEFAULT_INTERVAL_S,
    ):
        self._mtx = threading.Lock()
        self.proportional_weight = proportional_weight
        self.integral_weight = integral_weight
        self.interval_s = interval_s
        if tracking_window_s < interval_s:
            raise ValueError(
                "tracking_window_s must be at least interval_s "
                f"({tracking_window_s} < {interval_s})"
            )
        self.max_intervals = int(tracking_window_s / interval_s)
        self.history_max_size = _interval_to_history_offset(self.max_intervals) + 1
        self.num_intervals = 0
        self.history: List[float] = []
        self.history_weights: List[float] = []
        self.history_weight_sum = 0.0
        self.history_value = 1.0  # perfect history so far
        self.good = 0.0
        self.bad = 0.0
        self.paused = False
        self._last_tick = time.monotonic()

    # -- events (metric.go BadEvents/GoodEvents) -------------------------

    def bad_events(self, num: int = 1) -> None:
        with self._mtx:
            self._unpause()
            self.bad += num

    def good_events(self, num: int = 1) -> None:
        with self._mtx:
            self._unpause()
            self.good += num

    def pause(self) -> None:
        """History stops evolving until the next recorded event."""
        with self._mtx:
            self.paused = True

    # -- scores ----------------------------------------------------------

    def trust_value(self) -> float:
        with self._mtx:
            return self._calc_trust_value()

    def trust_score(self) -> int:
        """0..100 (metric.go TrustScore)."""
        return int(math.floor(self.trust_value() * 100))

    # -- interval advancement -------------------------------------------

    def tick(self) -> None:
        """Advance by however many whole intervals of wall time elapsed
        (replaces the reference's ticker goroutine). The elapsed-interval
        bookkeeping happens under the lock so concurrent tickers cannot
        double-advance."""
        now = time.monotonic()
        with self._mtx:
            n = int((now - self._last_tick) / self.interval_s)
            if n <= 0:
                return
            self._last_tick += n * self.interval_s
        for _ in range(n):
            self.advance()

    def advance(self) -> None:
        """metric.go NextTimeInterval."""
        with self._mtx:
            if self.paused:
                return
            new_hist = self._calc_trust_value()
            self.history.append(new_hist)
            if len(self.history) > self.history_max_size:
                self.history = self.history[-self.history_max_size :]
            if self.num_intervals < self.max_intervals:
                self.num_intervals += 1
                wk = HISTORY_DATA_WEIGHT**self.num_intervals
                self.history_weights.append(wk)
                self.history_weight_sum += wk
            self._update_faded_memory()
            self.history_value = self._calc_history_value()
            self.good = 0.0
            self.bad = 0.0

    # -- persistence (store.go / MetricHistoryJSON) ----------------------

    def history_dict(self) -> dict:
        with self._mtx:
            return {"intervals": self.num_intervals, "history": list(self.history)}

    def history_json(self) -> str:
        return json.dumps(self.history_dict())

    def init_from_json(self, data: str) -> None:
        """metric.go Init: restore a saved history. Inconsistent blobs
        (interval count unsupported by the history list — a truncated or
        corrupt write) are clamped rather than trusted: every faded-memory
        offset the restored interval count implies must be addressable."""
        hist = json.loads(data)
        n = min(int(hist.get("intervals", 0)), self.max_intervals)
        h = [float(x) for x in hist.get("history", [])][-self.history_max_size :]
        while n > 0 and (
            not h
            or (n > 1 and _interval_to_history_offset(n - 1) >= len(h))
        ):
            n -= 1
        with self._mtx:
            if n == 0:
                self.num_intervals = 0
                self.history = []
                self.history_weights = []
                self.history_weight_sum = 0.0
                self.history_value = 1.0
                return
            self.num_intervals = n
            self.history = h
            self.history_weights = [
                HISTORY_DATA_WEIGHT**i for i in range(1, n + 1)
            ]
            self.history_weight_sum = sum(self.history_weights)
            self.history_value = self._calc_history_value()

    # -- private (metric.go:320-405) -------------------------------------

    def _unpause(self) -> None:
        if self.paused:
            self.good = 0.0
            self.bad = 0.0
            self.paused = False

    def _proportional_value(self) -> float:
        total = self.good + self.bad
        return self.good / total if total > 0 else 1.0

    def _calc_trust_value(self) -> float:
        p = self._proportional_value()
        d = p - self.history_value
        weight = DERIVATIVE_GAMMA2 if d < 0 else DERIVATIVE_GAMMA1
        tv = (
            self.proportional_weight * p
            + self.integral_weight * self.history_value
            + weight * d
        )
        return max(tv, 0.0)

    def _calc_history_value(self) -> float:
        hv = 0.0
        for i in range(self.num_intervals):
            hv += self._faded_memory_value(i) * self.history_weights[i]
        return hv / self.history_weight_sum if self.history_weight_sum else 1.0

    def _faded_memory_value(self, interval: int) -> float:
        first = len(self.history) - 1
        if interval == 0:
            return self.history[first]
        return self.history[first - _interval_to_history_offset(interval)]

    def _update_faded_memory(self) -> None:
        """Faded memories: merge pairs, spreading older data out
        (metric.go:390-405)."""
        size = len(self.history)
        if size < 2:
            return
        end = size - 1
        for count in range(1, size):
            i = end - count
            x = 2.0**count
            self.history[i] = ((self.history[i] * (x - 1)) + self.history[i + 1]) / x


class TrustMetricStore:
    """store.go Store: per-peer metrics with optional persistence into a
    DB-like object (get/set of the JSON blob under one key)."""

    _KEY = b"trustMetricStore"

    def __init__(self, db=None, **metric_kwargs):
        self._mtx = threading.Lock()
        self._db = db
        self._kwargs = metric_kwargs
        self.metrics: Dict[str, TrustMetric] = {}
        if db is not None:
            self._load()

    def size(self) -> int:
        with self._mtx:
            return len(self.metrics)

    def get_peer_trust_metric(self, peer_id: str) -> TrustMetric:
        with self._mtx:
            m = self.metrics.get(peer_id)
            if m is None:
                m = TrustMetric(**self._kwargs)
                self.metrics[peer_id] = m
            return m

    def peer_disconnected(self, peer_id: str) -> None:
        """store.go PeerDisconnected: pause the metric so history stops
        evolving while the peer is away."""
        with self._mtx:
            m = self.metrics.get(peer_id)
        if m is not None:
            m.pause()

    def save(self) -> None:
        if self._db is None:
            return
        with self._mtx:
            blob = json.dumps(
                {pid: m.history_dict() for pid, m in self.metrics.items()}
            )
        self._db.set(self._KEY, blob.encode())

    def _load(self) -> None:
        raw: Optional[bytes] = self._db.get(self._KEY)
        if not raw:
            return
        try:
            peers = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(peers, dict):
            return  # corrupt top level: start every peer fresh
        for pid, hist in peers.items():
            m = TrustMetric(**self._kwargs)
            try:
                m.init_from_json(json.dumps(hist))
            except (ValueError, TypeError, AttributeError):
                continue  # corrupt entry: start the peer fresh
            self.metrics[pid] = m
