"""tendermint_tpu.p2p — the distributed communication backend (reference
internal/p2p/, L9): router + peer manager + MConn transport over
SecretConnection, plus the in-memory transport for tests."""

from .conn.mconnection import ChannelDescriptor, MConnection  # noqa: F401
from .conn.secret_connection import SecretConnection  # noqa: F401
from .key import NodeKey, node_id_from_pubkey, validate_node_id  # noqa: F401
from .peermanager import PeerAddress, PeerManager  # noqa: F401
from .router import Channel, Envelope, PeerUpdate, Router  # noqa: F401
from .transport import (  # noqa: F401
    MConnTransport,
    MemoryTransport,
    new_memory_network,
)
