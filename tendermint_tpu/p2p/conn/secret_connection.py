"""SecretConnection — authenticated encryption for peer links.

Reference parity: internal/p2p/conn/secret_connection.go — the STS
pattern: ephemeral X25519 ECDH → HKDF-SHA256 key derivation (one key per
direction, lexicographic ephemeral-key ordering picks which) → challenge
signed by the node's ed25519 key, exchanged over the encrypted channel →
ChaCha20-Poly1305 AEAD frames with per-direction 96-bit counter nonces
and 1024-byte data frames (conn/secret_connection.go:18-21,55,63,92).

Deviation (documented): the reference hashes the handshake transcript with
a Merlin/STROBE transcript; this build uses HKDF-SHA256 over the same
transcript inputs. Same authentication structure, different KDF — nodes of
this framework interoperate with each other, not with Go peers.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Tuple

try:  # X25519/AEAD need the OpenSSL wheel. Under TM_TPU_PUREPY_CRYPTO=1
    # (see crypto/ed25519) the p2p package still imports without it
    # (memory transports, router, peer manager) and only establishing a
    # SecretConnection raises.
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives import hashes

    _HAVE_OPENSSL = True
except ModuleNotFoundError:
    if not os.environ.get("TM_TPU_PUREPY_CRYPTO"):
        raise
    _HAVE_OPENSSL = False

from ...crypto import PrivKey, PubKey, ed25519
from ...wire.proto import ProtoWriter, decode_message, field_bytes

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = 1028
AEAD_TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE


class ShareEphemeralError(RuntimeError):
    pass


class AuthError(RuntimeError):
    pass


def _hkdf_keys(secret: bytes, transcript: bytes) -> Tuple[bytes, bytes, bytes]:
    """Derive (recv_for_lo, send_for_lo, challenge): 96 bytes total."""
    out = HKDF(
        algorithm=hashes.SHA256(),
        length=96,
        salt=None,
        info=b"TENDERMINT_TPU_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN" + transcript,
    ).derive(secret)
    return out[:32], out[32:64], out[64:96]


class SecretConnection:
    """Wraps a duplex stream-like object with read(n)/write(b)/close()."""

    def __init__(self, conn, local_priv: PrivKey):
        if not _HAVE_OPENSSL:
            raise RuntimeError(
                "SecretConnection requires the `cryptography` OpenSSL wheel "
                "(X25519/ChaCha20-Poly1305)"
            )
        self._conn = conn
        self._send_mtx = threading.Lock()
        self._recv_mtx = threading.Lock()
        self._recv_buf = b""
        self._send_nonce = 0
        self._recv_nonce = 0

        # 1. exchange ephemeral X25519 pubkeys (unencrypted)
        eph_priv = X25519PrivateKey.generate()
        eph_pub = eph_priv.public_key().public_bytes_raw()
        self._write_raw(eph_pub)
        remote_eph = self._read_raw(32)

        # 2. DH + directional key derivation (lexicographic ordering)
        secret = eph_priv.exchange(X25519PublicKey.from_public_bytes(remote_eph))
        lo, hi = sorted([eph_pub, remote_eph])
        transcript = lo + hi
        recv_lo, send_lo, challenge = _hkdf_keys(secret, transcript)
        if eph_pub == lo:
            send_key, recv_key = send_lo, recv_lo
        else:
            send_key, recv_key = recv_lo, send_lo
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)

        # 3. exchange AuthSig{pubkey, sig(challenge)} over the encrypted link
        sig = local_priv.sign(challenge)
        w = ProtoWriter()
        w.write_bytes(1, local_priv.pub_key().bytes())
        w.write_bytes(2, sig)
        self.write(w.bytes())
        auth = self.read_msg()
        f = decode_message(auth)
        remote_pub_bytes = field_bytes(f, 1)
        remote_sig = field_bytes(f, 2)
        remote_pub = ed25519.PubKey(remote_pub_bytes)
        if not remote_pub.verify_signature(challenge, remote_sig):
            self.close()
            raise AuthError("challenge verification failed")
        self.remote_pubkey: PubKey = remote_pub

    # -- raw I/O --------------------------------------------------------

    def _write_raw(self, b: bytes) -> None:
        self._conn.write(b)

    def _read_raw(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = self._conn.read(n - len(out))
            if not chunk:
                raise ConnectionError("secret connection closed")
            out += chunk
        return out

    def _nonce(self, counter: int) -> bytes:
        return b"\x00\x00\x00\x00" + struct.pack("<Q", counter)

    # -- frames ---------------------------------------------------------

    def write(self, data: bytes) -> int:
        """Encrypt and send in 1024-byte frames (secret_connection.go:Write)."""
        n = 0
        with self._send_mtx:
            while True:
                chunk, data = data[:DATA_MAX_SIZE], data[DATA_MAX_SIZE:]
                frame = struct.pack("<I", len(chunk)) + chunk
                frame = frame.ljust(TOTAL_FRAME_SIZE, b"\x00")
                sealed = self._send_aead.encrypt(self._nonce(self._send_nonce), frame, None)
                self._send_nonce += 1
                self._write_raw(sealed)
                n += len(chunk)
                if not data:
                    return n

    def read_frame(self) -> bytes:
        with self._recv_mtx:
            sealed = self._read_raw(SEALED_FRAME_SIZE)
            frame = self._recv_aead.decrypt(self._nonce(self._recv_nonce), sealed, None)
            self._recv_nonce += 1
            (length,) = struct.unpack("<I", frame[:DATA_LEN_SIZE])
            if length > DATA_MAX_SIZE:
                raise ValueError("frame length exceeds max")
            return frame[DATA_LEN_SIZE : DATA_LEN_SIZE + length]

    def read(self, n: int) -> bytes:
        """Stream-style read of up to n bytes."""
        if not self._recv_buf:
            self._recv_buf = self.read_frame()
        out, self._recv_buf = self._recv_buf[:n], self._recv_buf[n:]
        return out

    def read_msg(self) -> bytes:
        """One logical frame (used during handshake)."""
        return self.read_frame()

    def close(self) -> None:
        self._conn.close()
