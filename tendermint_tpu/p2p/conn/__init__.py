"""Connection-level primitives: SecretConnection + MConnection."""
