"""MConnection — multiplexed, flow-controlled peer connection.

Reference parity: internal/p2p/conn/connection.go:74 — per-channel send
queues with priorities, packet framing (PacketPing/PacketPong/PacketMsg
with msg chunking + EOF marker), ping/pong keepalive, flush throttling,
sendRoutine/recvRoutine threads (connection.go:334,223).

Packet wire form (proto oneof, conn/connection.go's Packet):
  1 ping{} | 2 pong{} | 3 msg{1 channel_id, 2 eof(bool), 3 data}
framed with a uvarint length prefix.
"""

from __future__ import annotations

import queue
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...wire.proto import (
    ProtoWriter,
    decode_message,
    field_bytes,
    field_int,
    marshal_delimited,
    unmarshal_delimited,
)

MAX_PACKET_MSG_PAYLOAD_SIZE = 1400  # config default
PING_INTERVAL = 10.0
PONG_TIMEOUT = 45.0
FLUSH_THROTTLE = 0.1


@dataclass
class ChannelDescriptor:
    """conn/connection.go ChannelDescriptor / reactor channel specs."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 100
    recv_message_capacity: int = 1024 * 1024


def encode_packet_msg(channel_id: int, eof: bool, data: bytes) -> bytes:
    m = ProtoWriter()
    m.write_varint(1, channel_id)
    m.write_varint(2, 1 if eof else 0)
    m.write_bytes(3, data)
    w = ProtoWriter()
    w.write_message(3, m.bytes(), always=True)
    return marshal_delimited(w.bytes())


def encode_ping() -> bytes:
    w = ProtoWriter()
    w.write_message(1, b"", always=True)
    return marshal_delimited(w.bytes())


def encode_pong() -> bytes:
    w = ProtoWriter()
    w.write_message(2, b"", always=True)
    return marshal_delimited(w.bytes())


class _Channel:
    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        self.send_queue: "queue.Queue[bytes]" = queue.Queue(maxsize=desc.send_queue_capacity)
        self.recving = b""
        self.sending = b""

    def is_send_pending(self) -> bool:
        return bool(self.sending) or not self.send_queue.empty()

    def next_packet_chunk(self) -> Optional[tuple]:
        if not self.sending:
            try:
                self.sending = self.send_queue.get_nowait()
            except queue.Empty:
                return None
        chunk = self.sending[:MAX_PACKET_MSG_PAYLOAD_SIZE]
        self.sending = self.sending[MAX_PACKET_MSG_PAYLOAD_SIZE:]
        eof = not self.sending
        return (self.desc.id, eof, chunk)


class MConnection:
    """connection.go:74-520 (thread-per-direction variant)."""

    def __init__(
        self,
        conn,  # read(n)/write(b)/close()
        channel_descs: List[ChannelDescriptor],
        on_receive: Callable[[int, bytes], None],
        on_error: Callable[[Exception], None],
        send_rate: Optional[float] = None,  # bytes/s; None = unlimited
        recv_rate: Optional[float] = None,
    ):
        from ...libs import flowrate

        self._conn = conn
        self._channels: Dict[int, _Channel] = {
            d.id: _Channel(d) for d in channel_descs
        }
        self._on_receive = on_receive
        self._on_error = on_error
        self._send_signal = threading.Event()
        self._quit = threading.Event()
        self._last_pong = time.time()
        self._threads: List[threading.Thread] = []
        # connection.go:103-104: flowrate monitors + optional rate caps
        self.send_monitor = flowrate.Monitor()
        self.recv_monitor = flowrate.Monitor()
        self._send_limiter = flowrate.Limiter(send_rate) if send_rate else None
        self._recv_limiter = flowrate.Limiter(recv_rate) if recv_rate else None

    def start(self) -> None:
        for fn in (self._send_routine, self._recv_routine):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        if self._quit.is_set():
            return
        self._quit.set()
        self._send_signal.set()
        try:
            self._conn.close()
        except OSError:
            pass

    def is_running(self) -> bool:
        return not self._quit.is_set()

    # -- sending --------------------------------------------------------

    def send(self, channel_id: int, msg: bytes, block: bool = True) -> bool:
        """connection.go Send: enqueue on the channel; False if full."""
        ch = self._channels.get(channel_id)
        if ch is None or self._quit.is_set():
            return False
        try:
            ch.send_queue.put(msg, block=block, timeout=10 if block else None)
        except queue.Full:
            return False
        self._send_signal.set()
        return True

    def try_send(self, channel_id: int, msg: bytes) -> bool:
        return self.send(channel_id, msg, block=False)

    def _send_routine(self) -> None:
        last_ping = time.time()
        try:
            while not self._quit.is_set():
                self._send_signal.wait(timeout=0.05)
                self._send_signal.clear()
                now = time.time()
                if now - last_ping > PING_INTERVAL:
                    self._conn.write(encode_ping())
                    last_ping = now
                # drain by priority: highest priority channel with pending data
                wrote = True
                while wrote and not self._quit.is_set():
                    wrote = False
                    pending = [
                        ch for ch in self._channels.values() if ch.is_send_pending()
                    ]
                    if not pending:
                        break
                    pending.sort(key=lambda c: -c.desc.priority)
                    chunk = pending[0].next_packet_chunk()
                    if chunk is not None:
                        pkt = encode_packet_msg(*chunk)
                        if self._send_limiter is not None:
                            self._send_limiter.wait(len(pkt))
                        self._conn.write(pkt)
                        self.send_monitor.update(len(pkt))
                        wrote = True
        except (OSError, ConnectionError, ValueError) as e:
            self._error(e)

    # -- receiving ------------------------------------------------------

    def _recv_routine(self) -> None:
        buf = b""
        try:
            while not self._quit.is_set():
                chunk = self._conn.read(65536)
                if not chunk:
                    raise ConnectionError("connection closed by peer")
                if self._recv_limiter is not None:
                    self._recv_limiter.wait(len(chunk))
                self.recv_monitor.update(len(chunk))
                buf += chunk
                while True:
                    try:
                        msg, consumed = unmarshal_delimited(buf)
                    except ValueError:
                        break
                    buf = buf[consumed:]
                    self._handle_packet(msg)
        except (OSError, ConnectionError, ValueError) as e:
            self._error(e)

    def _handle_packet(self, msg: bytes) -> None:
        f = decode_message(msg)
        if 1 in f:  # ping
            self._conn.write(encode_pong())
            return
        if 2 in f:  # pong
            self._last_pong = time.time()
            return
        if 3 in f:
            pm = decode_message(f[3][-1][1])
            channel_id = field_int(pm, 1)
            eof = bool(field_int(pm, 2))
            data = field_bytes(pm, 3)
            ch = self._channels.get(channel_id)
            if ch is None:
                raise ValueError(f"unknown channel {channel_id}")
            ch.recving += data
            if len(ch.recving) > ch.desc.recv_message_capacity:
                raise ValueError("recv message exceeds capacity")
            if eof:
                complete, ch.recving = ch.recving, b""
                self._on_receive(channel_id, complete)
            return
        raise ValueError("unknown packet oneof")

    def _error(self, e: Exception) -> None:
        if not self._quit.is_set():
            self.stop()
            self._on_error(e)
