"""UPnP NAT traversal — SSDP discovery + WANIPConnection port mapping.

Reference parity: internal/p2p/upnp/ (upnp.go Discover/AddPortMapping/
DeletePortMapping/GetExternalAddress; probe.go Probe/Capabilities). The
protocol: an SSDP M-SEARCH multicast finds the gateway's description URL,
the description XML names the WANIPConnection control endpoint, and SOAP
POSTs drive the IGD actions.

Discovery and HTTP endpoints are injectable (ssdp_addr / socket factory)
so the full flow is testable against an in-process fake gateway — the
probe in this environment has no real multicast route.
"""

from __future__ import annotations

import re
import socket
import xml.sax.saxutils
import urllib.request
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional, Tuple
from urllib.parse import urljoin, urlparse

SSDP_ADDR = ("239.255.255.250", 1900)
WAN_SERVICE_RE = re.compile(
    r"urn:(?P<domain>[\w.-]+):service:WANIPConnection:1"
)


class UPnPError(Exception):
    pass


def _msearch_message() -> bytes:
    # upnp.go:58-64
    return (
        "M-SEARCH * HTTP/1.1\r\n"
        "HOST: 239.255.255.250:1900\r\n"
        "ST: ssdp:all\r\n"
        'MAN: "ssdp:discover"\r\n'
        "MX: 2\r\n\r\n"
    ).encode()


def parse_ssdp_response(data: bytes) -> Optional[str]:
    """Location URL from an SSDP response advertising an
    InternetGatewayDevice (upnp.go:74-112)."""
    text = data.decode("utf-8", "replace")
    if "InternetGatewayDevice" not in text:
        return None
    for line in text.split("\r\n"):
        name, _, value = line.partition(":")
        if name.strip().lower() == "location":
            return value.strip()
    return None


def discover(
    timeout: float = 3.0, ssdp_addr: Optional[Tuple[str, int]] = None, attempts: int = 3
) -> "UPnPNAT":
    """upnp.go:39 Discover: multicast M-SEARCH, follow the gateway's
    Location to its description XML, resolve the WANIPConnection control
    URL."""
    if ssdp_addr is None:
        ssdp_addr = SSDP_ADDR  # read at call time (tests repoint it)
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout / attempts)
    try:
        for _ in range(attempts):
            sock.sendto(_msearch_message(), ssdp_addr)
            try:
                data, _ = sock.recvfrom(4096)
            except socket.timeout:
                continue
            loc = parse_ssdp_response(data)
            if loc is None:
                continue
            control_url, domain = get_service_url(loc)
            local_ip = _local_ip_for(loc)
            return UPnPNAT(control_url=control_url, urn_domain=domain, local_ip=local_ip)
        raise UPnPError("UPnP port discovery failed")
    finally:
        sock.close()


def _local_ip_for(root_url: str) -> str:
    """The local interface address routing to the gateway
    (upnp.go:179 localIPv4)."""
    host = urlparse(root_url).hostname or "127.0.0.1"
    port = urlparse(root_url).port or 80
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((host, port))
        return s.getsockname()[0]
    finally:
        s.close()


def get_service_url(root_url: str) -> Tuple[str, str]:
    """Fetch the device description and return (control URL, urn domain)
    for WANIPConnection:1 (upnp.go:204-258)."""
    with urllib.request.urlopen(root_url, timeout=5) as resp:
        tree = ET.parse(resp)
    # namespace-agnostic walk (gateways vary)
    def local(tag: str) -> str:
        return tag.rsplit("}", 1)[-1]

    for service in tree.iter():
        if local(service.tag) != "service":
            continue
        st = ctl = None
        for child in service:
            if local(child.tag) == "serviceType":
                st = (child.text or "").strip()
            elif local(child.tag) == "controlURL":
                ctl = (child.text or "").strip()
        if st and ctl:
            m = WAN_SERVICE_RE.fullmatch(st)
            if m:
                return urljoin(root_url, ctl), m.group("domain")
    raise UPnPError("no WANIPConnection service in device description")


def _soap_request(url: str, function: str, body: str, domain: str) -> bytes:
    """upnp.go:260 soapRequest."""
    envelope = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        "<s:Body>" + body + "</s:Body></s:Envelope>"
    )
    req = urllib.request.Request(
        url,
        data=envelope.encode(),
        headers={
            "Content-Type": "text/xml; charset=\"utf-8\"",
            "SOAPAction": f'"urn:{domain}:service:WANIPConnection:1#{function}"',
            "Connection": "Close",
        },
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        raise UPnPError(f"SOAP {function} failed: HTTP {e.code}") from e


@dataclass
class UPnPNAT:
    """upnp.go upnpNAT (the NAT interface implementation)."""

    control_url: str
    urn_domain: str
    local_ip: str

    def get_external_address(self) -> str:
        """upnp.go:301,336 GetExternalAddress."""
        body = (
            f'<u:GetExternalIPAddress xmlns:u="urn:{self.urn_domain}:'
            'service:WANIPConnection:1"/>'
        )
        resp = _soap_request(
            self.control_url, "GetExternalIPAddress", body, self.urn_domain
        )
        m = re.search(
            rb"<NewExternalIPAddress>\s*([^<\s]+)\s*</NewExternalIPAddress>", resp
        )
        if not m:
            raise UPnPError("gateway returned no external IP")
        return m.group(1).decode()

    def add_port_mapping(
        self,
        protocol: str,
        external_port: int,
        internal_port: int,
        description: str,
        lease_duration_s: int = 0,
    ) -> int:
        """upnp.go:348 AddPortMapping; returns the mapped external port."""
        body = (
            f'<u:AddPortMapping xmlns:u="urn:{self.urn_domain}:service:WANIPConnection:1">'
            "<NewRemoteHost></NewRemoteHost>"
            f"<NewExternalPort>{external_port}</NewExternalPort>"
            f"<NewProtocol>{protocol.upper()}</NewProtocol>"
            f"<NewInternalPort>{internal_port}</NewInternalPort>"
            f"<NewInternalClient>{self.local_ip}</NewInternalClient>"
            "<NewEnabled>1</NewEnabled>"
            "<NewPortMappingDescription>"
            + xml.sax.saxutils.escape(description)
            + "</NewPortMappingDescription>"
            f"<NewLeaseDuration>{lease_duration_s}</NewLeaseDuration>"
            "</u:AddPortMapping>"
        )
        _soap_request(self.control_url, "AddPortMapping", body, self.urn_domain)
        return external_port

    def delete_port_mapping(self, protocol: str, external_port: int) -> None:
        """upnp.go:384 DeletePortMapping."""
        body = (
            f'<u:DeletePortMapping xmlns:u="urn:{self.urn_domain}:service:WANIPConnection:1">'
            "<NewRemoteHost></NewRemoteHost>"
            f"<NewExternalPort>{external_port}</NewExternalPort>"
            f"<NewProtocol>{protocol.upper()}</NewProtocol>"
            "</u:DeletePortMapping>"
        )
        _soap_request(self.control_url, "DeletePortMapping", body, self.urn_domain)


@dataclass
class Capabilities:
    """probe.go Capabilities."""

    port_mapping: bool = False
    hairpin: bool = False


def probe(
    int_port: int = 8001,
    ext_port: int = 8001,
    timeout: float = 3.0,
    ssdp_addr: Optional[Tuple[str, int]] = None,
) -> Capabilities:
    """probe.go:84 Probe: discover the gateway, map a port, check the
    external address, then clean up. Hairpin (dialing your own external
    address) is reported false unless the loopback dial succeeds."""
    caps = Capabilities()
    nat = discover(timeout=timeout, ssdp_addr=ssdp_addr)
    ext_ip = nat.get_external_address()
    nat.add_port_mapping("tcp", ext_port, int_port, "tendermint-probe", 0)
    caps.port_mapping = True
    # hairpin test needs a real local listener on int_port for the
    # gateway to forward back to (probe.go:16 makeUPNPListener dials
    # only after listening)
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("", int_port))
        listener.listen(1)
        s = socket.create_connection((ext_ip, ext_port), timeout=1)
        s.close()
        caps.hairpin = True
    except OSError:
        pass
    finally:
        listener.close()
        try:
            nat.delete_port_mapping("tcp", ext_port)
        except UPnPError:
            pass
    return caps
