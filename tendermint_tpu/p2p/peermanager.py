"""PeerManager — peer lifecycle: dial, connect, evict, retry, score.

Reference parity: internal/p2p/peermanager.go:27-60 — the state machine
for candidate/connected/evicting peers, persistent peers with unconditional
retries, exponential dial backoff, upgrade/eviction when above capacity,
and a persisted address book.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..db import DB, MemDB

MAX_PEER_SCORE = 100
PERSISTENT_PEER_SCORE = MAX_PEER_SCORE

# Score at or below which a connected peer is scheduled for eviction and a
# candidate stops being dialed (peermanager.go's negative-score behavior).
EVICT_SCORE = -10
# Cap on stored (unconnected) addresses before the book GCs the worst ones.
DEFAULT_MAX_PEERS = 1000


@dataclass
class PeerAddress:
    node_id: str
    address: str  # "host:port" (or memory node id)


@dataclass
class _PeerInfo:
    node_id: str
    addresses: List[str] = field(default_factory=list)
    persistent: bool = False
    last_dial_failure: float = 0.0
    dial_failures: int = 0
    mutable_score: int = 0
    banned_until: float = 0.0

    def score(self) -> int:
        if self.persistent:
            return PERSISTENT_PEER_SCORE
        return max(min(self.mutable_score, MAX_PEER_SCORE - 1), -100)


class PeerManager:
    """peermanager.go:229-1404 (condensed state machine)."""

    def __init__(
        self,
        self_id: str,
        db: Optional[DB] = None,
        max_connected: int = 16,
        min_retry_time: float = 0.25,
        max_retry_time: float = 30.0,
        max_peers: int = DEFAULT_MAX_PEERS,
        ban_duration: float = 60.0,
    ):
        self._self_id = self_id
        self._db = db or MemDB()
        self._max_connected = max_connected
        self._min_retry = min_retry_time
        self._max_retry = max_retry_time
        self._max_peers = max_peers
        self._ban_duration = ban_duration
        self._mtx = threading.RLock()
        self._peers: Dict[str, _PeerInfo] = {}
        self._connected: Set[str] = set()
        self._dialing: Set[str] = set()
        self._evicting: Set[str] = set()
        self._evict_queue: List[str] = []
        self._load()

    # -- address book ----------------------------------------------------

    def add_address(self, addr: PeerAddress, persistent: bool = False) -> bool:
        """peermanager.go Add: returns True if new."""
        if addr.node_id == self._self_id:
            return False
        with self._mtx:
            info = self._peers.get(addr.node_id)
            is_new = info is None
            if info is None:
                info = _PeerInfo(node_id=addr.node_id)
                self._peers[addr.node_id] = info
            if addr.address and addr.address not in info.addresses:
                info.addresses.append(addr.address)
            if persistent:
                info.persistent = True
            self._save(info)
            return is_new

    def addresses(self, node_id: str) -> List[str]:
        with self._mtx:
            info = self._peers.get(node_id)
            return list(info.addresses) if info else []

    def peers(self) -> List[str]:
        with self._mtx:
            return list(self._peers)

    def connected_peers(self) -> List[str]:
        with self._mtx:
            return list(self._connected)

    def num_connected(self) -> int:
        with self._mtx:
            return len(self._connected)

    # -- dialing state machine -------------------------------------------

    def dial_next(self) -> Optional[PeerAddress]:
        """peermanager.go DialNext: best candidate ready for dialing."""
        with self._mtx:
            if len(self._connected) + len(self._dialing) >= self._max_connected:
                return None
            now = time.time()
            candidates = []
            for info in self._peers.values():
                if info.node_id in self._connected or info.node_id in self._dialing:
                    continue
                if not info.addresses:
                    continue
                if now < info.banned_until:
                    continue
                if info.dial_failures > 0:
                    backoff = min(
                        self._min_retry * (2 ** (info.dial_failures - 1)), self._max_retry
                    )
                    if not info.persistent and info.dial_failures > 8:
                        continue  # give up on non-persistent peers
                    if now - info.last_dial_failure < backoff:
                        continue
                candidates.append(info)
            if not candidates:
                return None
            candidates.sort(key=lambda i: -i.score())
            best = candidates[0]
            self._dialing.add(best.node_id)
            return PeerAddress(best.node_id, random.choice(best.addresses))

    def dial_failed(self, node_id: str) -> None:
        with self._mtx:
            self._dialing.discard(node_id)
            info = self._peers.get(node_id)
            if info:
                info.dial_failures += 1
                info.last_dial_failure = time.time()

    def _admit_locked(self, node_id: str) -> bool:
        """Shared admission: dedup/self/ban checks, then capacity with the
        upgrade rule (peermanager.go upgrade machinery): a candidate that
        outscores the worst connected non-persistent peer displaces it —
        the loser is queued for eviction and the candidate admitted.
        The address-book entry is only created AFTER admission — rejected
        connection attempts (capacity, bans) must not grow the book."""
        if node_id in self._connected or node_id == self._self_id:
            return False
        info = self._peers.get(node_id) or _PeerInfo(node_id=node_id)
        if time.time() < info.banned_until:
            return False
        if len(self._connected) >= self._max_connected:
            evictable = [
                self._peers[n]
                for n in self._connected
                if n not in self._evicting
                and n not in self._evict_queue
                and not self._peers[n].persistent
            ]
            if not evictable:
                return False
            worst = min(evictable, key=lambda i: i.score())
            if worst.score() >= info.score():
                return False
            self._schedule_evict_locked(worst.node_id)
        self._peers.setdefault(node_id, info)
        self._connected.add(node_id)
        return True

    def dialed(self, node_id: str) -> bool:
        """Outbound connect succeeded; False -> reject (e.g. full/dup)."""
        with self._mtx:
            self._dialing.discard(node_id)
            if not self._admit_locked(node_id):
                return False
            self._peers[node_id].dial_failures = 0
            return True

    def accepted(self, node_id: str) -> bool:
        """Inbound connect; same admission rules (peermanager.go Accepted)."""
        with self._mtx:
            return self._admit_locked(node_id)

    def disconnected(self, node_id: str) -> None:
        with self._mtx:
            self._connected.discard(node_id)
            self._evicting.discard(node_id)
            if node_id in self._evict_queue:
                self._evict_queue.remove(node_id)

    def errored(self, node_id: str, err: Exception, weight: int = 1) -> None:
        """peermanager.go Errored: demote the peer's score; once it sinks
        to EVICT_SCORE the peer is queued for eviction and (non-persistent
        peers) banned from redial for ban_duration."""
        with self._mtx:
            info = self._peers.get(node_id)
            if info is None:
                return
            info.mutable_score -= weight
            if info.score() <= EVICT_SCORE and not info.persistent:
                info.banned_until = time.time() + self._ban_duration
                self._schedule_evict_locked(node_id)

    # -- eviction (peermanager.go EvictNext/evict state) ------------------

    def _schedule_evict_locked(self, node_id: str) -> None:
        if (
            node_id in self._connected
            and node_id not in self._evicting
            and node_id not in self._evict_queue
        ):
            self._evict_queue.append(node_id)

    def schedule_evict(self, node_id: str) -> None:
        with self._mtx:
            self._schedule_evict_locked(node_id)

    def evict_next(self) -> Optional[str]:
        """peermanager.go EvictNext: pop a peer the router must drop.
        Non-blocking; the router pumps this in its eviction loop."""
        with self._mtx:
            # over capacity -> evict the lowest-scoring non-persistent peer
            if len(self._connected) > self._max_connected:
                excess = [
                    self._peers[n]
                    for n in self._connected
                    if n not in self._evicting and not self._peers[n].persistent
                ]
                if excess:
                    worst = min(excess, key=lambda i: i.score())
                    self._schedule_evict_locked(worst.node_id)
            while self._evict_queue:
                nid = self._evict_queue.pop(0)
                if nid in self._connected and nid not in self._evicting:
                    self._evicting.add(nid)
                    return nid
            return None

    def evict_failed(self, node_id: str) -> None:
        """The router had no live connection for a popped eviction (admit
        race: accepted() marks connected before the router registers the
        conn). Clear the in-flight mark and re-queue so the eviction is
        retried once the connection lands — otherwise the peer would stay
        in _evicting forever and become immune to eviction."""
        with self._mtx:
            self._evicting.discard(node_id)
            self._schedule_evict_locked(node_id)

    def is_banned(self, node_id: str) -> bool:
        with self._mtx:
            info = self._peers.get(node_id)
            return bool(info and time.time() < info.banned_until)

    # -- address book GC --------------------------------------------------

    def prune_addresses(self) -> int:
        """peermanager.go prunePeers: when the book exceeds max_peers,
        drop the lowest-scored unconnected, non-persistent entries."""
        with self._mtx:
            overflow = len(self._peers) - self._max_peers
            if overflow <= 0:
                return 0
            candidates = [
                i
                for i in self._peers.values()
                if i.node_id not in self._connected
                and i.node_id not in self._dialing
                and not i.persistent
            ]
            candidates.sort(key=lambda i: i.score())
            dropped = 0
            for info in candidates[:overflow]:
                del self._peers[info.node_id]
                self._db.delete(b"peer:" + info.node_id.encode())
                dropped += 1
            return dropped

    # -- persistence -----------------------------------------------------

    def _save(self, info: _PeerInfo) -> None:
        import json

        self._db.set(
            b"peer:" + info.node_id.encode(),
            json.dumps(
                {"addresses": info.addresses, "persistent": info.persistent}
            ).encode(),
        )

    def _load(self) -> None:
        import json

        for k, v in self._db.iterator(b"peer:", b"peer;"):
            node_id = k[len(b"peer:") :].decode()
            obj = json.loads(v)
            self._peers[node_id] = _PeerInfo(
                node_id=node_id,
                addresses=obj.get("addresses", []),
                persistent=obj.get("persistent", False),
            )
