"""Priority mempool.

Reference parity: internal/mempool/ — TxMempool (mempool.go:31): CheckTx
via ABCI with priority/sender from the response, priority ordering for
block building (ReapMaxBytesMaxGas, mempool.go:344), FIFO order for
gossip, LRU cache of seen txs (cache.go), post-commit Update with recheck
(mempool.go:430).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..abci import types as abci
from ..types.tx import tx_key
from . import ingress as _ingress

# CheckTx rejection codes for the signature stage (codespace "ingress")
CODE_BAD_SIGNATURE = 101
CODE_BAD_NONCE = 102


class TxCache:
    """LRU cache of tx keys (internal/mempool/cache.go)."""

    def __init__(self, size: int):
        self._size = size
        self._map: "OrderedDict[bytes, None]" = OrderedDict()
        self._mtx = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present (mempool has seen it)."""
        k = tx_key(tx)
        with self._mtx:
            if k in self._map:
                self._map.move_to_end(k)
                return False
            self._map[k] = None
            if self._size and len(self._map) > self._size:
                self._map.popitem(last=False)
            return True

    def remove(self, tx: bytes) -> None:
        with self._mtx:
            self._map.pop(tx_key(tx), None)

    def has(self, tx: bytes) -> bool:
        with self._mtx:
            return tx_key(tx) in self._map

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()


@dataclass(order=True)
class _WrappedTx:
    sort_key: tuple = field(compare=True)
    tx: bytes = field(compare=False, default=b"")
    key: bytes = field(compare=False, default=b"")
    priority: int = field(compare=False, default=0)
    sender: str = field(compare=False, default="")
    gas_wanted: int = field(compare=False, default=0)
    height: int = field(compare=False, default=0)
    timestamp: float = field(compare=False, default=0.0)
    seq: int = field(compare=False, default=0)
    removed: bool = field(compare=False, default=False)


class TxMempool:
    """internal/mempool/mempool.go:31-520 (synchronous variant: CheckTx
    calls the ABCI mempool connection inline; the reactor broadcasts from
    the FIFO list)."""

    def __init__(
        self,
        proxy_app,  # mempool-connection ABCI client
        config=None,
        height: int = 0,
        ingress=None,  # mempool/ingress.py IngressAccumulator (opt-in)
    ):
        from ..config import MempoolConfig

        self._cfg = config or MempoolConfig()
        self._proxy = proxy_app
        self._height = height
        self._mtx = threading.RLock()
        self._cache = TxCache(self._cfg.cache_size)
        self._tx_by_key: Dict[bytes, _WrappedTx] = {}
        self._fifo: List[_WrappedTx] = []  # gossip & FIFO order
        self._seq = itertools.count()
        self._size_bytes = 0
        self._pre_check: Optional[Callable] = None
        self._post_check: Optional[Callable] = None
        self._notify_available: Optional[Callable] = None
        # libs.metrics.MempoolMetrics, attached by node setup when the
        # instrumentation config enables prometheus (None = no-op)
        self.metrics = None
        # device-batched ingress (ISSUE 13): when attached, signed-tx
        # CheckTx signature verdicts come from the accumulator's batched
        # device windows; without one they verify inline on the host —
        # the sequential baseline, same code path minus the batching
        self._ingress = ingress
        # per-sender replay protection: pubkey -> highest accepted nonce
        self._nonces: Dict[bytes, int] = {}

    # -- config hooks ---------------------------------------------------

    def set_pre_check(self, fn: Callable) -> None:
        self._pre_check = fn

    def set_post_check(self, fn: Callable) -> None:
        self._post_check = fn

    def set_notify_available(self, fn: Callable) -> None:
        """Called once when the mempool transitions empty -> non-empty
        (consensus's txsAvailable channel)."""
        self._notify_available = fn

    # -- core -----------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return len(self._tx_by_key)

    def size_bytes(self) -> int:
        with self._mtx:
            return self._size_bytes

    def is_empty(self) -> bool:
        return self.size() == 0

    def attach_ingress(self, accumulator) -> None:
        """Attach a mempool/ingress.py IngressAccumulator: signed-tx
        CheckTx signature verdicts now come from batched device windows
        instead of inline host verification."""
        self._ingress = accumulator

    def check_tx(self, tx: bytes, callback: Optional[Callable] = None, sender: str = "") -> abci.ResponseCheckTx:
        """mempool.go:230-342 — sync facade over check_tx_async: blocks
        until the signature verdict (if any) and the app CheckTx land."""
        return self.check_tx_async(tx, callback, sender).result(timeout=300)

    def check_tx_async(
        self, tx: bytes, callback: Optional[Callable] = None,
        sender: str = "",
    ) -> "Future[abci.ResponseCheckTx]":
        """CheckTx with a device-batched signature stage (ISSUE 13).

        Prechecks (size, pre_check hook, envelope structure, seen-cache)
        raise synchronously exactly as check_tx always has. The returned
        future resolves to the ResponseCheckTx; it raises
        MempoolFullError (the sync path's raise, deferred) or the
        DispatchError of a poisoned device window (infrastructure
        failure — the tx is dropped from the seen-cache so a retry can
        resubmit it).

        Unsigned (legacy) txs and signed txs without an accumulator
        complete INLINE on the calling thread — byte-identical responses
        to the pre-ISSUE-13 code. Signed txs with an accumulator complete
        on its completer thread once the batched verdict lands; the
        mempool lock is never held across the device wait."""
        if len(tx) > self._cfg.max_tx_bytes:
            raise ValueError(
                f"tx size {len(tx)} exceeds max {self._cfg.max_tx_bytes}"
            )
        if self._pre_check is not None:
            self._pre_check(tx)
        stx = _ingress.parse_signed_tx(tx)  # MalformedTxError on bad envelope
        if not self._cache.push(tx):
            # seen before: reject as duplicate (mempool.go:270-287)
            raise DuplicateTxError(tx_key(tx))
        fut: "Future[abci.ResponseCheckTx]" = Future()
        if stx is None:
            self._finish_check_tx(tx, None, True, sender, callback, fut)
        elif self._ingress is None:
            # sequential baseline: same completion path, host verdict
            self._finish_check_tx(
                tx, stx, _ingress.host_verify(stx), sender, callback, fut
            )
        else:
            vfut = self._ingress.submit(stx)

            def _on_verdict(f, tx=tx, stx=stx):
                # runs on the ingress COMPLETER thread (never the
                # pipeline resolver — see mempool/ingress.py)
                try:
                    ok = bool(f.result())
                except Exception as e:  # noqa: BLE001 — poisoned window
                    # device-infrastructure failure, not a parity
                    # rejection: drop the seen-cache entry so the tx is
                    # retryable, and surface the DispatchError
                    self._cache.remove(tx)
                    if not fut.done():
                        fut.set_exception(e)
                    return
                self._finish_check_tx(tx, stx, ok, sender, callback, fut)

            vfut.add_done_callback(_on_verdict)
        return fut

    def _finish_check_tx(self, tx: bytes, stx, sig_ok: bool, sender: str,
                         callback: Optional[Callable], fut: Future) -> None:
        """Complete CheckTx from the signature verdict. Takes the mempool
        lock only around state mutation — no device or future waits
        inside it (the lock-discipline shape tmlint now flags)."""
        try:
            res = self._check_tx_verdict(tx, stx, sig_ok, sender)
        except BaseException as e:  # noqa: BLE001 — incl. MempoolFullError
            if not fut.done():
                fut.set_exception(e)
            return
        try:
            if callback is not None:
                callback(res)
        except BaseException as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)
            return
        if not fut.done():
            fut.set_result(res)

    def _sig_reject(self, tx: bytes, code: int, log: str) -> abci.ResponseCheckTx:
        if self.metrics is not None:
            self.metrics.failed_txs.inc()
        if not self._cfg.keep_invalid_txs_in_cache:
            self._cache.remove(tx)
        return abci.ResponseCheckTx(code=code, log=log, codespace="ingress")

    def _check_tx_verdict(self, tx: bytes, stx, sig_ok: bool,
                          sender: str) -> abci.ResponseCheckTx:
        if stx is not None:
            if not sig_ok:
                return self._sig_reject(
                    tx, CODE_BAD_SIGNATURE, "invalid signature"
                )
            with self._mtx:
                last = self._nonces.get(stx.pub)
            if last is not None and stx.nonce <= last:
                return self._sig_reject(
                    tx, CODE_BAD_NONCE,
                    f"nonce {stx.nonce} <= {last}: replay or out of order",
                )
        res = self._proxy.check_tx(abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        if res.is_ok():
            stale = None
            with self._mtx:
                if stx is not None:
                    # authoritative nonce check: the unlocked fast-path
                    # read above races concurrent same-sender txs; this
                    # one is serialized with the record below
                    prev = self._nonces.get(stx.pub)
                    if prev is not None and stx.nonce <= prev:
                        stale = prev
                if stale is None:
                    if len(self._tx_by_key) >= self._cfg.size or (
                        self._size_bytes + len(tx) > self._cfg.max_txs_bytes
                    ):
                        # full: evict strictly-lower-priority txs to make room
                        # (mempool.go:498 + priority_queue.go GetEvictableTxs);
                        # reject when no such set frees enough capacity
                        victims = self._evictable_locked(res.priority, len(tx))
                        if not victims:
                            self._cache.remove(tx)
                            raise MempoolFullError(len(self._tx_by_key))
                        for v in victims:
                            self._remove_tx(v.key, compact=False)
                            self._cache.remove(v.tx)
                        self._compact_fifo()
                        if self.metrics is not None:
                            self.metrics.evicted_txs.inc(len(victims))
                    was_empty = not self._tx_by_key
                    wtx = _WrappedTx(
                        sort_key=(-res.priority, next(self._seq)),
                        tx=tx,
                        key=tx_key(tx),
                        priority=res.priority,
                        sender=res.sender or sender,
                        gas_wanted=res.gas_wanted,
                        height=self._height,
                        timestamp=time.time(),
                    )
                    self._tx_by_key[wtx.key] = wtx
                    self._fifo.append(wtx)
                    self._size_bytes += len(tx)
                    if stx is not None:
                        self._nonces[stx.pub] = stx.nonce
            if stale is not None:
                return self._sig_reject(
                    tx, CODE_BAD_NONCE,
                    f"nonce {stx.nonce} <= {stale}: replay or out of order",
                )
            if was_empty and self._notify_available is not None:
                self._notify_available()
            if self.metrics is not None:
                self.metrics.tx_size_bytes.observe(len(tx))
        else:
            if self.metrics is not None:
                self.metrics.failed_txs.inc()
            if not self._cfg.keep_invalid_txs_in_cache:
                self._cache.remove(tx)
        return res

    def _evictable_locked(self, priority: int, tx_size: int) -> List[_WrappedTx]:
        """priority_queue.go:34 GetEvictableTxs: ascending-priority txs
        strictly below `priority`, taken until the new tx fits both the
        byte and count budgets; empty when impossible."""
        candidates = sorted(
            self._tx_by_key.values(), key=lambda w: (w.priority, -w.seq)
        )
        victims: List[_WrappedTx] = []
        bytes_after = self._size_bytes
        count_after = len(self._tx_by_key)
        for w in candidates:
            if w.priority >= priority:
                break
            victims.append(w)
            bytes_after -= len(w.tx)
            count_after -= 1
            if (
                bytes_after + tx_size <= self._cfg.max_txs_bytes
                and count_after < self._cfg.size
            ):
                return victims
        return []

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """mempool.go:344-402: highest priority first, FIFO within equal
        priority, respecting byte/gas budgets."""
        with self._mtx:
            ordered = sorted(self._tx_by_key.values())
            out: List[bytes] = []
            total_bytes = 0
            total_gas = 0
            for wtx in ordered:
                sz = len(wtx.tx) + 6  # framing overhead like ComputeProtoSizeForTxs
                if max_bytes > -1 and total_bytes + sz > max_bytes:
                    break
                if max_gas > -1 and total_gas + wtx.gas_wanted > max_gas:
                    break
                total_bytes += sz
                total_gas += wtx.gas_wanted
                out.append(wtx.tx)
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._mtx:
            ordered = sorted(self._tx_by_key.values())
            if n < 0:
                n = len(ordered)
            return [w.tx for w in ordered[:n]]

    def txs_fifo(self) -> List[bytes]:
        """Gossip order (the clist walk in the reference's reactor)."""
        with self._mtx:
            return [w.tx for w in self._fifo if not w.removed]

    # -- consensus integration ------------------------------------------

    def lock(self) -> None:
        # cross-method Lock/Unlock API mirroring the reference's
        # Mempool.Lock (consensus holds it across ReapMaxBytes + Update);
        # a with-block cannot span the two calls
        self._mtx.acquire()  # tmlint: disable=lock-discipline — reference API shape

    def unlock(self) -> None:
        self._mtx.release()

    def flush_app_conn(self) -> None:
        if hasattr(self._proxy, "flush"):
            self._proxy.flush()

    def update(
        self,
        height: int,
        txs: List[bytes],
        deliver_tx_responses: List[abci.ResponseDeliverTx],
        pre_check: Optional[Callable] = None,
        post_check: Optional[Callable] = None,
    ) -> None:
        """mempool.go:430-500. Caller must hold the lock."""
        self._height = height
        if pre_check is not None:
            self._pre_check = pre_check
        if post_check is not None:
            self._post_check = post_check
        for tx, res in zip(txs, deliver_tx_responses):
            if res.is_ok():
                self._cache.push(tx)  # committed: keep in cache forever-ish
            elif not self._cfg.keep_invalid_txs_in_cache:
                self._cache.remove(tx)
            self._remove_tx(tx_key(tx), compact=False)
        self._compact_fifo()
        self._purge_expired_txs()
        if self._cfg.recheck and self._tx_by_key:
            self._recheck_txs()

    def _purge_expired_txs(self) -> None:
        """mempool.go:806-850 purgeExpiredTxs: drop txs past the
        height-based (ttl_num_blocks) or time-based (ttl_duration_ms)
        TTL. No-op when both are 0."""
        ttl_blocks = self._cfg.ttl_num_blocks
        ttl_s = self._cfg.ttl_duration_ms / 1000.0
        if ttl_blocks <= 0 and ttl_s <= 0:
            return
        now = time.time()
        for wtx in list(self._tx_by_key.values()):
            if ttl_blocks > 0 and self._height - wtx.height > ttl_blocks:
                self._remove_tx(wtx.key, compact=False)
                self._cache.remove(wtx.tx)
            elif ttl_s > 0 and now - wtx.timestamp > ttl_s:
                self._remove_tx(wtx.key, compact=False)
                self._cache.remove(wtx.tx)
        self._compact_fifo()

    def _remove_tx(self, key: bytes, compact: bool = True) -> None:
        wtx = self._tx_by_key.pop(key, None)
        if wtx is not None:
            wtx.removed = True
            self._size_bytes -= len(wtx.tx)
        if compact:
            self._compact_fifo()

    def _compact_fifo(self) -> None:
        self._fifo = [w for w in self._fifo if not w.removed]

    def _recheck_txs(self) -> None:
        """mempool.go:580-620: re-CheckTx all remaining txs.

        ISSUE 13: signed txs re-verify their signatures first — as ONE
        block-sized device batch through the ingress accumulator when one
        is attached, per-tx on the host otherwise — then the survivors
        re-run app CheckTx exactly as before. The caller holds the
        mempool lock; the device wait below is on a raw PIPELINE future
        (the resolver thread never takes this lock), NOT on a per-tx
        ingress future (those resolve on the completer thread, which
        does — waiting on one here would deadlock the process)."""
        if self.metrics is not None:
            self.metrics.recheck_times.inc(len(self._tx_by_key))
        sig_bad: set = set()
        signed: List = []
        for wtx in self._tx_by_key.values():
            try:
                stx = _ingress.parse_signed_tx(wtx.tx)
            except ValueError:
                stx = None  # unreachable past check_tx, but never fatal
            if stx is not None:
                signed.append((wtx, stx))
        dev = [p for p in signed
               if p[1].scheme == _ingress.SCHEME_ED25519]
        host = [p for p in signed
                if p[1].scheme != _ingress.SCHEME_ED25519]
        if dev and self._ingress is not None:
            from ..ops.entry_block import EntryBlock

            block = EntryBlock.from_entries(
                [(s.pub, s.signed_bytes(), s.sig) for _, s in dev]
            )
            try:
                verdicts = self._ingress.submit_block(block).result(
                    timeout=300
                )
                for (wtx, _), ok in zip(dev, verdicts):
                    if not ok:
                        sig_bad.add(wtx.key)
            except Exception:  # noqa: BLE001 — infra failure, not verdicts
                # keep the txs; they recheck again after the next commit
                pass
        else:
            for wtx, s in dev:
                if not _ingress.host_verify(s):
                    sig_bad.add(wtx.key)
        for wtx, s in host:
            if not _ingress.host_verify(s):
                sig_bad.add(wtx.key)
        for wtx in list(self._tx_by_key.values()):
            ok = wtx.key not in sig_bad
            if ok:
                res = self._proxy.check_tx(
                    abci.RequestCheckTx(tx=wtx.tx, type=abci.CHECK_TX_TYPE_RECHECK)
                )
                ok = res.is_ok()
                if ok and self._post_check is not None:
                    try:
                        self._post_check(wtx.tx, res)
                    except ValueError:
                        ok = False
            if not ok:
                self._remove_tx(wtx.key, compact=False)
                if not self._cfg.keep_invalid_txs_in_cache:
                    self._cache.remove(wtx.tx)
        self._compact_fifo()

    def remove_tx_by_key(self, key: bytes) -> bool:
        """mempool.go RemoveTxByKey (public API used by the remove_tx
        RPC): drop a tx by key; False if absent."""
        with self._mtx:
            if key not in self._tx_by_key:
                return False
            self._remove_tx(key)
            return True

    def flush(self) -> None:
        with self._mtx:
            self._tx_by_key.clear()
            self._fifo.clear()
            self._size_bytes = 0
            self._cache.reset()
            self._nonces.clear()

    def ingress_stats(self) -> dict:
        """The attached accumulator's snapshot (rpc /status); a mempool
        without one reports {"enabled": False}."""
        if self._ingress is None:
            return {"enabled": False}
        return dict(self._ingress.stats(), enabled=True)


class DuplicateTxError(ValueError):
    def __init__(self, key: bytes):
        super().__init__(f"tx already exists in cache: {key.hex()}")
        self.key = key


class MempoolFullError(RuntimeError):
    def __init__(self, size: int):
        super().__init__(f"mempool is full: {size} txs")
