"""Priority mempool.

Reference parity: internal/mempool/ — TxMempool (mempool.go:31): CheckTx
via ABCI with priority/sender from the response, priority ordering for
block building (ReapMaxBytesMaxGas, mempool.go:344), FIFO order for
gossip, LRU cache of seen txs (cache.go), post-commit Update with recheck
(mempool.go:430).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..abci import types as abci
from ..types.tx import tx_key


class TxCache:
    """LRU cache of tx keys (internal/mempool/cache.go)."""

    def __init__(self, size: int):
        self._size = size
        self._map: "OrderedDict[bytes, None]" = OrderedDict()
        self._mtx = threading.Lock()

    def push(self, tx: bytes) -> bool:
        """False if already present (mempool has seen it)."""
        k = tx_key(tx)
        with self._mtx:
            if k in self._map:
                self._map.move_to_end(k)
                return False
            self._map[k] = None
            if self._size and len(self._map) > self._size:
                self._map.popitem(last=False)
            return True

    def remove(self, tx: bytes) -> None:
        with self._mtx:
            self._map.pop(tx_key(tx), None)

    def has(self, tx: bytes) -> bool:
        with self._mtx:
            return tx_key(tx) in self._map

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()


@dataclass(order=True)
class _WrappedTx:
    sort_key: tuple = field(compare=True)
    tx: bytes = field(compare=False, default=b"")
    key: bytes = field(compare=False, default=b"")
    priority: int = field(compare=False, default=0)
    sender: str = field(compare=False, default="")
    gas_wanted: int = field(compare=False, default=0)
    height: int = field(compare=False, default=0)
    timestamp: float = field(compare=False, default=0.0)
    seq: int = field(compare=False, default=0)
    removed: bool = field(compare=False, default=False)


class TxMempool:
    """internal/mempool/mempool.go:31-520 (synchronous variant: CheckTx
    calls the ABCI mempool connection inline; the reactor broadcasts from
    the FIFO list)."""

    def __init__(
        self,
        proxy_app,  # mempool-connection ABCI client
        config=None,
        height: int = 0,
    ):
        from ..config import MempoolConfig

        self._cfg = config or MempoolConfig()
        self._proxy = proxy_app
        self._height = height
        self._mtx = threading.RLock()
        self._cache = TxCache(self._cfg.cache_size)
        self._tx_by_key: Dict[bytes, _WrappedTx] = {}
        self._fifo: List[_WrappedTx] = []  # gossip & FIFO order
        self._seq = itertools.count()
        self._size_bytes = 0
        self._pre_check: Optional[Callable] = None
        self._post_check: Optional[Callable] = None
        self._notify_available: Optional[Callable] = None
        # libs.metrics.MempoolMetrics, attached by node setup when the
        # instrumentation config enables prometheus (None = no-op)
        self.metrics = None

    # -- config hooks ---------------------------------------------------

    def set_pre_check(self, fn: Callable) -> None:
        self._pre_check = fn

    def set_post_check(self, fn: Callable) -> None:
        self._post_check = fn

    def set_notify_available(self, fn: Callable) -> None:
        """Called once when the mempool transitions empty -> non-empty
        (consensus's txsAvailable channel)."""
        self._notify_available = fn

    # -- core -----------------------------------------------------------

    def size(self) -> int:
        with self._mtx:
            return len(self._tx_by_key)

    def size_bytes(self) -> int:
        with self._mtx:
            return self._size_bytes

    def is_empty(self) -> bool:
        return self.size() == 0

    def check_tx(self, tx: bytes, callback: Optional[Callable] = None, sender: str = "") -> abci.ResponseCheckTx:
        """mempool.go:230-342."""
        if len(tx) > self._cfg.max_tx_bytes:
            raise ValueError(
                f"tx size {len(tx)} exceeds max {self._cfg.max_tx_bytes}"
            )
        if self._pre_check is not None:
            self._pre_check(tx)
        if not self._cache.push(tx):
            # seen before: reject as duplicate (mempool.go:270-287)
            raise DuplicateTxError(tx_key(tx))
        res = self._proxy.check_tx(abci.RequestCheckTx(tx=tx, type=abci.CHECK_TX_TYPE_NEW))
        if res.is_ok():
            with self._mtx:
                if len(self._tx_by_key) >= self._cfg.size or (
                    self._size_bytes + len(tx) > self._cfg.max_txs_bytes
                ):
                    # full: evict strictly-lower-priority txs to make room
                    # (mempool.go:498 + priority_queue.go GetEvictableTxs);
                    # reject when no such set frees enough capacity
                    victims = self._evictable_locked(res.priority, len(tx))
                    if not victims:
                        self._cache.remove(tx)
                        raise MempoolFullError(len(self._tx_by_key))
                    for v in victims:
                        self._remove_tx(v.key, compact=False)
                        self._cache.remove(v.tx)
                    self._compact_fifo()
                    if self.metrics is not None:
                        self.metrics.evicted_txs.inc(len(victims))
                was_empty = not self._tx_by_key
                wtx = _WrappedTx(
                    sort_key=(-res.priority, next(self._seq)),
                    tx=tx,
                    key=tx_key(tx),
                    priority=res.priority,
                    sender=res.sender or sender,
                    gas_wanted=res.gas_wanted,
                    height=self._height,
                    timestamp=time.time(),
                )
                self._tx_by_key[wtx.key] = wtx
                self._fifo.append(wtx)
                self._size_bytes += len(tx)
            if was_empty and self._notify_available is not None:
                self._notify_available()
            if self.metrics is not None:
                self.metrics.tx_size_bytes.observe(len(tx))
        else:
            if self.metrics is not None:
                self.metrics.failed_txs.inc()
            if not self._cfg.keep_invalid_txs_in_cache:
                self._cache.remove(tx)
        if callback is not None:
            callback(res)
        return res

    def _evictable_locked(self, priority: int, tx_size: int) -> List[_WrappedTx]:
        """priority_queue.go:34 GetEvictableTxs: ascending-priority txs
        strictly below `priority`, taken until the new tx fits both the
        byte and count budgets; empty when impossible."""
        candidates = sorted(
            self._tx_by_key.values(), key=lambda w: (w.priority, -w.seq)
        )
        victims: List[_WrappedTx] = []
        bytes_after = self._size_bytes
        count_after = len(self._tx_by_key)
        for w in candidates:
            if w.priority >= priority:
                break
            victims.append(w)
            bytes_after -= len(w.tx)
            count_after -= 1
            if (
                bytes_after + tx_size <= self._cfg.max_txs_bytes
                and count_after < self._cfg.size
            ):
                return victims
        return []

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> List[bytes]:
        """mempool.go:344-402: highest priority first, FIFO within equal
        priority, respecting byte/gas budgets."""
        with self._mtx:
            ordered = sorted(self._tx_by_key.values())
            out: List[bytes] = []
            total_bytes = 0
            total_gas = 0
            for wtx in ordered:
                sz = len(wtx.tx) + 6  # framing overhead like ComputeProtoSizeForTxs
                if max_bytes > -1 and total_bytes + sz > max_bytes:
                    break
                if max_gas > -1 and total_gas + wtx.gas_wanted > max_gas:
                    break
                total_bytes += sz
                total_gas += wtx.gas_wanted
                out.append(wtx.tx)
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._mtx:
            ordered = sorted(self._tx_by_key.values())
            if n < 0:
                n = len(ordered)
            return [w.tx for w in ordered[:n]]

    def txs_fifo(self) -> List[bytes]:
        """Gossip order (the clist walk in the reference's reactor)."""
        with self._mtx:
            return [w.tx for w in self._fifo if not w.removed]

    # -- consensus integration ------------------------------------------

    def lock(self) -> None:
        # cross-method Lock/Unlock API mirroring the reference's
        # Mempool.Lock (consensus holds it across ReapMaxBytes + Update);
        # a with-block cannot span the two calls
        self._mtx.acquire()  # tmlint: disable=lock-discipline — reference API shape

    def unlock(self) -> None:
        self._mtx.release()

    def flush_app_conn(self) -> None:
        if hasattr(self._proxy, "flush"):
            self._proxy.flush()

    def update(
        self,
        height: int,
        txs: List[bytes],
        deliver_tx_responses: List[abci.ResponseDeliverTx],
        pre_check: Optional[Callable] = None,
        post_check: Optional[Callable] = None,
    ) -> None:
        """mempool.go:430-500. Caller must hold the lock."""
        self._height = height
        if pre_check is not None:
            self._pre_check = pre_check
        if post_check is not None:
            self._post_check = post_check
        for tx, res in zip(txs, deliver_tx_responses):
            if res.is_ok():
                self._cache.push(tx)  # committed: keep in cache forever-ish
            elif not self._cfg.keep_invalid_txs_in_cache:
                self._cache.remove(tx)
            self._remove_tx(tx_key(tx), compact=False)
        self._compact_fifo()
        self._purge_expired_txs()
        if self._cfg.recheck and self._tx_by_key:
            self._recheck_txs()

    def _purge_expired_txs(self) -> None:
        """mempool.go:806-850 purgeExpiredTxs: drop txs past the
        height-based (ttl_num_blocks) or time-based (ttl_duration_ms)
        TTL. No-op when both are 0."""
        ttl_blocks = self._cfg.ttl_num_blocks
        ttl_s = self._cfg.ttl_duration_ms / 1000.0
        if ttl_blocks <= 0 and ttl_s <= 0:
            return
        now = time.time()
        for wtx in list(self._tx_by_key.values()):
            if ttl_blocks > 0 and self._height - wtx.height > ttl_blocks:
                self._remove_tx(wtx.key, compact=False)
                self._cache.remove(wtx.tx)
            elif ttl_s > 0 and now - wtx.timestamp > ttl_s:
                self._remove_tx(wtx.key, compact=False)
                self._cache.remove(wtx.tx)
        self._compact_fifo()

    def _remove_tx(self, key: bytes, compact: bool = True) -> None:
        wtx = self._tx_by_key.pop(key, None)
        if wtx is not None:
            wtx.removed = True
            self._size_bytes -= len(wtx.tx)
        if compact:
            self._compact_fifo()

    def _compact_fifo(self) -> None:
        self._fifo = [w for w in self._fifo if not w.removed]

    def _recheck_txs(self) -> None:
        """mempool.go:580-620: re-CheckTx all remaining txs."""
        if self.metrics is not None:
            self.metrics.recheck_times.inc(len(self._tx_by_key))
        for wtx in list(self._tx_by_key.values()):
            res = self._proxy.check_tx(
                abci.RequestCheckTx(tx=wtx.tx, type=abci.CHECK_TX_TYPE_RECHECK)
            )
            ok = res.is_ok()
            if ok and self._post_check is not None:
                try:
                    self._post_check(wtx.tx, res)
                except ValueError:
                    ok = False
            if not ok:
                self._remove_tx(wtx.key, compact=False)
                if not self._cfg.keep_invalid_txs_in_cache:
                    self._cache.remove(wtx.tx)
        self._compact_fifo()

    def remove_tx_by_key(self, key: bytes) -> bool:
        """mempool.go RemoveTxByKey (public API used by the remove_tx
        RPC): drop a tx by key; False if absent."""
        with self._mtx:
            if key not in self._tx_by_key:
                return False
            self._remove_tx(key)
            return True

    def flush(self) -> None:
        with self._mtx:
            self._tx_by_key.clear()
            self._fifo.clear()
            self._size_bytes = 0
            self._cache.reset()


class DuplicateTxError(ValueError):
    def __init__(self, key: bytes):
        super().__init__(f"tx already exists in cache: {key.hex()}")
        self.key = key


class MempoolFullError(RuntimeError):
    def __init__(self, size: int):
        super().__init__(f"mempool is full: {size} txs")
