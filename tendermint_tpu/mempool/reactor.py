"""Mempool reactor — tx gossip.

Reference parity: internal/mempool/reactor.go — channel 0x30, Txs message
(batched), per-peer dedup via tx-seen tracking (internal/mempool/ids.go +
the clist walk). Here: broadcast on local CheckTx success, relay
first-seen txs from peers.

Wire: Txs{1 txs(repeated bytes)}.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional, Set

from ..p2p.conn.mconnection import ChannelDescriptor
from ..p2p.router import Router
from ..types.tx import tx_key
from ..wire.proto import ProtoWriter, decode_message
from . import DuplicateTxError, MempoolFullError, TxMempool

MEMPOOL_CHANNEL = 0x30
MEMPOOL_DESC = ChannelDescriptor(
    id=MEMPOOL_CHANNEL, priority=5, recv_message_capacity=1024 * 1024
)


def encode_txs(txs) -> bytes:
    w = ProtoWriter()
    for tx in txs:
        w.write_bytes(1, tx, always=True)
    return w.bytes()


def decode_txs(data: bytes):
    f = decode_message(data)
    from ..wire.proto import field_repeated_bytes
    return field_repeated_bytes(f, 1)


class MempoolReactor:
    def __init__(self, mempool: TxMempool, router: Router, broadcast: bool = True):
        self._mempool = mempool
        self._router = router
        self._broadcast = broadcast
        self._ch = router.open_channel(MEMPOOL_DESC)
        self._stopped = threading.Event()
        self._seen_from_peers: Set[bytes] = set()

    def start(self) -> None:
        t = threading.Thread(target=self._recv_loop, daemon=True)
        t.start()

    def stop(self) -> None:
        self._stopped.set()

    # -- local entry: checked tx broadcast -------------------------------

    def check_tx_and_broadcast(self, tx: bytes):
        res = self._mempool.check_tx(tx)
        if res.is_ok() and self._broadcast:
            self._ch.broadcast(encode_txs([tx]))
        return res

    # -- peer gossip ------------------------------------------------------

    def _recv_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                env = self._ch.receive(timeout=0.5)
            except queue.Empty:
                continue
            for tx in decode_txs(env.message):
                k = tx_key(tx)
                if k in self._seen_from_peers:
                    continue
                self._seen_from_peers.add(k)
                try:
                    res = self._mempool.check_tx(tx, sender=env.from_id)
                except (DuplicateTxError, MempoolFullError, ValueError):
                    continue
                if res.is_ok() and self._broadcast:
                    # relay to the rest of the mesh (reactor.go broadcast walk)
                    self._ch.broadcast(encode_txs([tx]))
