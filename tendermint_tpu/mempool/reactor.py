"""Mempool reactor — tx gossip.

Reference parity: internal/mempool/reactor.go — channel 0x30, Txs message
(batched), per-peer dedup via tx-seen tracking (internal/mempool/ids.go +
the clist walk). Here: broadcast on local CheckTx success, relay
first-seen txs from peers.

Wire: Txs{1 txs(repeated bytes)}.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional, Set

from ..p2p.conn.mconnection import ChannelDescriptor
from ..p2p.router import Router
from ..types.tx import tx_key
from ..wire.proto import ProtoWriter, decode_message
from . import DuplicateTxError, MempoolFullError, TxMempool

MEMPOOL_CHANNEL = 0x30
MEMPOOL_DESC = ChannelDescriptor(
    id=MEMPOOL_CHANNEL, priority=5, recv_message_capacity=1024 * 1024
)


def encode_txs(txs) -> bytes:
    w = ProtoWriter()
    for tx in txs:
        w.write_bytes(1, tx, always=True)
    return w.bytes()


def decode_txs(data: bytes):
    f = decode_message(data)
    from ..wire.proto import field_repeated_bytes
    return field_repeated_bytes(f, 1)


class MempoolReactor:
    def __init__(self, mempool: TxMempool, router: Router, broadcast: bool = True):
        self._mempool = mempool
        self._router = router
        self._broadcast = broadcast
        self._ch = router.open_channel(MEMPOOL_DESC)
        self._stopped = threading.Event()
        self._seen_from_peers: Set[bytes] = set()

    def start(self) -> None:
        t = threading.Thread(target=self._recv_loop, daemon=True)
        t.start()

    def stop(self) -> None:
        self._stopped.set()

    # -- local entry: checked tx broadcast -------------------------------

    def submit_tx_and_broadcast(self, tx: bytes):
        """Async entry (ISSUE 13): submit through check_tx_async and
        broadcast from a done-callback on verdict success — the caller
        never blocks on the device window, and no mempool lock is held
        anywhere near the wait. The callback only reads the response and
        pushes to the p2p channel (thread-safe), so running it on the
        ingress completer thread is fine. Precheck failures (duplicate,
        oversize, malformed envelope) still raise synchronously."""
        fut = self._mempool.check_tx_async(tx)

        def _relay(f, tx=tx):
            try:
                res = f.result()
            except Exception:  # noqa: BLE001 — rejected/poisoned: no relay
                return
            if res.is_ok() and self._broadcast:
                self._ch.broadcast(encode_txs([tx]))

        fut.add_done_callback(_relay)
        return fut

    def check_tx_and_broadcast(self, tx: bytes):
        """Sync facade over submit_tx_and_broadcast (the RPC
        broadcast_tx_sync path): blocks for the response, but the
        broadcast-on-success rides the done-callback either way."""
        return self.submit_tx_and_broadcast(tx).result(timeout=300)

    # -- peer gossip ------------------------------------------------------

    def _recv_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                env = self._ch.receive(timeout=0.5)
            except queue.Empty:
                continue
            for tx in decode_txs(env.message):
                k = tx_key(tx)
                if k in self._seen_from_peers:
                    continue
                self._seen_from_peers.add(k)
                try:
                    # async per tx: a peer's batched Txs message lands in
                    # ONE accumulator window instead of serializing this
                    # loop on per-tx device waits (ISSUE 13)
                    fut = self._mempool.check_tx_async(
                        tx, sender=env.from_id
                    )
                except (DuplicateTxError, MempoolFullError, ValueError):
                    continue

                def _relay(f, tx=tx):
                    try:
                        res = f.result()
                    except Exception:  # noqa: BLE001 — no relay on failure
                        return
                    if res.is_ok() and self._broadcast:
                        # relay to the rest of the mesh (reactor.go
                        # broadcast walk)
                        self._ch.broadcast(encode_txs([tx]))

                fut.add_done_callback(_relay)
