"""Device-batched transaction ingress (ISSUE 13).

The second serving workload from the north star: user transactions.
`check_tx` used to be a pure host path — the signed-tx envelope below
adds signature-carrying txs, and this module's accumulator batches their
signatures into `EntryBlock`s over a short time/size window and submits
them to the SHARED AsyncBatchVerifier at INGRESS priority, so a tx flood
rides the device pipeline (thousands of sigs per relay command) without
ever starving consensus commit batches (ops/pipeline.py QoS classes).

Signed-tx envelope (scheme-tagged, nonce-carrying):

    MAGIC(4) | scheme(1) | pub(32|33) | nonce(8 BE) | sig(64) | payload

The signed message is the envelope minus the signature field (MAGIC +
scheme + pub + nonce + payload) — a signature cannot be transplanted
onto a different payload, nonce or key. Txs WITHOUT the magic (the
kvstore's `k=v` and `val:` txs, every pre-existing test fixture) carry
no signature and bypass the verification stage entirely: their CheckTx
responses are byte-identical to the pre-ISSUE-13 behavior.

Scheme lanes (the 2302.00418 story):
  ed25519    device lane — batched through the shared verifier
  sr25519    host batch lane — crypto/sr25519.verify_batch (the native
             schnorrkel batch path when built); schnorrkel's transcript
             binding has no device kernel here yet
  secp256k1  host fallback, one ECDSA verify per tx on the completion
             thread — batched ECDSA verification is the documented gap
             (README "Transaction ingress"); NEVER silently dropped: an
             unverifiable sig is an explicit rejection, not an accept.

Threading (the deadlock rule this module exists to respect): completion
work that takes the mempool lock runs on the accumulator's OWN completer
thread, never on the pipeline's resolver thread — consensus holds the
mempool lock across update()→recheck while waiting on pipeline futures,
so a resolver blocked on that lock would deadlock the process. Verifier
done-callbacks only enqueue; the completer does the locking.
"""

from __future__ import annotations

import os
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

MAGIC = b"\xc1TX1"
SCHEME_ED25519 = 0
SCHEME_SR25519 = 1
SCHEME_SECP256K1 = 2
_PUB_LEN = {SCHEME_ED25519: 32, SCHEME_SR25519: 32, SCHEME_SECP256K1: 33}
_SIG_LEN = 64
_NONCE_LEN = 8

DEFAULT_BATCH = 256
DEFAULT_WINDOW_MS = 4.0


class MalformedTxError(ValueError):
    """Envelope magic present but the structure is broken (truncated
    fields, unknown scheme). A ValueError so the reactor/RPC catch sites
    that already reject bad txs reject these too."""


class SignedTx:
    __slots__ = ("scheme", "pub", "nonce", "sig", "payload", "raw")

    def __init__(self, scheme: int, pub: bytes, nonce: int, sig: bytes,
                 payload: bytes, raw: bytes):
        self.scheme = scheme
        self.pub = pub
        self.nonce = nonce
        self.sig = sig
        self.payload = payload
        self.raw = raw

    def signed_bytes(self) -> bytes:
        """The message the signature covers: the envelope minus the
        signature field."""
        return (MAGIC + bytes([self.scheme]) + self.pub
                + self.nonce.to_bytes(_NONCE_LEN, "big") + self.payload)


def parse_signed_tx(tx: bytes) -> Optional[SignedTx]:
    """None when `tx` carries no envelope (legacy tx — no sig stage);
    MalformedTxError when the magic is present but the layout is not."""
    if not tx.startswith(MAGIC):
        return None
    if len(tx) < len(MAGIC) + 1:
        raise MalformedTxError("signed tx truncated before scheme byte")
    scheme = tx[len(MAGIC)]
    pub_len = _PUB_LEN.get(scheme)
    if pub_len is None:
        raise MalformedTxError(f"unknown signature scheme {scheme}")
    hdr = len(MAGIC) + 1 + pub_len + _NONCE_LEN + _SIG_LEN
    if len(tx) < hdr:
        raise MalformedTxError(
            f"signed tx truncated: {len(tx)} < {hdr} header bytes"
        )
    off = len(MAGIC) + 1
    pub = tx[off : off + pub_len]
    off += pub_len
    nonce = int.from_bytes(tx[off : off + _NONCE_LEN], "big")
    off += _NONCE_LEN
    sig = tx[off : off + _SIG_LEN]
    off += _SIG_LEN
    return SignedTx(scheme, pub, nonce, sig, tx[off:], tx)


def encode_signed_tx(scheme: int, pub: bytes, nonce: int, sig: bytes,
                     payload: bytes) -> bytes:
    if len(pub) != _PUB_LEN[scheme]:
        raise ValueError(f"scheme {scheme} pubkey must be "
                         f"{_PUB_LEN[scheme]} bytes, got {len(pub)}")
    if len(sig) != _SIG_LEN:
        raise ValueError(f"signature must be {_SIG_LEN} bytes")
    return (MAGIC + bytes([scheme]) + pub
            + int(nonce).to_bytes(_NONCE_LEN, "big") + sig + payload)


def make_signed_tx(priv, payload: bytes, nonce: int,
                   scheme: int = SCHEME_ED25519) -> bytes:
    """Sign `payload` under the envelope: the signature covers the full
    header (scheme, pub, nonce) plus the payload."""
    pub = priv.pub_key().bytes()
    body = (MAGIC + bytes([scheme]) + pub
            + int(nonce).to_bytes(_NONCE_LEN, "big") + payload)
    sig = priv.sign(body)
    return encode_signed_tx(scheme, pub, nonce, sig, payload)


def host_verify(stx: SignedTx) -> bool:
    """Per-scheme host verification — the sequential baseline (no
    accumulator attached) and the recheck fallback for host-lane schemes.
    An unverifiable signature (missing native backend, structurally bad
    key) is False — an explicit rejection — never a silent accept."""
    msg = stx.signed_bytes()
    try:
        if stx.scheme == SCHEME_ED25519:
            from ..crypto import ed25519 as _ed

            return bool(_ed.verify_zip215_fast(stx.pub, msg, stx.sig))
        if stx.scheme == SCHEME_SR25519:
            from ..crypto import sr25519 as _sr

            return bool(_sr.verify_batch([(stx.pub, msg, stx.sig)])[0])
        if stx.scheme == SCHEME_SECP256K1:
            from ..crypto import secp256k1 as _secp

            return bool(_secp.PubKey(stx.pub).verify_signature(msg, stx.sig))
    except Exception:  # noqa: BLE001 — reject, never crash CheckTx
        return False
    return False


class _Pending:
    __slots__ = ("stx", "future", "t_enq")

    def __init__(self, stx: SignedTx, t_enq: float):
        self.stx = stx
        self.future: "Future[bool]" = Future()
        self.t_enq = t_enq


# live accumulators for /status aggregation (rpc/core.py)
_ACTIVE: "weakref.WeakSet[IngressAccumulator]" = weakref.WeakSet()


def ingress_stats() -> dict:
    """Aggregate snapshot over every live accumulator in the process —
    the /status `mempool_ingress` section."""
    accs = list(_ACTIVE)
    if not accs:
        return {"enabled": False}
    out: Dict[str, float] = {
        "enabled": True, "queue_depth": 0, "batches": 0, "sigs": 0,
        "host_lane_sigs": 0, "preemptions": 0, "dispatch_errors": 0,
    }
    waits = []
    for a in accs:
        s = a.stats()
        out["queue_depth"] += s["queue_depth"]
        out["batches"] += s["batches"]
        out["sigs"] += s["sigs"]
        out["host_lane_sigs"] += s["host_lane_sigs"]
        out["preemptions"] += s["preemptions"]
        out["dispatch_errors"] += s["dispatch_errors"]
        if s["batch_wait_ms_avg"]:
            waits.append(s["batch_wait_ms_avg"])
    out["batch_wait_ms_avg"] = sum(waits) / len(waits) if waits else 0.0
    return out


class IngressAccumulator:
    """Window/size-batched CheckTx signature verification.

    submit(stx) returns a Future[bool] sig verdict. ed25519 entries
    accumulate until `max_batch` signatures or `window_ms` after the
    oldest entry, then flush as ONE EntryBlock into the shared verifier
    at PRIORITY_INGRESS; sr25519/secp256k1 entries flush on the same
    clock through their host lanes. Verdict futures resolve on the
    accumulator's completer thread (see the module docstring for why
    that thread exists). A DispatchError from the device poisons ONLY
    its own window's futures — later windows are untouched.

    Knobs: TM_TPU_MEMPOOL_BATCH (default 256 sigs) and
    TM_TPU_MEMPOOL_WINDOW_MS (default 4 ms)."""

    def __init__(self, verifier=None, max_batch: Optional[int] = None,
                 window_ms: Optional[float] = None, metrics=None):
        if max_batch is None:
            max_batch = int(os.environ.get("TM_TPU_MEMPOOL_BATCH",
                                           DEFAULT_BATCH))
        if window_ms is None:
            window_ms = float(os.environ.get("TM_TPU_MEMPOOL_WINDOW_MS",
                                             DEFAULT_WINDOW_MS))
        self._max = max(int(max_batch), 1)
        self._window_s = max(float(window_ms), 0.0) / 1000.0
        self._v = verifier
        self._v_hooked = False
        self.metrics = metrics
        self._mtx = threading.Lock()
        self._pend_dev: List[_Pending] = []    # ed25519 → device lane
        self._pend_host: List[_Pending] = []   # sr25519/secp256k1 lanes
        self._t_first = 0.0
        self._wake = threading.Event()   # new work for the flusher
        self._full = threading.Event()   # batch hit max: flush now
        self._cq: "queue.Queue" = queue.Queue()
        self._inflight = 0               # flushed-but-uncompleted batches
        self._stopped = threading.Event()
        # counters (read via stats(); the metrics set mirrors them)
        self.batches = 0
        self.sigs = 0
        self.host_lane_sigs = 0
        self.preempted = 0
        self.dispatch_errors = 0
        self._wait_ms_sum = 0.0
        self._thread = threading.Thread(
            target=self._flusher, daemon=True, name="mempool-ingress-flush"
        )
        self._cthread = threading.Thread(
            target=self._completer, daemon=True,
            name="mempool-ingress-complete",
        )
        self._thread.start()
        self._cthread.start()
        _ACTIVE.add(self)

    # -- wiring ----------------------------------------------------------

    def _metrics(self):
        if self.metrics is None:
            from ..libs import metrics as _m

            self.metrics = _m.mempool_metrics()
        return self.metrics

    def _ensure_verifier(self):
        if self._v is None:
            from ..ops import pipeline as _pl

            self._v = _pl.shared_verifier()
        if not self._v_hooked:
            self._v_hooked = True
            hook = getattr(self._v, "add_preempt_hook", None)
            if hook is not None:
                hook(self._note_preempt)
        return self._v

    def _note_preempt(self, n: int) -> None:
        self.preempted += n
        try:
            self._metrics().checktx_preemptions.inc(n)
        except Exception:  # noqa: BLE001 — observability never fatal
            pass

    # -- submission ------------------------------------------------------

    def submit(self, stx: SignedTx) -> "Future[bool]":
        """Queue one signature; the returned future resolves to the bool
        verdict (or raises DispatchError when the device window failed)
        on the completer thread."""
        if self._stopped.is_set():
            raise RuntimeError("ingress accumulator is closed")
        p = _Pending(stx, time.perf_counter())
        with self._mtx:
            lane = (self._pend_dev if stx.scheme == SCHEME_ED25519
                    else self._pend_host)
            if not self._pend_dev and not self._pend_host:
                self._t_first = p.t_enq
            lane.append(p)
            depth = len(self._pend_dev) + len(self._pend_host)
            full = depth >= self._max or self._window_s <= 0.0
        m = self._metrics()
        if m is not None:
            m.ingress_queue_depth.set(depth)
        if full:
            self._full.set()
        self._wake.set()
        return p.future

    def submit_block(self, block, priority: Optional[int] = None):
        """Raw EntryBlock passthrough for recheck: returns the PIPELINE
        future directly (resolved on the resolver thread, which never
        takes the mempool lock) — safe to wait on while holding the
        mempool lock, unlike the per-tx futures from submit()."""
        from ..ops import pipeline as _pl

        if priority is None:
            priority = _pl.PRIORITY_INGRESS
        return self._ensure_verifier().submit(block, priority=priority)

    def flush_now(self) -> None:
        self._full.set()
        self._wake.set()

    # -- flusher thread --------------------------------------------------

    def _flusher(self) -> None:
        while True:
            with self._mtx:
                have = bool(self._pend_dev or self._pend_host)
                t_first = self._t_first
            if not have:
                if self._stopped.is_set():
                    break
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            if self._window_s > 0.0 and not self._stopped.is_set():
                remaining = t_first + self._window_s - time.perf_counter()
                if remaining > 0 and not self._full.is_set():
                    self._full.wait(remaining)
            self._full.clear()
            self._flush()

    def _flush(self) -> None:
        with self._mtx:
            dev, self._pend_dev = self._pend_dev, []
            host, self._pend_host = self._pend_host, []
            self._t_first = 0.0
        if not dev and not host:
            return
        now = time.perf_counter()
        wait_ms = max(
            (now - min(p.t_enq for p in dev + host)) * 1e3, 0.0
        )
        self.batches += 1
        self.sigs += len(dev) + len(host)
        self.host_lane_sigs += len(host)
        self._wait_ms_sum += wait_ms
        m = self._metrics()
        if m is not None:
            m.ingress_queue_depth.set(0)
            m.ingress_batch_wait_ms.observe(wait_ms)
        if dev:
            self._flush_device(dev)
        if host:
            self._cq.put(("host", host))

    def _flush_device(self, dev: List[_Pending]) -> None:
        try:
            from ..ops.entry_block import EntryBlock

            block = EntryBlock.from_entries(
                [(p.stx.pub, p.stx.signed_bytes(), p.stx.sig) for p in dev]
            )
            with self._mtx:
                self._inflight += 1
            fut = self.submit_block(block)
        except Exception as e:  # noqa: BLE001 — window isolation
            with self._mtx:
                self._inflight = max(self._inflight - 1, 0)
            for p in dev:
                if not p.future.done():
                    p.future.set_exception(e)
            return
        # done-callback runs on the pipeline resolver: ONLY enqueue —
        # the completer owns any work that may take the mempool lock
        fut.add_done_callback(
            lambda f, batch=dev: self._cq.put(("device", batch, f))
        )

    # -- completer thread ------------------------------------------------

    def _completer(self) -> None:
        while True:
            item = self._cq.get()
            if item is None:
                break
            if item[0] == "device":
                _, batch, fut = item
                self._complete_device(batch, fut)
                with self._mtx:
                    self._inflight = max(self._inflight - 1, 0)
            else:
                self._complete_host(item[1])

    @staticmethod
    def _deliver(p: _Pending, ok: bool) -> None:
        if not p.future.done():
            p.future.set_result(bool(ok))

    def _complete_device(self, batch: List[_Pending], fut) -> None:
        err = fut.exception()
        if err is not None:
            # poisoned window: exactly these futures fail; the
            # accumulator and every later window keep flowing
            self.dispatch_errors += 1
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(err)
            return
        verdicts = fut.result()
        for p, ok in zip(batch, verdicts):
            self._deliver(p, bool(ok))

    def _complete_host(self, batch: List[_Pending]) -> None:
        sr = [p for p in batch if p.stx.scheme == SCHEME_SR25519]
        if sr:
            try:
                from ..crypto import sr25519 as _sr

                verdicts = _sr.verify_batch(
                    [(p.stx.pub, p.stx.signed_bytes(), p.stx.sig)
                     for p in sr]
                )
            except Exception:  # noqa: BLE001 — reject, never drop
                verdicts = [False] * len(sr)
            for p, ok in zip(sr, verdicts):
                self._deliver(p, bool(ok))
        for p in batch:
            if p.stx.scheme == SCHEME_SR25519:
                continue
            # secp256k1 (and anything future): per-sig host fallback —
            # the explicit non-batched path, never a silent drop
            self._deliver(p, host_verify(p.stx))

    # -- lifecycle / introspection ---------------------------------------

    def stats(self) -> dict:
        with self._mtx:
            depth = len(self._pend_dev) + len(self._pend_host)
        return {
            "queue_depth": depth,
            "batches": self.batches,
            "sigs": self.sigs,
            "host_lane_sigs": self.host_lane_sigs,
            "batch_wait_ms_avg": (
                self._wait_ms_sum / self.batches if self.batches else 0.0
            ),
            "preemptions": self.preempted,
            "dispatch_errors": self.dispatch_errors,
            "max_batch": self._max,
            "window_ms": self._window_s * 1e3,
        }

    def close(self, timeout: float = 10.0) -> None:
        self._stopped.set()
        self._wake.set()
        self._full.set()
        self._thread.join(timeout=timeout)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mtx:
                if self._inflight == 0:
                    break
            time.sleep(0.005)
        self._cq.put(None)
        self._cthread.join(timeout=timeout)
