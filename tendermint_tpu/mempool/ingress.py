"""Device-batched transaction ingress (ISSUE 13).

The second serving workload from the north star: user transactions.
`check_tx` used to be a pure host path — the signed-tx envelope below
adds signature-carrying txs, and this module's accumulator batches their
signatures into `EntryBlock`s over a short time/size window and submits
them to the SHARED AsyncBatchVerifier at INGRESS priority, so a tx flood
rides the device pipeline (thousands of sigs per relay command) without
ever starving consensus commit batches (ops/pipeline.py QoS classes).

Signed-tx envelope (scheme-tagged, nonce-carrying):

    MAGIC(4) | scheme(1) | pub(32|33) | nonce(8 BE) | sig(64) | payload

The signed message is the envelope minus the signature field (MAGIC +
scheme + pub + nonce + payload) — a signature cannot be transplanted
onto a different payload, nonce or key. Txs WITHOUT the magic (the
kvstore's `k=v` and `val:` txs, every pre-existing test fixture) carry
no signature and bypass the verification stage entirely: their CheckTx
responses are byte-identical to the pre-ISSUE-13 behavior.

Scheme lanes (the 2302.00418 story):
  ed25519    device lane — batched through the shared verifier
  sr25519    host batch lane — crypto/sr25519.verify_batch (the native
             schnorrkel batch path when built); schnorrkel's transcript
             binding has no device kernel here yet
  secp256k1  host fallback, one ECDSA verify per tx on the completion
             thread — batched ECDSA verification is the documented gap
             (README "Transaction ingress"); NEVER silently dropped: an
             unverifiable sig is an explicit rejection, not an accept.

Threading (the deadlock rule this module exists to respect): completion
work that takes the mempool lock runs on the ingress fabric's completer
thread, never on the pipeline's resolver thread — consensus holds the
mempool lock across update()→recheck while waiting on pipeline futures,
so a resolver blocked on that lock would deadlock the process. Verifier
done-callbacks only enqueue; the completer does the locking.

Since ISSUE 17 the windowing machinery itself lives in ops/ingress.py
(the one ingress fabric): this module keeps the envelope format, the
host-stage scheme routing, and the verdict-future delivery — a LaneSpec
plus callbacks. Knobs: TM_TPU_INGRESS_MEMPOOL_BATCH / _WINDOW_MS
(legacy TM_TPU_MEMPOOL_BATCH / TM_TPU_MEMPOOL_WINDOW_MS still honored
with a DeprecationWarning).
"""

from __future__ import annotations

import weakref
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

from ..ops import ingress as _fabric

MAGIC = b"\xc1TX1"
SCHEME_ED25519 = 0
SCHEME_SR25519 = 1
SCHEME_SECP256K1 = 2
_PUB_LEN = {SCHEME_ED25519: 32, SCHEME_SR25519: 32, SCHEME_SECP256K1: 33}
_SIG_LEN = 64
_NONCE_LEN = 8

DEFAULT_BATCH = 256
DEFAULT_WINDOW_MS = 4.0


class MalformedTxError(ValueError):
    """Envelope magic present but the structure is broken (truncated
    fields, unknown scheme). A ValueError so the reactor/RPC catch sites
    that already reject bad txs reject these too."""


class SignedTx:
    __slots__ = ("scheme", "pub", "nonce", "sig", "payload", "raw")

    def __init__(self, scheme: int, pub: bytes, nonce: int, sig: bytes,
                 payload: bytes, raw: bytes):
        self.scheme = scheme
        self.pub = pub
        self.nonce = nonce
        self.sig = sig
        self.payload = payload
        self.raw = raw

    def signed_bytes(self) -> bytes:
        """The message the signature covers: the envelope minus the
        signature field."""
        return (MAGIC + bytes([self.scheme]) + self.pub
                + self.nonce.to_bytes(_NONCE_LEN, "big") + self.payload)


def parse_signed_tx(tx: bytes) -> Optional[SignedTx]:
    """None when `tx` carries no envelope (legacy tx — no sig stage);
    MalformedTxError when the magic is present but the layout is not."""
    if not tx.startswith(MAGIC):
        return None
    if len(tx) < len(MAGIC) + 1:
        raise MalformedTxError("signed tx truncated before scheme byte")
    scheme = tx[len(MAGIC)]
    pub_len = _PUB_LEN.get(scheme)
    if pub_len is None:
        raise MalformedTxError(f"unknown signature scheme {scheme}")
    hdr = len(MAGIC) + 1 + pub_len + _NONCE_LEN + _SIG_LEN
    if len(tx) < hdr:
        raise MalformedTxError(
            f"signed tx truncated: {len(tx)} < {hdr} header bytes"
        )
    off = len(MAGIC) + 1
    pub = tx[off : off + pub_len]
    off += pub_len
    nonce = int.from_bytes(tx[off : off + _NONCE_LEN], "big")
    off += _NONCE_LEN
    sig = tx[off : off + _SIG_LEN]
    off += _SIG_LEN
    return SignedTx(scheme, pub, nonce, sig, tx[off:], tx)


def encode_signed_tx(scheme: int, pub: bytes, nonce: int, sig: bytes,
                     payload: bytes) -> bytes:
    if len(pub) != _PUB_LEN[scheme]:
        raise ValueError(f"scheme {scheme} pubkey must be "
                         f"{_PUB_LEN[scheme]} bytes, got {len(pub)}")
    if len(sig) != _SIG_LEN:
        raise ValueError(f"signature must be {_SIG_LEN} bytes")
    return (MAGIC + bytes([scheme]) + pub
            + int(nonce).to_bytes(_NONCE_LEN, "big") + sig + payload)


def make_signed_tx(priv, payload: bytes, nonce: int,
                   scheme: int = SCHEME_ED25519) -> bytes:
    """Sign `payload` under the envelope: the signature covers the full
    header (scheme, pub, nonce) plus the payload."""
    pub = priv.pub_key().bytes()
    body = (MAGIC + bytes([scheme]) + pub
            + int(nonce).to_bytes(_NONCE_LEN, "big") + payload)
    sig = priv.sign(body)
    return encode_signed_tx(scheme, pub, nonce, sig, payload)


def host_verify(stx: SignedTx) -> bool:
    """Per-scheme host verification — the sequential baseline (no
    accumulator attached) and the recheck fallback for host-lane schemes.
    An unverifiable signature (missing native backend, structurally bad
    key) is False — an explicit rejection — never a silent accept."""
    msg = stx.signed_bytes()
    try:
        if stx.scheme == SCHEME_ED25519:
            from ..crypto import ed25519 as _ed

            return bool(_ed.verify_zip215_fast(stx.pub, msg, stx.sig))
        if stx.scheme == SCHEME_SR25519:
            from ..crypto import sr25519 as _sr

            return bool(_sr.verify_batch([(stx.pub, msg, stx.sig)])[0])
        if stx.scheme == SCHEME_SECP256K1:
            from ..crypto import secp256k1 as _secp

            return bool(_secp.PubKey(stx.pub).verify_signature(msg, stx.sig))
    except Exception:  # noqa: BLE001 — reject, never crash CheckTx
        return False
    return False


# live accumulators for /status aggregation (rpc/core.py)
_ACTIVE: "weakref.WeakSet[IngressAccumulator]" = weakref.WeakSet()


def ingress_stats() -> dict:
    """Aggregate snapshot over every live accumulator in the process —
    the /status `mempool_ingress` section."""
    accs = list(_ACTIVE)
    if not accs:
        return {"enabled": False}
    out: Dict[str, float] = {
        "enabled": True, "queue_depth": 0, "batches": 0, "sigs": 0,
        "host_lane_sigs": 0, "preemptions": 0, "dispatch_errors": 0,
    }
    waits = []
    for a in accs:
        s = a.stats()
        out["queue_depth"] += s["queue_depth"]
        out["batches"] += s["batches"]
        out["sigs"] += s["sigs"]
        out["host_lane_sigs"] += s["host_lane_sigs"]
        out["preemptions"] += s["preemptions"]
        out["dispatch_errors"] += s["dispatch_errors"]
        if s["batch_wait_ms_avg"]:
            waits.append(s["batch_wait_ms_avg"])
    out["batch_wait_ms_avg"] = sum(waits) / len(waits) if waits else 0.0
    return out


class IngressAccumulator:
    """Window/size-batched CheckTx signature verification — a `mempool`
    lane on the shared ingress fabric (ops/ingress.py).

    submit(stx) returns a Future[bool] sig verdict. ed25519 entries
    accumulate until the lane's batch target or window elapses, then
    flush as ONE EntryBlock into the shared verifier at
    PRIORITY_INGRESS; sr25519/secp256k1 entries flush on the same clock
    through their host lanes. Verdict futures resolve on the fabric's
    completer thread (see the module docstring for why that thread
    exists). A DispatchError from the device poisons ONLY its own
    window's futures — later windows are untouched.

    Explicit max_batch/window_ms pin the window (deterministic, the
    pre-fabric behavior); defaulted knobs get the adaptive SLO-aware
    controller unless TM_TPU_INGRESS_MEMPOOL_ADAPTIVE says otherwise."""

    def __init__(self, verifier=None, max_batch: Optional[int] = None,
                 window_ms: Optional[float] = None, metrics=None):
        cfg = _fabric.resolve_lane_config(
            "mempool", batch=max_batch, window_ms=window_ms,
            legacy_batch="TM_TPU_MEMPOOL_BATCH",
            legacy_window="TM_TPU_MEMPOOL_WINDOW_MS",
        )
        self.metrics = metrics
        self._lane = _fabric.shared_engine().register(_fabric.LaneSpec(
            name="mempool",
            priority=_fabric.PRIORITY_INGRESS,
            batch=cfg.batch,
            window_ms=cfg.window_ms,
            budget_ms=cfg.budget_ms,
            adaptive=cfg.adaptive,
            use_completer=True,      # delivery may take the mempool lock
            closed_msg="ingress accumulator is closed",
            verifier=verifier,
            entries_fn=lambda s: (s.pub, s.signed_bytes(), s.sig),
            route_fn=lambda s: s.scheme == SCHEME_ED25519,
            host_fn=self._host_check,
            deliver=self._deliver,
            observer=self,
        ))
        _ACTIVE.add(self)

    # -- lane callbacks ---------------------------------------------------

    def _deliver(self, items, verdicts, err) -> None:
        """Resolve the per-tx verdict futures (fabric completer thread).
        A window error fails exactly these futures — poisoned-window
        isolation, the txs stay retryable upstream."""
        if err is not None:
            for it in items:
                if not it.future.done():
                    it.future.set_exception(err)
            return
        for it, ok in zip(items, verdicts):
            if not it.future.done():
                it.future.set_result(bool(ok))

    def _host_check(self, stxs: List[SignedTx]) -> Sequence[bool]:
        """Host-lane verification in item order: sr25519 as one native
        batch (schnorrkel when built), secp256k1 (and anything future)
        per-sig — the explicit non-batched path, never a silent drop."""
        verdicts: List[bool] = [False] * len(stxs)
        sr_idx = [i for i, s in enumerate(stxs)
                  if s.scheme == SCHEME_SR25519]
        if sr_idx:
            try:
                from ..crypto import sr25519 as _sr

                vs = _sr.verify_batch(
                    [(stxs[i].pub, stxs[i].signed_bytes(), stxs[i].sig)
                     for i in sr_idx]
                )
            except Exception:  # noqa: BLE001 — reject, never drop
                vs = [False] * len(sr_idx)
            for i, ok in zip(sr_idx, vs):
                verdicts[i] = bool(ok)
        for i, s in enumerate(stxs):
            if s.scheme != SCHEME_SR25519:
                verdicts[i] = host_verify(s)
        return verdicts

    # -- legacy metric mirror (fabric observer) ---------------------------

    def _metrics(self):
        if self.metrics is None:
            from ..libs import metrics as _m

            self.metrics = _m.mempool_metrics()
        return self.metrics

    def depth(self, d: int) -> None:
        self._metrics().ingress_queue_depth.set(d)

    def flush(self, n: int, wait_ms: float) -> None:
        m = self._metrics()
        m.ingress_queue_depth.set(0)
        m.ingress_batch_wait_ms.observe(wait_ms)

    def preempt(self, n: int) -> None:
        self._metrics().checktx_preemptions.inc(n)

    # -- public API -------------------------------------------------------

    def submit(self, stx: SignedTx) -> "Future[bool]":
        """Queue one signature; the returned future resolves to the bool
        verdict (or raises DispatchError when the device window failed)
        on the fabric completer thread."""
        return self._lane.submit(stx, want_future=True)

    def submit_block(self, block, priority: Optional[int] = None):
        """Raw EntryBlock passthrough for recheck: returns the PIPELINE
        future directly (resolved on the resolver thread, which never
        takes the mempool lock) — safe to wait on while holding the
        mempool lock, unlike the per-tx futures from submit()."""
        return self._lane.submit_block(block, priority=priority,
                                       count=False)

    def flush_now(self) -> None:
        self._lane.flush_now()

    def stats(self) -> dict:
        s = self._lane.stats()
        return {k: s[k] for k in (
            "queue_depth", "batches", "sigs", "host_lane_sigs",
            "batch_wait_ms_avg", "preemptions", "dispatch_errors",
            "max_batch", "window_ms",
        )}

    def close(self, timeout: float = 10.0) -> None:
        self._lane.close(timeout=timeout)
