"""AppConns — the 4-connection ABCI multiplexer.

Reference parity: internal/proxy/multi_app_conn.go — one logical ABCI
connection per use (consensus / mempool / query / snapshot), each with its
own client instance so a slow CheckTx can't block block execution, plus
per-connection call metrics (internal/proxy/client.go).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..abci import LocalClient, SocketClient
from ..abci.application import Application
from ..libs.metrics import Registry


class _TimedConn:
    """Wraps an ABCI client, timing every method (proxy metrics)."""

    def __init__(self, inner, histogram=None):
        self._inner = inner
        self._hist = histogram

    def __getattr__(self, name):
        fn = getattr(self._inner, name)
        if not callable(fn) or self._hist is None:
            return fn

        def timed(*a, **k):
            t0 = time.perf_counter()
            try:
                return fn(*a, **k)
            finally:
                self._hist.observe(time.perf_counter() - t0)

        return timed


class AppConns:
    """multi_app_conn.go AppConns: consensus/mempool/query/snapshot."""

    def __init__(
        self,
        client_factory: Callable[[], object],
        registry: Optional[Registry] = None,
    ):
        hist = None
        if registry is not None:
            hist = registry.histogram(
                "abci_connection", "method_timing_seconds", "ABCI call latency."
            )
        self.consensus = _TimedConn(client_factory(), hist)
        self.mempool = _TimedConn(client_factory(), hist)
        self.query = _TimedConn(client_factory(), hist)
        self.snapshot = _TimedConn(client_factory(), hist)

    def stop(self) -> None:
        for conn in (self.consensus, self.mempool, self.query, self.snapshot):
            inner = conn._inner
            if hasattr(inner, "close"):
                inner.close()


def local_client_factory(app: Application) -> Callable[[], object]:
    """DefaultClientCreator for in-process apps (abci/client/creators.go)."""
    return lambda: LocalClient(app)


def socket_client_factory(address: str) -> Callable[[], object]:
    return lambda: SocketClient(address)
