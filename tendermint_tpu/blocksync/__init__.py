"""Block sync — catch up by downloading committed blocks from peers.

Reference parity: internal/blocksync/ — BlockPool (pool.go:69) with
parallel per-height requesters and peer timeout/removal, and the reactor
verify/apply loop (reactor.go:500-560): each block is verified with
VerifyCommitLight against the NEXT block's LastCommit (the device batch
path — BASELINE's pipelined sync workload), then applied via the
BlockExecutor. Hands off to consensus when caught up (IsCaughtUp,
pool.go:188).

Wire (channel 0x40, proto oneof):
  1 block_request{1 height} | 2 no_block_response{1 height}
  | 3 block_response{1 block} | 4 status_request{} | 5 status_response{1 height, 2 base}
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..p2p.conn.mconnection import ChannelDescriptor
from ..p2p.router import Router
from ..types import BlockID
from ..types.block import Block
from ..types.part_set import BLOCK_PART_SIZE_BYTES, PartSet
from ..types.validation import verify_commit_light
from ..wire.proto import ProtoWriter, decode_message, field_bytes, field_int, to_signed64
from .replay import ReplayEngine


def _metrics():
    from ..libs.metrics import blocksync_metrics

    return blocksync_metrics()


def _pipeline_error():
    # lazy: blocksync importing ops.pipeline at module import would pull
    # jax into every node start; by the time a speculation future can
    # fail, the pipeline module is necessarily loaded already
    from ..ops.pipeline import DispatchError

    return DispatchError

BLOCKSYNC_CHANNEL = 0x40
BLOCKSYNC_DESC = ChannelDescriptor(
    id=BLOCKSYNC_CHANNEL, priority=5, recv_message_capacity=12 * 1024 * 1024
)

_REQUEST_WINDOW = 16  # concurrent height requesters (pool.go requesters)
_PEER_TIMEOUT = 15.0


def _enc(kind: int, fields: Optional[dict] = None) -> bytes:
    inner = ProtoWriter()
    for num, val in sorted((fields or {}).items()):
        if isinstance(val, bytes):
            inner.write_bytes(num, val)
        else:
            inner.write_varint(num, val)
    w = ProtoWriter()
    w.write_message(kind, inner.bytes(), always=True)
    return w.bytes()


@dataclass
class _PendingRequest:
    height: int
    peer_id: str = ""
    block: Optional[Block] = None
    requested_at: float = 0.0


class BlockPool:
    """pool.go:69-250 (condensed): window of in-flight height requests.

    `clock` is injected (defaults to the wall clock) so simnet-driven
    pools stay deterministic. Consumers register wake events via
    `waker()`; every event is set whenever pool state changes in a way
    the reactor loops care about (new block, new peer range, height
    advance, redo) — the loops block on their event instead of polling
    (each loop owns its event, so one loop's clear() can never swallow
    another's wake)."""

    def __init__(self, start_height: int, clock=None):
        self.height = start_height  # next height to apply
        self._requests: Dict[int, _PendingRequest] = {}
        self._peers: Dict[str, tuple] = {}  # peer_id -> (base, height)
        self._mtx = threading.RLock()
        self._clock = clock if clock is not None else time.time
        self._wakers: list = []

    def waker(self) -> threading.Event:
        ev = threading.Event()
        with self._mtx:
            self._wakers.append(ev)
        return ev

    def signal(self) -> None:
        with self._mtx:
            wakers = list(self._wakers)
        for ev in wakers:
            ev.set()

    def set_peer_range(self, peer_id: str, base: int, height: int) -> None:
        with self._mtx:
            self._peers[peer_id] = (base, height)
        self.signal()

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self._peers.pop(peer_id, None)
            for req in self._requests.values():
                if req.peer_id == peer_id and req.block is None:
                    req.peer_id = ""  # re-requestable
        self.signal()

    def max_peer_height(self) -> int:
        with self._mtx:
            return max((h for _, h in self._peers.values()), default=0)

    def is_caught_up(self) -> bool:
        """pool.go:188: caught up when at/above the best peer height."""
        with self._mtx:
            if not self._peers:
                return False
            return self.height >= self.max_peer_height()

    def next_requests(self) -> Dict[int, str]:
        """Heights to (re)request and the peer to ask."""
        out: Dict[int, str] = {}
        now = self._clock()
        with self._mtx:
            peers = [
                (pid, base, h) for pid, (base, h) in self._peers.items()
            ]
            if not peers:
                return out
            for height in range(self.height, self.height + _REQUEST_WINDOW):
                if height > self.max_peer_height():
                    break
                req = self._requests.get(height)
                if req is not None and req.block is not None:
                    continue
                if req is not None and req.peer_id and now - req.requested_at < _PEER_TIMEOUT:
                    continue
                candidates = [pid for pid, base, h in peers if base <= height <= h]
                if not candidates:
                    continue
                pid = candidates[height % len(candidates)]
                self._requests[height] = _PendingRequest(
                    height=height, peer_id=pid, requested_at=now
                )
                out[height] = pid
        return out

    def add_block(self, peer_id: str, block: Block) -> bool:
        with self._mtx:
            h = block.header.height
            req = self._requests.get(h)
            if req is None:
                if h < self.height:
                    return False
                self._requests[h] = _PendingRequest(height=h, peer_id=peer_id, block=block)
                self.signal()
                return True
            if req.block is not None:
                return False
            req.peer_id = peer_id
            req.block = block
        self.signal()
        return True

    def peek_two_blocks(self):
        """reactor.go:500-520: need (first, second) to verify first."""
        return self.peek_blocks_at(self.height)

    def peek_blocks_at(self, height: int):
        """(block at height, block at height+1) if both fetched — used by
        the pipelined pre-verification to look one block ahead."""
        with self._mtx:
            a = self._requests.get(height)
            b = self._requests.get(height + 1)
            return (
                a.block if a else None,
                b.block if b else None,
            )

    def peek_run(self, max_blocks: int):
        """The consecutive run of fetched blocks starting at the next
        apply height — the raw material for a replay range (ISSUE 14).
        Height h is only VERIFIABLE when block h+1 is also fetched, so a
        run of k blocks yields k-1 replayable heights."""
        out = []
        with self._mtx:
            h = self.height
            while len(out) < max_blocks:
                req = self._requests.get(h)
                if req is None or req.block is None:
                    break
                out.append(req.block)
                h += 1
        return out

    def pop_first(self) -> None:
        with self._mtx:
            self._requests.pop(self.height, None)
            self.height += 1
        self.signal()

    def redo_request(self, height: int) -> None:
        """Invalid block: drop both candidate blocks and re-request."""
        with self._mtx:
            for h in (height, height + 1):
                req = self._requests.pop(h, None)
                if req is not None and req.peer_id:
                    self._peers.pop(req.peer_id, None)
        self.signal()


class BlockSyncReactor:
    """reactor.go (blocksync): serve + consume block requests."""

    def __init__(
        self,
        router: Router,
        block_store,
        block_exec,
        initial_state,
        on_caught_up=None,
    ):
        self._router = router
        self._ch = router.open_channel(BLOCKSYNC_DESC)
        self._store = block_store
        self._block_exec = block_exec
        self._state = initial_state
        self._on_caught_up = on_caught_up
        self._pool = BlockPool(initial_state.last_block_height + 1)
        self._req_wake = self._pool.waker()
        self._apply_wake = self._pool.waker()
        self._engine = ReplayEngine()
        # idle wake counters per loop — the no-hot-spin guard: with no
        # work available the loops block on events, so these stay small
        self.loop_wakes = {"request": 0, "apply": 0, "status": 0}
        self._stopped = threading.Event()
        # serving (answering block/status requests) continues for the
        # node's lifetime; CONSUMING (requesting + applying) stops when
        # consensus takes over (node.go switchToConsensus)
        self._consuming = threading.Event()
        self._consuming.set()
        self._threads = []

    @property
    def pool(self) -> BlockPool:
        return self._pool

    @property
    def state(self):
        return self._state

    def start(self) -> None:
        for fn in (self._recv_loop, self._request_loop, self._apply_loop, self._status_loop):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stopped.set()
        self._pool.signal()  # unblock waiting loops
        # the replay engine (and its writer thread) is closed by
        # _apply_loop on its way out, never here: closing it mid-replay
        # would queue the writer's shutdown sentinel ahead of still-
        # arriving save_blocks (silently dropping them — state advanced
        # past the store) and leave the post-range drain() waiting on a
        # writer that already exited.

    def stop_consuming(self) -> None:
        """Stop requesting/applying blocks; keep serving peers."""
        self._consuming.clear()
        self._pool.signal()

    def reset_to_state(self, state) -> None:
        """Re-point the pool after statesync restored a later state —
        otherwise the pool would re-request (and re-apply) from genesis
        against an app that is already at the snapshot height."""
        self._state = state
        self._pool = BlockPool(state.last_block_height + 1)
        self._req_wake = self._pool.waker()
        self._apply_wake = self._pool.waker()

    # -- loops ----------------------------------------------------------

    def _status_loop(self) -> None:
        while not self._stopped.is_set():
            self.loop_wakes["status"] += 1
            self._ch.broadcast(_enc(4))  # status_request
            self._ch.broadcast(
                _enc(5, {1: self._store.height(), 2: self._store.base()})
            )
            # event-wait, not sleep: stop() returns immediately
            self._stopped.wait(1.0)

    def _request_loop(self) -> None:
        """Wake-driven (ISSUE 14, the PR-2/PR-3 busy-poll removal): the
        pool's wake event fires on new peer ranges, fetched blocks, and
        height advances — the three things that change next_requests().
        The timeout only re-arms the _PEER_TIMEOUT re-request scan."""
        while not self._stopped.is_set():
            # re-read every iteration: reset_to_state() swaps the pool
            # and mints fresh wake events — a cached local would leave
            # this loop waiting on an event the new pool never signals
            wake = self._req_wake
            if not self._consuming.is_set():
                wake.wait(timeout=1.0)
                wake.clear()
                continue
            self.loop_wakes["request"] += 1
            for height, peer_id in self._pool.next_requests().items():
                self._ch.send(peer_id, _enc(1, {1: height}))
            wake.wait(timeout=1.0)
            wake.clear()

    def _recv_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                env = self._ch.receive(timeout=0.5)
            except queue.Empty:
                continue
            try:
                self._handle(env)
            except (ValueError, KeyError):
                continue

    def _handle(self, env) -> None:
        f = decode_message(env.message)
        if 1 in f:  # block_request
            req = decode_message(field_bytes(f, 1))
            height = to_signed64(field_int(req, 1))
            block = self._store.load_block(height)
            if block is not None:
                self._ch.send(env.from_id, _enc(3, {1: block.encode()}))
            else:
                self._ch.send(env.from_id, _enc(2, {1: height}))
        elif 3 in f:  # block_response
            resp = decode_message(field_bytes(f, 3))
            block = Block.decode(field_bytes(resp, 1))
            self._pool.add_block(env.from_id, block)
        elif 4 in f:  # status_request
            self._ch.send(
                env.from_id, _enc(5, {1: self._store.height(), 2: self._store.base()})
            )
        elif 5 in f:  # status_response
            resp = decode_message(field_bytes(f, 5))
            self._pool.set_peer_range(
                env.from_id,
                to_signed64(field_int(resp, 2)),
                to_signed64(field_int(resp, 1)),
            )

    # minimum fetched run (blocks) before the range engine takes over
    # from the depth-1 speculative path — near the tip the classic path
    # wins (it overlaps ONE verify with the ABCI apply; a 2-3 block
    # "range" would just add planning overhead)
    _REPLAY_MIN_BLOCKS = 4

    def _apply_loop(self) -> None:
        """reactor.go:500-560: verify first with second's LastCommit, apply.

        Pipelined: while block h runs through ABCI apply (a host/process
        round trip), block h+1's commit verification batch is already
        in flight on the device via the shared AsyncBatchVerifier —
        speculation is keyed on the validator-set hash and discarded if
        the applied block changed the validators (SURVEY.md §7 hard-part
        4; the device analog of pool.go:127's fetch/verify overlap).

        Range mode (ISSUE 14): when the pool holds a run of ≥
        _REPLAY_MIN_BLOCKS consecutive fetched blocks — a node deep in
        catch-up — whole epoch ranges go through the ReplayEngine
        instead: one mesh superbatch per ~bucket of signatures at
        PRIORITY_REPLAY, store writes pipelined behind verification."""
        caught_up_reported = False
        spec = None  # (height, valset_hash, future) of a pre-verification
        while not self._stopped.is_set():
            # re-read every iteration (see _request_loop): reset_to_state
            # replaces the pool's wake events
            wake = self._apply_wake
            if not self._consuming.is_set():
                wake.wait(timeout=1.0)
                wake.clear()
                continue
            self.loop_wakes["apply"] += 1
            first, second = self._pool.peek_two_blocks()
            if first is None or second is None:
                if (
                    not caught_up_reported
                    and self._pool.is_caught_up()
                    and self._on_caught_up is not None
                ):
                    caught_up_reported = True
                    self._on_caught_up(self._state)
                wake.wait(timeout=0.5)
                wake.clear()
                continue
            run = self._pool.peek_run(self._engine.window + 1)
            if len(run) >= self._REPLAY_MIN_BLOCKS:
                if spec is not None:
                    # the range engine supersedes any pending depth-1
                    # speculation; count it as a discard
                    _metrics().speculation_discards.inc()
                    spec = None
                self._replay_run(run)
                continue
            parts = PartSet.from_data(first.encode(), BLOCK_PART_SIZE_BYTES)
            first_id = BlockID(hash=first.hash(), part_set_header=parts.header())
            ok = self._take_speculation(spec, first, first_id, second)
            spec = None
            if ok is None:  # no usable speculation: verify synchronously
                try:
                    # VerifyCommitLight on the device engine (reactor.go:533)
                    verify_commit_light(
                        self._state.chain_id,
                        self._state.validators,
                        first_id,
                        first.header.height,
                        second.last_commit,
                    )
                    ok = True
                except (ValueError, RuntimeError):
                    ok = False
            if not ok:
                self._pool.redo_request(first.header.height)
                continue
            # launch next block's verification before the ABCI apply so the
            # device works while the app executes transactions
            spec = self._speculate_next(first.header.height)
            self._store.save_block(first, parts, second.last_commit)
            self._state = self._block_exec.apply_block(self._state, first_id, first)
            self._pool.pop_first()
        # this loop owns the engine: only close it after the last
        # replay_blocks has returned (and drained its writer) — see stop()
        self._engine.close()

    def _replay_run(self, run) -> None:
        """Hand a consecutive fetched run to the ReplayEngine: range
        verification through the dispatcher, store writes on the writer
        thread, applies inline on this thread. The engine stops at epoch
        cuts / window edges; the loop simply re-peeks and continues."""
        eng = self._engine
        before = (eng.ranges, eng.fallback_ranges)

        def _apply(block_id, block):
            self._state = self._block_exec.apply_block(
                self._state, block_id, block
            )
            return self._state

        def _applied(_height: int) -> None:
            self._pool.pop_first()

        state, out = eng.replay_blocks(
            self._state,
            run,
            save=self._store.save_block,
            apply=_apply,
            applied=_applied,
            should_stop=lambda: (
                self._stopped.is_set() or not self._consuming.is_set()
            ),
        )
        self._state = state
        m = _metrics()
        if out.range_heights:
            m.replay_heights.inc(out.range_heights)
        if out.sequential_heights:
            m.replay_fallback_heights.inc(out.sequential_heights)
        if eng.ranges > before[0]:
            m.replay_ranges.inc(eng.ranges - before[0])
        if eng.fallback_ranges > before[1]:
            m.replay_fallback_ranges.inc(eng.fallback_ranges - before[1])
        if out.failed_height is not None:
            # identical to the sequential path's rejection: drop the bad
            # block (and its successor carrying the commit) and re-request
            self._pool.redo_request(out.failed_height)

    def _speculate_next(self, applied_height: int):
        """Pre-submit verification of the next pending block's commit,
        assuming the validator set does not change at applied_height."""
        from ..ops import backend as _backend
        from ..ops import pipeline as _pipeline

        nxt, after = self._pool.peek_blocks_at(applied_height + 1)
        if nxt is None or after is None:
            return None
        vals = self._state.validators
        try:
            needed = vals.total_voting_power() * 2 // 3
            entries, _ = _pipeline.commit_entries(
                self._state.chain_id, vals, after.last_commit, needed
            )
        except (ValueError, RuntimeError, IndexError):
            return None
        if len(entries) < _backend.DEVICE_THRESHOLD:
            return None  # small batches: sync path is cheaper than a round trip
        fut = _pipeline.shared_verifier().submit(entries)
        return (nxt.header.height, vals, vals.hash(), nxt.hash(), after.hash(), fut)

    def _take_speculation(self, spec, first, first_id, second):
        """Return True/False if the speculation covers (first, second) with
        the current validator set, else None (caller verifies sync).

        Metric semantics (ISSUE 14): a HIT is a usable device verdict
        (either way — a confirmed-bad commit is still a useful answer); a
        DISCARD is a speculation invalidated before use (height/valset/
        hash mismatch, dispatch error, device timeout); a MISS is having
        no speculation at all when one was needed."""
        m = _metrics()
        if spec is None:
            m.speculation_misses.inc()
            return None
        height, spec_vals, valhash, fhash, shash, fut = spec
        cur_vals = self._state.validators
        if height != first.header.height:
            m.speculation_discards.inc()
            return None
        # identity first: the common no-valset-change case skips a full
        # Merkle rehash of the set on every applied block
        if spec_vals is not cur_vals and valhash != cur_vals.hash():
            m.speculation_discards.inc()
            return None
        if fhash != first_id.hash or shash != second.hash():
            m.speculation_discards.inc()
            return None
        try:
            valid = fut.result(timeout=300)
        except (_pipeline_error(), FutureTimeoutError):
            # device trouble is recoverable — fall back to the sync
            # verify. Anything else (a bug, not an outcome) propagates:
            # silently re-verifying would mask it forever.
            m.speculation_discards.inc()
            return None
        m.speculation_hits.inc()
        if not bool(valid.all()):
            return False
        # structural checks the speculative path skipped
        try:
            from ..types.validation import _verify_basic_vals_and_commit

            _verify_basic_vals_and_commit(
                self._state.validators, second.last_commit,
                first.header.height, first_id,
            )
        except (ValueError, RuntimeError):
            return False
        return True
