"""Chain-replay catch-up engine (ISSUE 14, ROADMAP item 3).

Turns blocksync from verify-one-ahead into a pipelined range verifier:
up to TM_TPU_REPLAY_WINDOW (default 64) fetched heights are decoded
ahead of apply, grouped by valset epoch — the window is cut at any
height whose header carries a different validators_hash, the range-wide
form of `_take_speculation`'s valhash check — and whole ranges are
packed into mesh superbatches through the shared AsyncBatchVerifier at
PRIORITY_REPLAY (below consensus, above ingress: the PR-12 preemption
points keep a rejoining node's flood from ever delaying live commits).
BlockStore.save_block writes ride a writer thread BEHIND device
verification so storage latency hides under the next range's relay.

Failure semantics are byte-identical to the sequential path: a bad
commit anywhere in a range falls back to per-height sequential
`verify_commit_light` for that range, so the rejected height's error
string matches the one-at-a-time path exactly.

The engine is deliberately transport-free: it consumes an ordered run
of consecutive fetched blocks plus save/apply callbacks, so the
BlockSyncReactor (live catch-up), bench.py blocksync (100k-height
replay) and the simnet rejoin scenario all drive the same code.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, List, Optional

from ..observability import trace as _trace
from ..types import BlockID
from ..types.block import Block
from ..types.part_set import BLOCK_PART_SIZE_BYTES, PartSet
from ..types.validation import (
    PrepareUnsupported,
    prepare_commit_range,
    verify_commit_light,
)

_span = _trace.span

DEFAULT_WINDOW = 64


def replay_window() -> int:
    """TM_TPU_INGRESS_REPLAY_WINDOW: max heights decoded ahead of apply
    (legacy TM_TPU_REPLAY_WINDOW honored with a DeprecationWarning)."""
    from ..ops import ingress as _fabric

    v = _fabric.env_setting("TM_TPU_INGRESS_REPLAY_WINDOW",
                            "TM_TPU_REPLAY_WINDOW")
    try:
        return max(int(v), 1)
    except (TypeError, ValueError):
        return DEFAULT_WINDOW


def plan_epoch_range(blocks: List[Block], limit: int) -> int:
    """How many of the verifiable heights at the head of `blocks` share
    the FIRST block's validators_hash — the epoch cut. `blocks` holds
    consecutive fetched blocks [h0 .. h0+k]; height h is verifiable when
    block h+1 (carrying h's commit) is present, so at most len-1 heights
    are plannable. A mismatching hash at block i means applying block
    i-1 changes the validator set: the range ends there and the next
    range starts under the post-apply set.

    A block whose header announces a valset change via
    next_validators_hash also ends the range after its height: applying
    it installs a new set, so later heights cannot share this range's
    verification key material.

    Header hashes are a grouping HEURISTIC only — verification authority
    stays with the applied state's validator set. A chain that lies
    about its hashes can at worst form a range whose commits verify
    under stale keys; the apply step then rejects the block under the
    live valset and the engine falls back to the sequential path (same
    errors, same rejection — see _apply_verified)."""
    n = min(len(blocks) - 1, limit)
    if n <= 0:
        return 0
    first = bytes(blocks[0].header.validators_hash)
    cut = 1
    while cut < n:
        if bytes(blocks[cut].header.validators_hash) != first:
            break
        nxt = bytes(blocks[cut - 1].header.next_validators_hash)
        if nxt and nxt != first:
            break
        cut += 1
    return cut


class ReplayOutcome:
    """Result of one replay_blocks() call."""

    __slots__ = ("applied", "failed_height", "error", "range_heights",
                 "sequential_heights")

    def __init__(self) -> None:
        self.applied = 0                 # heights saved + applied
        self.failed_height: Optional[int] = None
        self.error: Optional[str] = None
        self.range_heights = 0           # verified via a device range
        self.sequential_heights = 0      # verified per-height (fallback,
        #                                  sub-threshold, or tiny range)

    def __repr__(self) -> str:  # debugging aid
        return (
            f"ReplayOutcome(applied={self.applied}, "
            f"failed_height={self.failed_height}, error={self.error!r})"
        )


class _ApplyRejected(Exception):
    """apply() rejected a verified block (InvalidBlockError, a
    ValueError): wrapped so the range/sequential drivers can tell an
    apply rejection (fall back / surface failed_height) apart from a
    save failure (propagate — the store diverged, abort catch-up)."""


class _Writer:
    """Ordered store-write pipeline: save_block (which enforces strictly
    sequential heights itself) runs on this thread while the caller is
    already applying the next height / waiting on the next range's
    relay. The first error poisons the writer; drain() re-raises it on
    the replay thread so a failed save aborts catch-up instead of
    silently diverging store from state."""

    def __init__(self, depth: int = 128):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._err: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="replay-writer", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                if self._err is None:
                    save, args = item
                    try:
                        save(*args)
                    except BaseException as e:  # noqa: BLE001 — via drain()
                        self._err = e
            finally:
                self._q.task_done()

    def put(self, save: Callable, block, parts, seen_commit) -> None:
        if self._closed:
            # the sentinel is already queued: a save enqueued behind it
            # would never run (state advanced past the store on disk)
            raise RuntimeError("replay writer closed")
        if self._err is not None:
            raise RuntimeError("replay writer failed") from self._err
        self._q.put((save, (block, parts, seen_commit)))

    def drain(self) -> None:
        """Block until every queued save has run; raise the first error.
        Never hangs on a writer thread that already exited — a dead
        writer with queued saves is an error, not a deadlock."""
        q = self._q
        with q.all_tasks_done:
            while q.unfinished_tasks:
                if not self._thread.is_alive():
                    break
                q.all_tasks_done.wait(0.05)
        if self._err is not None:
            raise RuntimeError("replay writer failed") from self._err
        if q.unfinished_tasks:
            raise RuntimeError("replay writer exited with pending saves")

    def close(self, timeout: float = 10.0) -> None:
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout=timeout)


class ReplayEngine:
    """Range-batched catch-up verifier over the shared verify pipeline.

    replay_blocks(state, blocks, save, apply) verifies and applies as
    many consecutive heights as the window/epoch cuts allow, pipelining
    device verification of later range chunks behind the apply of
    earlier ones and store writes behind both. `synchronous=True` runs
    saves inline (no writer thread) — the simnet rejoin scenario uses it
    so a run stays a pure function of its seed."""

    def __init__(self, window: Optional[int] = None,
                 synchronous: bool = False,
                 verifier=None, result_timeout: float = 600.0):
        from ..ops import ingress as _fabric

        self._window = int(window) if window else replay_window()
        self._synchronous = bool(synchronous)
        self._verifier = verifier  # injected for tests; default shared
        self._timeout = float(result_timeout)
        self._writer: Optional[_Writer] = None
        # the `replay` lane: fused range chunks ride the shared fabric
        # at REPLAY priority (stepped — chunk cuts are data-dependent,
        # the scheduler never flushes for us: replay stays deterministic)
        self._lane = _fabric.shared_engine().register(_fabric.LaneSpec(
            name="replay",
            priority=_fabric.PRIORITY_REPLAY,
            stepped=True,
            closed_msg="replay engine is closed",
            verifier=verifier,
        ))
        # cumulative stats (deterministic: counts derive only from the
        # replayed chain, not from timing)
        self.ranges = 0
        self.range_heights = 0
        self.sequential_heights = 0
        self.fallback_ranges = 0
        self.sigs_submitted = 0
        self.heights_applied = 0

    # -- plumbing --------------------------------------------------------

    @staticmethod
    def _group_cap() -> int:
        from ..ops import backend as _backend

        return _backend.max_coalesce()

    @staticmethod
    def _device_threshold() -> int:
        from ..ops import backend as _backend

        return _backend.DEVICE_THRESHOLD

    @property
    def window(self) -> int:
        return self._window

    def stats(self) -> dict:
        total = self.range_heights + self.sequential_heights
        return {
            "ranges": self.ranges,
            "fallback_ranges": self.fallback_ranges,
            "range_heights": self.range_heights,
            "sequential_heights": self.sequential_heights,
            "heights_applied": self.heights_applied,
            "sigs_submitted": self.sigs_submitted,
            "hit_rate": (self.range_heights / total) if total else 0.0,
            "window": self._window,
        }

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        self._lane.close(timeout=0.0)

    # -- the range verifier ---------------------------------------------

    def replay_blocks(self, state, blocks: List[Block], save: Callable,
                      apply: Callable, applied: Optional[Callable] = None,
                      should_stop: Optional[Callable] = None):
        """Verify + apply consecutive heights from `blocks` (an ordered
        run [h0, h0+1, ...] with h0 == the next height to apply under
        `state`). Returns (new_state, ReplayOutcome).

        save(block, parts, seen_commit)   -> None   (BlockStore.save_block)
        apply(block_id, block)            -> state  (BlockExecutor.apply_block)
        applied(height)                   -> None   (e.g. pool.pop_first)
        should_stop()                     -> bool   (abort between chunks)
        """
        out = ReplayOutcome()
        if len(blocks) < 2:
            return state, out
        h0 = blocks[0].header.height
        for i, b in enumerate(blocks):  # the run must be consecutive
            if b.header.height != h0 + i:
                raise ValueError("replay_blocks requires consecutive heights")
        cut = plan_epoch_range(blocks, self._window)
        if cut <= 0:
            return state, out
        fid = _trace.next_flow() if _trace.TRACER.enabled else None
        if fid is not None:
            _trace.TRACER.flow_point(
                "blocksync.fetch", fid, "s", h0=h0, n=cut
            )
        state = self._replay_range(
            state, blocks[: cut + 1], save, apply, applied, should_stop,
            out, fid,
        )
        if fid is not None:
            _trace.TRACER.flow_point(
                "replay.apply", fid, "f", applied=out.applied
            )
        if self._writer is not None:
            self._writer.drain()
        return state, out

    def _replay_range(self, state, blocks, save, apply, applied,
                      should_stop, out: ReplayOutcome, fid) -> object:
        """One epoch range: blocks[0..n] covering heights h0..h0+n-1."""
        from ..ops.pipeline import DispatchError
        from concurrent.futures import TimeoutError as _FutTimeout

        chain_id = state.chain_id
        vals = state.validators
        n = len(blocks) - 1
        self.ranges += 1
        # decode once per height: part sets + block ids are needed by
        # both verification (block_id binds the commit) and save
        with _span("replay.range_pack", flow=fid, flow_phase="t",
                   h0=blocks[0].header.height, heights=n):
            parts = [
                PartSet.from_data(b.encode(), BLOCK_PART_SIZE_BYTES)
                for b in blocks[:n]
            ]
            ids = [
                BlockID(hash=b.hash(), part_set_header=p.header())
                for b, p in zip(blocks[:n], parts)
            ]
            items = [
                (blocks[i].header.height, ids[i], blocks[i + 1].last_commit)
                for i in range(n)
            ]
            try:
                prepared, synced = prepare_commit_range(
                    chain_id, vals, items
                )
            except (PrepareUnsupported, ValueError, RuntimeError,
                    IndexError):
                prepared, synced = None, None
        if prepared is None:
            # host-side prepare failed somewhere in the range: the
            # sequential path reproduces the exact error for the
            # offending height (and verifies the earlier ones normally)
            self.fallback_ranges += 1
            return self._apply_sequential(
                state, blocks, parts, ids, 0, n, save, apply, applied,
                should_stop, out,
            )
        synced_set = set(synced)
        total_sigs = sum(len(e) for _, e, _ in prepared)
        if total_sigs and total_sigs < self._device_threshold():
            # a tiny range (rare: right before an epoch cut) is cheaper
            # on the host path than a device round trip
            return self._apply_sequential(
                state, blocks, parts, ids, 0, n, save, apply, applied,
                should_stop, out,
            )
        # pack prepared heights into device chunks of up to ~max_coalesce
        # signatures through the fabric's BlockFuser; every chunk is ONE
        # lane submit (the pipeline launches a full bucket per chunk
        # instead of one launch per height)
        from ..ops import ingress as _fabric

        chunks = []  # (future, [((height, conclude), off, len)])

        def _chunk_done(fut, spans) -> None:
            self.sigs_submitted += spans[-1][1] + spans[-1][2]
            chunks.append((fut, spans))

        fuser = _fabric.BlockFuser(self._lane, self._group_cap(),
                                   _chunk_done, flow=fid)
        for height, entries, conclude in prepared:
            fuser.add((height, conclude), entries)
        fuser.flush()

        # resolve chunks in order, applying each chunk's heights while
        # later chunks are still in flight on the device
        verdicts = {}  # height -> conclude() ran clean
        for fut, spans in chunks:
            try:
                valid = fut.result(timeout=self._timeout)
            except (DispatchError, _FutTimeout):
                # device trouble, not a bad chain: everything not yet
                # applied in this range falls back to sequential
                self.fallback_ranges += 1
                return self._apply_sequential(
                    state, blocks, parts, ids,
                    self._range_resume(blocks, state), n,
                    save, apply, applied, should_stop, out,
                )
            for (height, conclude), off, ln in spans:
                try:
                    conclude(valid[off : off + ln])
                except (ValueError, RuntimeError):
                    # bad commit mid-range: per-height sequential
                    # verification for the REST of the range reproduces
                    # the sequential path's exact error string
                    self.fallback_ranges += 1
                    return self._apply_sequential(
                        state, blocks, parts, ids,
                        self._range_resume(blocks, state), n,
                        save, apply, applied, should_stop, out,
                    )
                verdicts[height] = True
            # apply the verified prefix of this chunk
            state, fallback = self._apply_verified(
                state, blocks, parts, ids, verdicts, synced_set, n,
                save, apply, applied, out,
            )
            if fallback:
                # apply rejected a range-verified block: the headers lied
                # about their valset epoch. Re-verify the rest under the
                # LIVE post-apply set — the sequential path's authority —
                # which reproduces its exact rejection for that height.
                self.fallback_ranges += 1
                return self._apply_sequential(
                    state, blocks, parts, ids,
                    self._range_resume(blocks, state), n,
                    save, apply, applied, should_stop, out,
                )
            if should_stop is not None and should_stop():
                return state
        # heights verified sub-threshold (synced) interleave with device
        # heights; a trailing run of them may remain unapplied
        state, fallback = self._apply_verified(
            state, blocks, parts, ids, verdicts, synced_set, n,
            save, apply, applied, out,
        )
        if fallback:
            self.fallback_ranges += 1
            return self._apply_sequential(
                state, blocks, parts, ids,
                self._range_resume(blocks, state), n,
                save, apply, applied, should_stop, out,
            )
        return state

    def _range_resume(self, blocks, state) -> int:
        """Index into the range where sequential fallback resumes: the
        first height not yet applied under `state`."""
        return int(
            state.last_block_height - blocks[0].header.height + 1
        )

    def _apply_verified(self, state, blocks, parts, ids, verdicts,
                        synced_set, n, save, apply, applied,
                        out: ReplayOutcome):
        """Apply the contiguous verified prefix starting at the first
        unapplied height. Returns (state, fallback_needed).

        Commit verification in this range ran under the valset the FIRST
        header claimed; that is a grouping heuristic, not authority. A
        chain forged with stale valset keys passes device verification
        but is rejected here by apply (InvalidBlockError, a ValueError)
        under the live state — in that case nothing is saved (the save
        is only enqueued after apply succeeds) and fallback_needed=True
        sends the caller to _apply_sequential, which re-verifies under
        the live post-apply set and surfaces the sequential path's exact
        failed_height/error for redo_request."""
        i = self._range_resume(blocks, state)
        while i < n:
            h = blocks[i].header.height
            if h in synced_set:
                via_range = False
            elif verdicts.get(h):
                via_range = True
            else:
                break  # later chunk still in flight
            try:
                state = self._save_and_apply(
                    state, blocks[i], parts[i], ids[i],
                    blocks[i + 1].last_commit, save, apply, applied, out,
                )
            except _ApplyRejected:
                return state, True
            if via_range:
                out.range_heights += 1
                self.range_heights += 1
            else:
                out.sequential_heights += 1
                self.sequential_heights += 1
            i += 1
        return state, False

    def _apply_sequential(self, state, blocks, parts, ids, start, n,
                          save, apply, applied, should_stop,
                          out: ReplayOutcome):
        """Per-height sequential verification for heights [start, n) —
        the byte-identical fallback. Stops at the first bad height,
        recording its exact sequential-path error."""
        i = max(self._range_resume(blocks, state), start)
        while i < n:
            if should_stop is not None and should_stop():
                return state
            h = blocks[i].header.height
            try:
                with _span("replay.sequential", height=h):
                    verify_commit_light(
                        state.chain_id, state.validators, ids[i],
                        h, blocks[i + 1].last_commit,
                    )
            except (ValueError, RuntimeError) as e:
                out.failed_height = h
                out.error = str(e)
                return state
            try:
                state = self._save_and_apply(
                    state, blocks[i], parts[i], ids[i],
                    blocks[i + 1].last_commit, save, apply, applied, out,
                )
            except _ApplyRejected as e:
                # commit verified but apply rejected the block body
                # (InvalidBlockError): surface it like a verification
                # failure so the reactor redo_requests instead of the
                # apply thread dying with the block half-persisted
                out.failed_height = h
                out.error = str(e)
                return state
            out.sequential_heights += 1
            self.sequential_heights += 1
            i += 1
        return state

    def _save_and_apply(self, state, block, parts, block_id, seen_commit,
                        save, apply, applied, out: ReplayOutcome):
        """Apply FIRST, save after: apply is the authority (it re-checks
        the block under live state), so a block it rejects must never
        reach the store — a persisted-but-invalid block would wedge the
        node on restart. Saves still pipeline: height h's store write
        runs on the writer thread while h+1 applies."""
        try:
            state = apply(block_id, block)
        except ValueError as e:
            raise _ApplyRejected(str(e)) from e
        if self._synchronous:
            save(block, parts, seen_commit)
        else:
            if self._writer is None:
                self._writer = _Writer()
            self._writer.put(save, block, parts, seen_commit)
        out.applied += 1
        self.heights_applied += 1
        if applied is not None:
            applied(block.header.height)
        return state
