// tm_native — native host-side hot paths for the TPU verification engine.
//
// The framework's compute path is JAX/XLA on the device; this module is the
// native runtime seam around it (SURVEY.md §2: the batch verification
// engine's host half): the per-batch packing that turns 10k signature
// triples into kernel input arrays, and RFC-6962 merkle hashing for part
// sets / block data. CPython C API (no pybind11 in this image), built by
// native/build.py via setuptools.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <cstdint>
#include <cstring>
#include <sched.h>
#include <stdlib.h>
#include <thread>
#include <vector>

// --------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), self-contained.

namespace sha256 {

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

struct Ctx {
  uint32_t h[8];
  uint64_t len;
  uint8_t buf[64];
  size_t buflen;
};

static void init(Ctx *c) {
  static const uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  memcpy(c->h, iv, sizeof(iv));
  c->len = 0;
  c->buflen = 0;
}

static void compress(Ctx *c, const uint8_t *p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void update(Ctx *c, const uint8_t *data, size_t n) {
  c->len += n;
  if (c->buflen) {
    size_t take = 64 - c->buflen;
    if (take > n) take = n;
    memcpy(c->buf + c->buflen, data, take);
    c->buflen += take;
    data += take;
    n -= take;
    if (c->buflen == 64) {
      compress(c, c->buf);
      c->buflen = 0;
    }
  }
  while (n >= 64) {
    compress(c, data);
    data += 64;
    n -= 64;
  }
  if (n) {
    memcpy(c->buf, data, n);
    c->buflen = n;
  }
}

static void final(Ctx *c, uint8_t out[32]) {
  uint64_t bitlen = c->len * 8;
  uint8_t pad = 0x80;
  update(c, &pad, 1);
  uint8_t z = 0;
  while (c->buflen != 56) update(c, &z, 1);
  uint8_t lenb[8];
  for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bitlen >> (56 - 8 * i));
  update(c, lenb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(c->h[i] >> 24);
    out[4 * i + 1] = uint8_t(c->h[i] >> 16);
    out[4 * i + 2] = uint8_t(c->h[i] >> 8);
    out[4 * i + 3] = uint8_t(c->h[i]);
  }
}

static void digest(const uint8_t *data, size_t n, uint8_t out[32]) {
  Ctx c;
  init(&c);
  update(&c, data, n);
  final(&c, out);
}

}  // namespace sha256

// --------------------------------------------------------------------------
// SHA-512 (FIPS 180-4) + reduction mod the ed25519 group order L — the
// host half of the batch challenge k = SHA512(R||A||M) mod L
// (crypto/ed25519/ed25519.go verification; ops/pallas_verify.py
// prepare_compact). One C call replaces a per-signature Python loop that
// measured ~50% of end-to-end batch time on a loaded host.

namespace sha512 {

static const uint64_t K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

struct Ctx {
  uint64_t h[8];
  uint8_t buf[128];
  size_t buflen;
  uint64_t total;  // bytes
};

static void init(Ctx *c) {
  static const uint64_t H0[8] = {
      0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
      0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
      0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  memcpy(c->h, H0, sizeof H0);
  c->buflen = 0;
  c->total = 0;
}

static void compress(Ctx *c, const uint8_t *p) {
  uint64_t w[80];
  for (int i = 0; i < 16; i++) {
    w[i] = 0;
    for (int b = 0; b < 8; b++) w[i] = (w[i] << 8) | p[8 * i + b];
  }
  for (int i = 16; i < 80; i++) {
    uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 80; i++) {
    uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = h + S1 + ch + K[i] + w[i];
    uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
    uint64_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint64_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void update(Ctx *c, const uint8_t *data, size_t n) {
  c->total += n;
  if (c->buflen) {
    size_t take = 128 - c->buflen;
    if (take > n) take = n;
    memcpy(c->buf + c->buflen, data, take);
    c->buflen += take;
    data += take;
    n -= take;
    if (c->buflen == 128) {
      compress(c, c->buf);
      c->buflen = 0;
    }
  }
  while (n >= 128) {
    compress(c, data);
    data += 128;
    n -= 128;
  }
  if (n) {
    memcpy(c->buf, data, n);
    c->buflen = n;
  }
}

static void final(Ctx *c, uint8_t out[64]) {
  uint64_t bits = c->total * 8;
  uint8_t pad = 0x80;
  update(c, &pad, 1);
  uint8_t z = 0;
  while (c->buflen != 112) update(c, &z, 1);
  uint8_t len[16] = {0};
  for (int i = 0; i < 8; i++) len[15 - i] = uint8_t(bits >> (8 * i));
  // counter only tracks real input; neutralize padding's contribution
  c->total = 0;
  update(c, len, 16);
  for (int i = 0; i < 8; i++)
    for (int b = 0; b < 8; b++) out[8 * i + b] = uint8_t(c->h[i] >> (56 - 8 * b));
}

// k = digest (64B little-endian integer) mod L, L = 2^252 + C,
// C = 27742317777372353535851937790883648493. Since 2^252 ≡ -C (mod L),
// each fold rewrites x = hi*2^252 + lo as lo + K_r - hi*C where K_r is a
// precomputed multiple of L large enough to keep the result positive
// (K1 = L<<133, K2 = L<<7, K3 = L; sizes 512 -> 386 -> 260 -> 254 bits),
// then conditionally subtracts L (at most 3 times; x3 < 2^254 < 4L).
static const uint64_t C_LO = 0x5812631a5cf5d3edULL;
static const uint64_t C_HI = 0x14def9dea2f79cd6ULL;  // C = C_HI<<64 | C_LO
static const uint64_t L_LIMBS[4] = {C_LO, C_HI, 0, 0x1000000000000000ULL};
static const uint64_t FOLD_K[3][7] = {
    {0x0000000000000000ULL, 0x0000000000000000ULL, 0x024c634b9eba7da0ULL,
     0x9bdf3bd45ef39acbULL, 0x0000000000000002ULL, 0x0000000000000000ULL,
     0x0000000000000002ULL},
    {0x09318d2e7ae9f680ULL, 0x6f7cef517bce6b2cULL, 0x000000000000000aULL,
     0x0000000000000000ULL, 0x0000000000000008ULL, 0x0000000000000000ULL,
     0x0000000000000000ULL},
    {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0x0000000000000000ULL,
     0x1000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL,
     0x0000000000000000ULL}};

static void mod_l(const uint8_t digest[64], uint8_t out[32]) {
  // x: 8 limbs LE; every intermediate fits in 7 limbs after round 1
  uint64_t x[8] = {0};
  for (int i = 0; i < 8; i++)
    for (int b = 0; b < 8; b++) x[i] |= uint64_t(digest[8 * i + b]) << (8 * b);
  for (int round = 0; round < 3; round++) {
    // hi = x >> 252 (up to 5 limbs), lo = x & (2^252 - 1)
    uint64_t hi[5];
    for (int i = 0; i < 5; i++) {
      uint64_t v = (i + 3 < 8) ? (x[i + 3] >> 60) : 0;
      if (i + 4 < 8) v |= x[i + 4] << 4;
      hi[i] = v;
    }
    uint64_t lo[4] = {x[0], x[1], x[2], x[3] & 0x0fffffffffffffffULL};
    // t = hi * C (7 limbs)
    uint64_t t[7];
    unsigned __int128 carry = 0;
    for (int i = 0; i < 7; i++) {
      unsigned __int128 acc = carry;
      if (i < 5) acc += (unsigned __int128)hi[i] * C_LO;
      if (i >= 1 && i <= 5) acc += (unsigned __int128)hi[i - 1] * C_HI;
      t[i] = uint64_t(acc);
      carry = acc >> 64;
    }
    // x = lo + K_round - t  (guaranteed non-negative)
    memset(x, 0, sizeof x);
    unsigned __int128 acc2 = 0;
    uint64_t borrow = 0;
    for (int i = 0; i < 7; i++) {
      acc2 += (i < 4 ? lo[i] : 0);
      acc2 += FOLD_K[round][i];
      uint64_t add = uint64_t(acc2);
      unsigned __int128 d = (unsigned __int128)add - t[i] - borrow;
      x[i] = uint64_t(d);
      borrow = (uint64_t)(d >> 64) ? 1 : 0;
      acc2 >>= 64;
    }
  }
  // now x < 2^254 < 4L: subtract L while x >= L
  for (int rep = 0; rep < 3; rep++) {
    bool ge = true;
    for (int i = 3; i >= 0; i--) {
      if (x[i] > L_LIMBS[i]) break;
      if (x[i] < L_LIMBS[i]) { ge = false; break; }
    }
    if (!ge) break;
    uint64_t borrow = 0;
    for (int i = 0; i < 4; i++) {
      unsigned __int128 d = (unsigned __int128)x[i] - L_LIMBS[i] - borrow;
      x[i] = uint64_t(d);
      borrow = (uint64_t)(d >> 64) ? 1 : 0;
    }
  }
  for (int i = 0; i < 4; i++)
    for (int b = 0; b < 8; b++) out[8 * i + b] = uint8_t(x[i] >> (8 * b));
}

}  // namespace sha512

// --------------------------------------------------------------------------
// RFC-6962 merkle (crypto/merkle/tree.go semantics)

static void leaf_hash(const uint8_t *data, size_t n, uint8_t out[32]) {
  sha256::Ctx c;
  sha256::init(&c);
  uint8_t prefix = 0x00;
  sha256::update(&c, &prefix, 1);
  sha256::update(&c, data, n);
  sha256::final(&c, out);
}

static void inner_hash(const uint8_t *l, const uint8_t *r, uint8_t out[32]) {
  sha256::Ctx c;
  sha256::init(&c);
  uint8_t prefix = 0x01;
  sha256::update(&c, &prefix, 1);
  sha256::update(&c, l, 32);
  sha256::update(&c, r, 32);
  sha256::final(&c, out);
}

static size_t split_point(size_t n) {
  size_t k = 1;
  while (k * 2 < n) k *= 2;
  return k;
}

static void merkle_root_hashes(std::vector<uint8_t> &hashes, size_t lo,
                               size_t hi, uint8_t out[32]) {
  size_t n = hi - lo;
  if (n == 1) {
    memcpy(out, &hashes[32 * lo], 32);
    return;
  }
  size_t k = split_point(n);
  uint8_t left[32], right[32];
  merkle_root_hashes(hashes, lo, lo + k, left);
  merkle_root_hashes(hashes, lo + k, hi, right);
  inner_hash(left, right, out);
}

// merkle_root(items: list[bytes]) -> bytes
static PyObject *py_merkle_root(PyObject *, PyObject *args) {
  PyObject *items;
  if (!PyArg_ParseTuple(args, "O", &items)) return nullptr;
  PyObject *seq = PySequence_Fast(items, "expected a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  uint8_t out[32];
  if (n == 0) {
    sha256::digest(nullptr, 0, out);
    Py_DECREF(seq);
    return PyBytes_FromStringAndSize((const char *)out, 32);
  }
  std::vector<uint8_t> hashes(size_t(n) * 32);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(item, &buf, &len) < 0) {
      Py_DECREF(seq);
      return nullptr;
    }
    leaf_hash((const uint8_t *)buf, size_t(len), &hashes[32 * size_t(i)]);
  }
  Py_DECREF(seq);
  merkle_root_hashes(hashes, 0, size_t(n), out);
  return PyBytes_FromStringAndSize((const char *)out, 32);
}

// sha256_many(items: list[bytes]) -> bytes (concatenated 32B digests)
static PyObject *py_sha256_many(PyObject *, PyObject *args) {
  PyObject *items;
  if (!PyArg_ParseTuple(args, "O", &items)) return nullptr;
  PyObject *seq = PySequence_Fast(items, "expected a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 32);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t *op = (uint8_t *)PyBytes_AS_STRING(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(item, &buf, &len) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    sha256::digest((const uint8_t *)buf, size_t(len), op + 32 * i);
  }
  Py_DECREF(seq);
  return out;
}

// pack_le_limbs(encodings: bytes (n*32), n: int) -> bytes (n*20 int32 LE)
// Low 255 bits of each 32-byte little-endian encoding into 20 radix-2^13
// limbs — the fe.py input format (ops/backend.py _pack_le_limbs).
static PyObject *py_pack_le_limbs(PyObject *, PyObject *args) {
  Py_buffer view;
  Py_ssize_t n;
  if (!PyArg_ParseTuple(args, "y*n", &view, &n)) return nullptr;
  if (view.len < n * 32) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "buffer too small");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 20 * 4);
  if (!out) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  int32_t *op = (int32_t *)PyBytes_AS_STRING(out);
  const uint8_t *ip = (const uint8_t *)view.buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    const uint8_t *enc = ip + 32 * i;
    // 255-bit value as four 64-bit words (top bit cleared)
    uint64_t w[4];
    for (int j = 0; j < 4; j++) {
      w[j] = 0;
      for (int b = 0; b < 8; b++) w[j] |= uint64_t(enc[8 * j + b]) << (8 * b);
    }
    w[3] &= 0x7fffffffffffffffULL;
    for (int limb = 0; limb < 20; limb++) {
      int bit = limb * 13;
      int word = bit >> 6, off = bit & 63;
      uint64_t v = w[word] >> off;
      if (off > 64 - 13 && word < 3) v |= w[word + 1] << (64 - off);
      op[20 * i + limb] = int32_t(v & 0x1fff);
    }
  }
  PyBuffer_Release(&view);
  return out;
}

// pack_bits_le(scalars: bytes (n*32), n: int, nbits: int)
//   -> bytes (nbits * n int32 LE), transposed for the ladder.
static PyObject *py_pack_bits_le(PyObject *, PyObject *args) {
  Py_buffer view;
  Py_ssize_t n;
  int nbits;
  if (!PyArg_ParseTuple(args, "y*ni", &view, &n, &nbits)) return nullptr;
  if (view.len < n * 32 || nbits > 256) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "bad buffer/nbits");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)nbits * n * 4);
  if (!out) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  int32_t *op = (int32_t *)PyBytes_AS_STRING(out);
  const uint8_t *ip = (const uint8_t *)view.buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    const uint8_t *s = ip + 32 * i;
    for (int b = 0; b < nbits; b++) {
      op[(Py_ssize_t)b * n + i] = (s[b >> 3] >> (b & 7)) & 1;
    }
  }
  PyBuffer_Release(&view);
  return out;
}


// --------------------------------------------------------------------------
// Merlin transcripts on STROBE-128 / Keccak-f[1600] — the sr25519
// (schnorrkel) challenge computation, which dominates host-side cost of
// the device sr25519 lane (pure-Python merlin is ~3 ms/signature; this is
// ~2 us). Mirrors crypto/_merlin.py bit-for-bit (differentially tested).

namespace merlin {

static const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static inline uint64_t rotl64(uint64_t v, int n) {
  return n ? (v << n) | (v >> (64 - n)) : v;
}

static const int ROTC[5][5] = {{0, 36, 3, 41, 18},
                               {1, 44, 10, 45, 2},
                               {62, 6, 43, 15, 61},
                               {28, 55, 25, 21, 56},
                               {27, 20, 39, 8, 14}};

static void keccak_f1600(uint8_t state[200]) {
  uint64_t lanes[5][5];
  for (int x = 0; x < 5; x++)
    for (int y = 0; y < 5; y++)
      memcpy(&lanes[x][y], state + 8 * (x + 5 * y), 8);
  for (int r = 0; r < 24; r++) {
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; x++)
      c[x] = lanes[x][0] ^ lanes[x][1] ^ lanes[x][2] ^ lanes[x][3] ^ lanes[x][4];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) lanes[x][y] ^= d[x];
    uint64_t b[5][5];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y][(2 * x + 3 * y) % 5] = rotl64(lanes[x][y], ROTC[x][y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        lanes[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y]);
    lanes[0][0] ^= RC[r];
  }
  for (int x = 0; x < 5; x++)
    for (int y = 0; y < 5; y++)
      memcpy(state + 8 * (x + 5 * y), &lanes[x][y], 8);
}

static const int STROBE_R = 166;
static const uint8_t F_I = 1, F_A = 1 << 1, F_C = 1 << 2, F_M = 1 << 4,
                     F_K = 1 << 5;

struct Strobe {
  uint8_t state[200];
  int pos, pos_begin;

  void run_f() {
    state[pos] ^= (uint8_t)pos_begin;
    state[pos + 1] ^= 0x04;
    state[STROBE_R + 1] ^= 0x80;
    keccak_f1600(state);
    pos = 0;
    pos_begin = 0;
  }

  void absorb(const uint8_t *d, size_t n) {
    for (size_t i = 0; i < n; i++) {
      state[pos] ^= d[i];
      if (++pos == STROBE_R) run_f();
    }
  }

  void squeeze(uint8_t *out, size_t n) {
    for (size_t i = 0; i < n; i++) {
      out[i] = state[pos];
      state[pos] = 0;
      if (++pos == STROBE_R) run_f();
    }
  }

  void begin_op(uint8_t flags) {
    uint8_t old_begin = (uint8_t)pos_begin;
    pos_begin = pos + 1;
    uint8_t hdr[2] = {old_begin, flags};
    absorb(hdr, 2);
    if ((flags & (F_C | F_K)) && pos != 0) run_f();
  }

  void meta_ad(const uint8_t *d, size_t n, bool more) {
    if (!more) begin_op(F_M | F_A);
    absorb(d, n);
  }

  void ad(const uint8_t *d, size_t n) {
    begin_op(F_A);
    absorb(d, n);
  }

  void prf(uint8_t *out, size_t n) {
    begin_op(F_I | F_A | F_C);
    squeeze(out, n);
  }

  void init(const uint8_t *label, size_t n) {
    memset(state, 0, 200);
    const uint8_t hdr[6] = {1, STROBE_R + 2, 1, 0, 1, 12 * 8};
    memcpy(state, hdr, 6);
    memcpy(state + 6, "STROBEv1.0.2", 12);
    keccak_f1600(state);
    pos = 0;
    pos_begin = 0;
    meta_ad(label, n, false);
  }
};

static void append_message(Strobe &s, const uint8_t *label, size_t ln,
                           const uint8_t *msg, size_t mn) {
  uint8_t le[4] = {(uint8_t)(mn & 0xff), (uint8_t)((mn >> 8) & 0xff),
                   (uint8_t)((mn >> 16) & 0xff), (uint8_t)((mn >> 24) & 0xff)};
  s.meta_ad(label, ln, false);
  s.meta_ad(le, 4, true);
  s.ad(msg, mn);
}

}  // namespace merlin

// sr25519_challenges(ctx, pubs, rs, msgs) -> n x 64-byte challenge bytes.
// Shared schnorrkel signing-transcript framing (consensus-critical label
// sequence) -> the 64-byte "sign:c" challenge. Used by both the
// challenge-only and full-verify lanes so the framing cannot diverge.
static void sr25519_challenge_64(const uint8_t *ctx, size_t ctx_len,
                                 const uint8_t *msg, size_t msg_len,
                                 const uint8_t *pub, const uint8_t *r,
                                 uint8_t out[64]) {
  merlin::Strobe s;
  s.init((const uint8_t *)"Merlin v1.0", 11);
  merlin::append_message(s, (const uint8_t *)"dom-sep", 7,
                         (const uint8_t *)"SigningContext", 14);
  merlin::append_message(s, (const uint8_t *)"", 0, ctx, ctx_len);
  merlin::append_message(s, (const uint8_t *)"sign-bytes", 10, msg, msg_len);
  merlin::append_message(s, (const uint8_t *)"proto-name", 10,
                         (const uint8_t *)"Schnorr-sig", 11);
  merlin::append_message(s, (const uint8_t *)"sign:pk", 7, pub, 32);
  merlin::append_message(s, (const uint8_t *)"sign:R", 6, r, 32);
  uint8_t le[4] = {64, 0, 0, 0};
  s.meta_ad((const uint8_t *)"sign:c", 6, false);
  s.meta_ad(le, 4, true);
  s.prf(out, 64);
}

static PyObject *py_sr25519_challenges(PyObject *, PyObject *args) {
  const char *ctx_buf, *pubs, *rs;
  Py_ssize_t ctx_len, pubs_len, rs_len;
  PyObject *msgs;
  if (!PyArg_ParseTuple(args, "y#y#y#O", &ctx_buf, &ctx_len, &pubs, &pubs_len,
                        &rs, &rs_len, &msgs))
    return nullptr;
  PyObject *seq = PySequence_Fast(msgs, "expected a sequence of messages");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (pubs_len != 32 * n || rs_len != 32 * n) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "pubs/rs must be n*32 bytes");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 64);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *m;
    Py_ssize_t mlen;
    if (PyBytes_AsStringAndSize(item, &m, &mlen) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    sr25519_challenge_64((const uint8_t *)ctx_buf, (size_t)ctx_len,
                         (const uint8_t *)m, (size_t)mlen,
                         (const uint8_t *)(pubs + 32 * i),
                         (const uint8_t *)(rs + 32 * i), dst + 64 * i);
  }
  Py_DECREF(seq);
  return out;
}

// --------------------------------------------------------------------------
// GF(2^255-19) + edwards25519 + ristretto255 — the native sr25519
// verification lane (crypto/sr25519/: schnorrkel R == [s]B - [k]A). The
// pure-Python crypto/_ristretto.py is the differential oracle; formulas
// mirror crypto/_edwards.py (add-2008-hwcd-3 / dbl-2008-hwcd, a=-1).

namespace ed {

typedef uint64_t fe[5];  // radix-2^51
static const uint64_t MASK51 = 0x7ffffffffffffULL;

static const fe D_FE = {0x34dca135978a3ULL, 0x1a8283b156ebdULL, 0x5e7a26001c029ULL, 0x739c663a03cbbULL, 0x52036cee2b6ffULL};
static const fe D2_FE = {0x69b9426b2f159ULL, 0x35050762add7aULL, 0x3cf44c0038052ULL, 0x6738cc7407977ULL, 0x2406d9dc56dffULL};
static const fe SQRT_M1_FE = {0x61b274a0ea0b0ULL, 0xd5a5fc8f189dULL, 0x7ef5e9cbd0c60ULL, 0x78595a6804c9eULL, 0x2b8324804fc1dULL};
static const fe BASE_X_FE = {0x62d608f25d51aULL, 0x412a4b4f6592aULL, 0x75b7171a4b31dULL, 0x1ff60527118feULL, 0x216936d3cd6e5ULL};
static const fe BASE_Y_FE = {0x6666666666658ULL, 0x4ccccccccccccULL, 0x1999999999999ULL, 0x3333333333333ULL, 0x6666666666666ULL};
static const fe BASE_T_FE = {0x68ab3a5b7dda3ULL, 0xeea2a5eadbbULL, 0x2af8df483c27eULL, 0x332b375274732ULL, 0x67875f0fd78b7ULL};
static const uint8_t POW_P58_BYTES[32] = {
    0xfd, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
    0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x0f};

static void fe_copy(fe h, const fe a) { memcpy(h, a, sizeof(fe)); }
static void fe_zero(fe h) { memset(h, 0, sizeof(fe)); }
static void fe_one(fe h) { fe_zero(h); h[0] = 1; }

static void fe_add(fe h, const fe a, const fe b) {
  for (int i = 0; i < 5; i++) h[i] = a[i] + b[i];
}

// h = a - b; adds 2p per limb to stay positive (inputs < 2^52)
static void fe_sub(fe h, const fe a, const fe b) {
  static const uint64_t TWO_P[5] = {0xfffffffffffdaULL, 0xffffffffffffeULL,
                                    0xffffffffffffeULL, 0xffffffffffffeULL,
                                    0xffffffffffffeULL};
  for (int i = 0; i < 5; i++) h[i] = a[i] + TWO_P[i] - b[i];
}

// carry-propagate so every limb < 2^51 (values stay mod p)
static void fe_carry(fe h) {
  uint64_t c;
  for (int r = 0; r < 2; r++) {
    c = h[0] >> 51; h[0] &= MASK51; h[1] += c;
    c = h[1] >> 51; h[1] &= MASK51; h[2] += c;
    c = h[2] >> 51; h[2] &= MASK51; h[3] += c;
    c = h[3] >> 51; h[3] &= MASK51; h[4] += c;
    c = h[4] >> 51; h[4] &= MASK51; h[0] += c * 19;
  }
}

static void fe_mul(fe h, const fe a, const fe b) {
  unsigned __int128 t[5] = {0, 0, 0, 0, 0};
  for (int i = 0; i < 5; i++) {
    for (int j = 0; j < 5; j++) {
      int k = i + j;
      unsigned __int128 prod = (unsigned __int128)a[i] * b[j];
      if (k >= 5) {
        k -= 5;
        prod *= 19;
      }
      t[k] += prod;
    }
  }
  // carry chain (each t[i] < ~2^115, fits)
  uint64_t r[5];
  unsigned __int128 c = 0;
  for (int i = 0; i < 5; i++) {
    t[i] += c;
    r[i] = (uint64_t)(t[i] & MASK51);
    c = t[i] >> 51;
  }
  r[0] += (uint64_t)(c * 19);
  memcpy(h, r, sizeof r);
  fe_carry(h);
}

static void fe_sq(fe h, const fe a) { fe_mul(h, a, a); }

// canonical little-endian bytes (full reduction)
static void fe_tobytes(uint8_t out[32], const fe a) {
  fe t;
  fe_copy(t, a);
  fe_carry(t);
  // final conditional subtract p (possibly twice)
  for (int rep = 0; rep < 2; rep++) {
    uint64_t borrow_p[5] = {0x7ffffffffffedULL, MASK51, MASK51, MASK51, MASK51};
    bool ge = true;
    for (int i = 4; i >= 0; i--) {
      if (t[i] > borrow_p[i]) break;
      if (t[i] < borrow_p[i]) { ge = false; break; }
    }
    if (!ge) break;
    uint64_t borrow = 0;
    for (int i = 0; i < 5; i++) {
      uint64_t d = t[i] - borrow_p[i] - borrow;
      borrow = (t[i] < borrow_p[i] + borrow) ? 1 : 0;
      t[i] = d & MASK51;
    }
  }
  uint64_t w0 = t[0] | (t[1] << 51);
  uint64_t w1 = (t[1] >> 13) | (t[2] << 38);
  uint64_t w2 = (t[2] >> 26) | (t[3] << 25);
  uint64_t w3 = (t[3] >> 39) | (t[4] << 12);
  uint64_t ws[4] = {w0, w1, w2, w3};
  for (int i = 0; i < 4; i++)
    for (int b = 0; b < 8; b++) out[8 * i + b] = (uint8_t)(ws[i] >> (8 * b));
}

static void fe_frombytes(fe h, const uint8_t in[32]) {
  uint64_t w[4];
  for (int i = 0; i < 4; i++) {
    w[i] = 0;
    for (int b = 0; b < 8; b++) w[i] |= (uint64_t)in[8 * i + b] << (8 * b);
  }
  h[0] = w[0] & MASK51;
  h[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
  h[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
  h[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
  h[4] = (w[3] >> 12) & MASK51;  // drops bit 255
}

static bool fe_is_negative(const fe a) {
  uint8_t b[32];
  fe_tobytes(b, a);
  return b[0] & 1;
}

static bool fe_is_zero(const fe a) {
  uint8_t b[32];
  fe_tobytes(b, a);
  for (int i = 0; i < 32; i++)
    if (b[i]) return false;
  return true;
}

static bool fe_eq(const fe a, const fe b) {
  fe d;
  fe_sub(d, a, b);
  return fe_is_zero(d);
}

static void fe_neg(fe h, const fe a) {
  fe z;
  fe_zero(z);
  fe_sub(h, z, a);
  fe_carry(h);
}

// a^((p-5)/8) by square-and-multiply over the constant exponent
static void fe_pow_p58(fe h, const fe a) {
  fe result, base;
  fe_one(result);
  fe_copy(base, a);
  for (int bit = 0; bit < 252; bit++) {
    if ((POW_P58_BYTES[bit >> 3] >> (bit & 7)) & 1) fe_mul(result, result, base);
    if (bit != 251) fe_sq(base, base);
  }
  fe_copy(h, result);
}

// _edwards._sqrt_ratio: r with v*r^2 == u, or false (r undefined)
static bool fe_sqrt_ratio(fe r, const fe u, const fe v) {
  fe v3, v7, t, uv7, pw;
  fe_sq(v3, v);
  fe_mul(v3, v3, v);       // v^3
  fe_sq(v7, v3);
  fe_mul(v7, v7, v);       // v^7
  fe_mul(uv7, u, v7);
  fe_pow_p58(pw, uv7);     // (u v^7)^((p-5)/8)
  fe_mul(t, u, v3);
  fe_mul(r, t, pw);        // u v^3 (u v^7)^((p-5)/8)
  fe check;
  fe_sq(check, r);
  fe_mul(check, check, v);  // v r^2
  if (fe_eq(check, u)) return true;
  fe nu;
  fe_neg(nu, u);
  if (fe_eq(check, nu)) {
    fe_mul(r, r, SQRT_M1_FE);
    return true;
  }
  return false;
}

// _ristretto._invsqrt: (was_square, 1/sqrt(u)); u=0 -> (true, 0)
static bool fe_invsqrt(fe r, const fe u) {
  if (fe_is_zero(u)) {
    fe_zero(r);
    return true;
  }
  fe one;
  fe_one(one);
  if (fe_sqrt_ratio(r, one, u)) return true;
  // not a square: r = sqrt(i/u) (decode rejects via ok=false anyway)
  fe_sqrt_ratio(r, SQRT_M1_FE, u);
  return false;
}

struct point {
  fe x, y, z, t;
};

static void pt_identity(point &p) {
  fe_zero(p.x);
  fe_one(p.y);
  fe_one(p.z);
  fe_zero(p.t);
}

// add-2008-hwcd-3, a=-1 (crypto/_edwards.py point_add)
static void pt_add(point &h, const point &p, const point &q) {
  fe a, b, c, d, e, f, g, hh, t1, t2;
  fe_sub(t1, p.y, p.x);
  fe_sub(t2, q.y, q.x);
  fe_carry(t1);
  fe_carry(t2);
  fe_mul(a, t1, t2);
  fe_add(t1, p.y, p.x);
  fe_add(t2, q.y, q.x);
  fe_carry(t1);
  fe_carry(t2);
  fe_mul(b, t1, t2);
  fe_mul(c, p.t, D2_FE);
  fe_mul(c, c, q.t);
  fe_mul(d, p.z, q.z);
  fe_add(d, d, d);
  fe_carry(d);
  fe_sub(e, b, a);
  fe_sub(f, d, c);
  fe_add(g, d, c);
  fe_add(hh, b, a);
  fe_carry(e);
  fe_carry(f);
  fe_carry(g);
  fe_carry(hh);
  fe_mul(h.x, e, f);
  fe_mul(h.y, g, hh);
  fe_mul(h.z, f, g);
  fe_mul(h.t, e, hh);
}

// dbl-2008-hwcd, a=-1 (crypto/_edwards.py point_double)
static void pt_double(point &h, const point &p) {
  fe a, b, c, d, e, f, g, hh, t1;
  fe_sq(a, p.x);
  fe_sq(b, p.y);
  fe_sq(c, p.z);
  fe_add(c, c, c);
  fe_carry(c);
  fe_neg(d, a);
  fe_add(t1, p.x, p.y);
  fe_carry(t1);
  fe_sq(e, t1);
  fe_sub(e, e, a);
  fe_sub(e, e, b);
  fe_carry(e);
  fe_add(g, d, b);
  fe_carry(g);
  fe_sub(f, g, c);
  fe_carry(f);
  fe_sub(hh, d, b);
  fe_carry(hh);
  fe_mul(h.x, e, f);
  fe_mul(h.y, g, hh);
  fe_mul(h.z, f, g);
  fe_mul(h.t, e, hh);
}

static void pt_neg(point &h, const point &p) {
  fe_neg(h.x, p.x);
  fe_copy(h.y, p.y);
  fe_copy(h.z, p.z);
  fe_neg(h.t, p.t);
}

// 4-bit fixed-window scalar multiply: scalar is 32 LE bytes (< L)
static void pt_scalar_mul(point &h, const uint8_t scalar[32], const point &p) {
  point table[16];
  pt_identity(table[0]);
  table[1] = p;
  for (int i = 2; i < 16; i++) pt_add(table[i], table[i - 1], p);
  pt_identity(h);
  bool started = false;
  for (int i = 63; i >= 0; i--) {
    int nib = (scalar[i >> 1] >> ((i & 1) ? 4 : 0)) & 0xf;
    if (started) {
      pt_double(h, h);
      pt_double(h, h);
      pt_double(h, h);
      pt_double(h, h);
    }
    if (nib) {
      if (started) {
        pt_add(h, h, table[nib]);
      } else {
        h = table[nib];
        started = true;
      }
    } else if (started) {
      // nothing to add
    }
  }
}

// ristretto255 DECODE (crypto/_ristretto.py decode); false on reject
static bool ristretto_decode(point &out, const uint8_t in[32]) {
  // reject s >= p or negative (odd)
  static const uint8_t P_BYTES[32] = {
      0xed, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff,
      0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0xff, 0x7f};
  bool lt = false;
  for (int i = 31; i >= 0; i--) {
    if (in[i] < P_BYTES[i]) { lt = true; break; }
    if (in[i] > P_BYTES[i]) return false;
  }
  if (!lt) return false;          // s == p
  if (in[0] & 1) return false;    // negative
  fe s, ss, u1, u2, u2s, v, t1, t2, one;
  fe_frombytes(s, in);
  fe_one(one);
  fe_sq(ss, s);
  fe_sub(u1, one, ss);
  fe_carry(u1);
  fe_add(u2, one, ss);
  fe_carry(u2);
  fe_sq(u2s, u2);
  fe_mul(t1, D_FE, u1);
  fe_mul(t1, t1, u1);
  fe_neg(t1, t1);
  fe_sub(v, t1, u2s);
  fe_carry(v);
  fe invsq, vu2s;
  fe_mul(vu2s, v, u2s);
  bool ok = fe_invsqrt(invsq, vu2s);
  fe den_x, den_y, x, y, t;
  fe_mul(den_x, invsq, u2);
  fe_mul(den_y, invsq, den_x);
  fe_mul(den_y, den_y, v);
  fe_add(t1, s, s);
  fe_carry(t1);
  fe_mul(x, t1, den_x);
  if (fe_is_negative(x)) fe_neg(x, x);
  fe_mul(y, u1, den_y);
  fe_mul(t, x, y);
  if (!ok || fe_is_negative(t) || fe_is_zero(y)) return false;
  fe_copy(out.x, x);
  fe_copy(out.y, y);
  fe_one(out.z);
  fe_copy(out.t, t);
  return true;
}

// ristretto equality: x1 y2 == y1 x2 or y1 y2 == x1 x2
static bool ristretto_eq(const point &a, const point &b) {
  fe l, r;
  fe_mul(l, a.x, b.y);
  fe_mul(r, a.y, b.x);
  if (fe_eq(l, r)) return true;
  fe_mul(l, a.y, b.y);
  fe_mul(r, a.x, b.x);
  return fe_eq(l, r);
}

}  // namespace ed

// OpenSSL's asm SHA-512 when libcrypto is present (no dev headers in the
// image, so resolve the one-shot SHA512() via dlopen; the scalar
// implementation above is the fallback and the differential-test oracle).
#include <dlfcn.h>
typedef unsigned char *(*ossl_sha512_fn)(const unsigned char *, size_t,
                                         unsigned char *);
static ossl_sha512_fn ossl_sha512() {
  static ossl_sha512_fn fn = []() -> ossl_sha512_fn {
    void *h = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_LOCAL);
    if (!h) h = dlopen("libcrypto.so", RTLD_NOW | RTLD_LOCAL);
    if (!h) return nullptr;
    return (ossl_sha512_fn)dlsym(h, "SHA512");
  }();
  return fn;
}

// ed25519_challenges(rs: n*32 bytes, pubs: n*32 bytes, msgs: seq[bytes])
//   -> bytes (n*32): k_i = SHA512(R_i || A_i || M_i) mod L, little-endian.
static PyObject *py_ed25519_challenges(PyObject *, PyObject *args) {
  Py_buffer rs, pubs;
  PyObject *msgs;
  int no_ossl = 0;  // tests force the scalar fallback path
  if (!PyArg_ParseTuple(args, "y*y*O|p", &rs, &pubs, &msgs, &no_ossl))
    return nullptr;
  PyObject *seq = PySequence_Fast(msgs, "expected a sequence of messages");
  if (!seq) {
    PyBuffer_Release(&rs);
    PyBuffer_Release(&pubs);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (rs.len < 32 * n || pubs.len < 32 * n) {
    Py_DECREF(seq);
    PyBuffer_Release(&rs);
    PyBuffer_Release(&pubs);
    PyErr_SetString(PyExc_ValueError, "rs/pubs must be at least n*32 bytes");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 32);
  if (!out) {
    Py_DECREF(seq);
    PyBuffer_Release(&rs);
    PyBuffer_Release(&pubs);
    return nullptr;
  }
  uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(out);
  const uint8_t *rp = (const uint8_t *)rs.buf;
  const uint8_t *pp = (const uint8_t *)pubs.buf;
  ossl_sha512_fn fast = no_ossl ? nullptr : ossl_sha512();
  // extract message pointers under the GIL, then hash WITHOUT it: this
  // loop is ~17 ms for a 10k batch and runs on the async pipeline's prep
  // path — holding the GIL here serializes prep against dispatch and
  // caps the stream at ~1/(prep+kernel) instead of 1/max(prep, kernel)
  std::vector<std::pair<const uint8_t *, size_t>> mv;
  mv.reserve((size_t)n);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *m;
    Py_ssize_t mlen;
    if (PyBytes_AsStringAndSize(item, &m, &mlen) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      PyBuffer_Release(&rs);
      PyBuffer_Release(&pubs);
      return nullptr;
    }
    mv.emplace_back((const uint8_t *)m, (size_t)mlen);
  }
  Py_BEGIN_ALLOW_THREADS
  std::vector<uint8_t> cat;
  for (Py_ssize_t i = 0; i < n; i++) {
    uint8_t digest[64];
    if (fast) {
      cat.resize(64 + mv[i].second);
      memcpy(cat.data(), rp + 32 * i, 32);
      memcpy(cat.data() + 32, pp + 32 * i, 32);
      if (mv[i].second) memcpy(cat.data() + 64, mv[i].first, mv[i].second);
      fast(cat.data(), cat.size(), digest);
    } else {
      sha512::Ctx c;
      sha512::init(&c);
      sha512::update(&c, rp + 32 * i, 32);
      sha512::update(&c, pp + 32 * i, 32);
      sha512::update(&c, mv[i].first, mv[i].second);
      sha512::final(&c, digest);
    }
    sha512::mod_l(digest, dst + 32 * i);
  }
  Py_END_ALLOW_THREADS
  Py_DECREF(seq);
  PyBuffer_Release(&rs);
  PyBuffer_Release(&pubs);
  return out;
}

// sr25519_verify_batch(ctx: bytes, pubs: n*32, sigs: n*64, msgs: seq)
//   -> bytes (n): 1 where R == [s]B - [k]A (schnorrkel verify), else 0.
// Transcript framing identical to sr25519_challenges; k = challenge mod L.
static PyObject *py_sr25519_verify_batch(PyObject *, PyObject *args) {
  const char *ctx_buf;
  Py_ssize_t ctx_len;
  Py_buffer pubs, sigs;
  PyObject *msgs;
  if (!PyArg_ParseTuple(args, "y#y*y*O", &ctx_buf, &ctx_len, &pubs, &sigs,
                        &msgs))
    return nullptr;
  PyObject *seq = PySequence_Fast(msgs, "expected a sequence of messages");
  if (!seq) {
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&sigs);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (pubs.len < 32 * n || sigs.len < 64 * n) {
    Py_DECREF(seq);
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&sigs);
    PyErr_SetString(PyExc_ValueError, "pubs/sigs must be n*32 / n*64 bytes");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n);
  if (!out) {
    Py_DECREF(seq);
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&sigs);
    return nullptr;
  }
  uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(out);
  const uint8_t *pp = (const uint8_t *)pubs.buf;
  const uint8_t *sp = (const uint8_t *)sigs.buf;
  // message pointers are pinned under the GIL; the verification loop is
  // embarrassingly parallel and runs with the GIL RELEASED across a
  // small thread pool (each signature touches only its own output byte)
  std::vector<const uint8_t *> mptrs(n);
  std::vector<size_t> mlens(n);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *m;
    Py_ssize_t mlen;
    if (PyBytes_AsStringAndSize(item, &m, &mlen) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      PyBuffer_Release(&pubs);
      PyBuffer_Release(&sigs);
      return nullptr;
    }
    mptrs[i] = (const uint8_t *)m;
    mlens[i] = (size_t)mlen;
  }
  const uint8_t *ctx_p = (const uint8_t *)ctx_buf;
  size_t ctx_l = (size_t)ctx_len;

  auto verify_range = [&](Py_ssize_t lo, Py_ssize_t hi) {
    ed::point base;
    ed::fe_copy(base.x, ed::BASE_X_FE);
    ed::fe_copy(base.y, ed::BASE_Y_FE);
    ed::fe_one(base.z);
    ed::fe_copy(base.t, ed::BASE_T_FE);
    for (Py_ssize_t i = lo; i < hi; i++) {
      dst[i] = 0;
      const uint8_t *sig = sp + 64 * i;
      const uint8_t *pub = pp + 32 * i;
      if (!(sig[63] & 0x80)) continue;  // schnorrkel v1 marker
      uint8_t s_bytes[32];
      memcpy(s_bytes, sig + 32, 32);
      s_bytes[31] &= 0x7f;
      // s < L check (L = limbs sha512::L_LIMBS, little-endian u64)
      {
        uint64_t s_limbs[4];
        for (int j = 0; j < 4; j++) {
          s_limbs[j] = 0;
          for (int b = 0; b < 8; b++)
            s_limbs[j] |= (uint64_t)s_bytes[8 * j + b] << (8 * b);
        }
        bool lt = false, ge = false;
        for (int j = 3; j >= 0; j--) {
          if (s_limbs[j] < sha512::L_LIMBS[j]) { lt = true; break; }
          if (s_limbs[j] > sha512::L_LIMBS[j]) { ge = true; break; }
        }
        if (ge || !lt) continue;  // s >= L
      }
      ed::point A, R;
      if (!ed::ristretto_decode(A, pub)) continue;
      if (!ed::ristretto_decode(R, sig)) continue;
      // k = merlin challenge mod L (same framing as sr25519_challenges)
      uint8_t k_wide[64], k_bytes[32];
      sr25519_challenge_64(ctx_p, ctx_l, mptrs[i], mlens[i], pub, sig, k_wide);
      sha512::mod_l(k_wide, k_bytes);
      // expected = [s]B + [k](-A); accept iff ristretto_eq(expected, R)
      ed::point sB, kA, negA, expected;
      ed::pt_scalar_mul(sB, s_bytes, base);
      ed::pt_neg(negA, A);
      ed::pt_scalar_mul(kA, k_bytes, negA);
      ed::pt_add(expected, sB, kA);
      dst[i] = ed::ristretto_eq(expected, R) ? 1 : 0;
    }
  };

  Py_BEGIN_ALLOW_THREADS
  // pool width: the affinity-mask CPU count (respects cpuset pinning),
  // overridable with TM_NATIVE_THREADS; hardware_concurrency() alone
  // oversubscribes cgroup-quota'd containers
  unsigned hw = 0;
  {
    cpu_set_t setmask;
    if (sched_getaffinity(0, sizeof(setmask), &setmask) == 0)
      hw = (unsigned)CPU_COUNT(&setmask);
    if (!hw) hw = std::thread::hardware_concurrency();
    const char *env = getenv("TM_NATIVE_THREADS");
    if (env && *env) {
      long v = strtol(env, nullptr, 10);
      if (v > 0 && v < 1024) hw = (unsigned)v;
    }
  }
  Py_ssize_t nthreads = (Py_ssize_t)(hw ? hw : 1);
  if (nthreads > n) nthreads = n > 0 ? n : 1;
  if (nthreads <= 1 || n < 16) {
    verify_range(0, n);
  } else {
    std::vector<std::thread> pool;
    Py_ssize_t chunk = (n + nthreads - 1) / nthreads;
    for (Py_ssize_t t = 0; t < nthreads; t++) {
      Py_ssize_t lo = t * chunk;
      Py_ssize_t hi = lo + chunk < n ? lo + chunk : n;
      if (lo >= hi) break;
      pool.emplace_back(verify_range, lo, hi);
    }
    for (auto &th : pool) th.join();
  }
  Py_END_ALLOW_THREADS

  Py_DECREF(seq);
  PyBuffer_Release(&pubs);
  PyBuffer_Release(&sigs);
  return out;
}

// --------------------------------------------------------------------------
// Host ed25519 RLC batch verification (the honest CPU batch baseline and
// the no-device fallback). Same construction as Go crypto/ed25519's batch
// path (crypto/ed25519/ed25519.go:192-227 -> curve25519-voi BatchVerifier):
// random 128-bit coefficients z_i, one cofactored check
//   [8]( sum z_i R_i + sum (z_i k_i mod L) A_i - [sum z_i s_i mod L] B ) == O
// evaluated with a Pippenger multi-scalar multiplication over 2n points.

#include <sys/random.h>

namespace ed {

// ZIP-215 edwards decompression (crypto/_edwards.py decompress with
// allow_noncanonical=True): y from the low 255 bits WITHOUT a y < p
// canonicity check, "negative zero" x accepted.
static bool ge_frombytes_zip215(point &out, const uint8_t in[32]) {
  fe y, yy, u, v, x;
  fe_frombytes(y, in);  // drops bit 255; value may be >= p (allowed)
  int sign = in[31] >> 7;
  fe_sq(yy, y);
  fe one;
  fe_one(one);
  fe_sub(u, yy, one);
  fe_carry(u);
  fe_mul(v, D_FE, yy);
  fe_add(v, v, one);
  fe_carry(v);
  if (!fe_sqrt_ratio(x, u, v)) return false;
  if (fe_is_negative(x) != (sign != 0)) fe_neg(x, x);
  fe_copy(out.x, x);
  fe_copy(out.y, y);
  fe_one(out.z);
  fe_mul(out.t, x, y);
  return true;
}

// 256-bit LE schoolbook product -> 64-byte LE -> mod L
static void sc_mul(uint8_t out[32], const uint8_t a[32], const uint8_t b[32]) {
  uint64_t al[4], bl[4];
  for (int i = 0; i < 4; i++) {
    al[i] = bl[i] = 0;
    for (int j = 0; j < 8; j++) {
      al[i] |= (uint64_t)a[8 * i + j] << (8 * j);
      bl[i] |= (uint64_t)b[8 * i + j] << (8 * j);
    }
  }
  uint64_t prod[8] = {0};
  for (int i = 0; i < 4; i++) {
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; j++) {
      unsigned __int128 cur =
          (unsigned __int128)al[i] * bl[j] + prod[i + j] + carry;
      prod[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    prod[i + 4] = (uint64_t)carry;
  }
  uint8_t wide[64];
  for (int i = 0; i < 8; i++)
    for (int j = 0; j < 8; j++) wide[8 * i + j] = (uint8_t)(prod[i] >> (8 * j));
  sha512::mod_l(wide, out);
}

// out = (a + b) mod L for a, b < L
static void sc_add(uint8_t out[32], const uint8_t a[32], const uint8_t b[32]) {
  uint64_t al[4], bl[4], s[4];
  for (int i = 0; i < 4; i++) {
    al[i] = bl[i] = 0;
    for (int j = 0; j < 8; j++) {
      al[i] |= (uint64_t)a[8 * i + j] << (8 * j);
      bl[i] |= (uint64_t)b[8 * i + j] << (8 * j);
    }
  }
  unsigned __int128 c = 0;
  for (int i = 0; i < 4; i++) {
    c += (unsigned __int128)al[i] + bl[i];
    s[i] = (uint64_t)c;
    c >>= 64;
  }
  // sum < 2L (< 2^253): one conditional subtract of L
  bool ge = c != 0;
  if (!ge) {
    ge = true;
    for (int i = 3; i >= 0; i--) {
      if (s[i] > sha512::L_LIMBS[i]) break;
      if (s[i] < sha512::L_LIMBS[i]) { ge = false; break; }
    }
  }
  if (ge) {
    uint64_t borrow = 0;
    for (int i = 0; i < 4; i++) {
      unsigned __int128 d =
          (unsigned __int128)s[i] - sha512::L_LIMBS[i] - borrow;
      s[i] = (uint64_t)d;
      borrow = (uint64_t)(d >> 64) ? 1 : 0;
    }
  }
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) out[8 * i + j] = (uint8_t)(s[i] >> (8 * j));
}

// Pippenger MSM with 8-bit windows: res = sum scalars[i] * pts[i].
// Scalars are 32-byte LE (< L). ~n + 512 point adds per window.
static void pippenger_msm(point &res, const std::vector<uint8_t> &scalars,
                          const std::vector<point> &pts) {
  size_t n = pts.size();
  pt_identity(res);
  static thread_local std::vector<point> buckets(256);
  static thread_local std::vector<uint8_t> used(256);
  for (int w = 31; w >= 0; w--) {
    if (w != 31)
      for (int d = 0; d < 8; d++) pt_double(res, res);
    memset(used.data(), 0, 256);
    for (size_t i = 0; i < n; i++) {
      uint8_t dig = scalars[32 * i + w];
      if (!dig) continue;
      if (!used[dig]) {
        buckets[dig] = pts[i];
        used[dig] = 1;
      } else {
        pt_add(buckets[dig], buckets[dig], pts[i]);
      }
    }
    // sum_d d * bucket[d] via suffix sums
    point running, acc;
    pt_identity(running);
    pt_identity(acc);
    bool any = false;
    for (int d = 255; d >= 1; d--) {
      if (used[d]) {
        pt_add(running, running, buckets[d]);
        any = true;
      }
      if (any) pt_add(acc, acc, running);
    }
    if (any) pt_add(res, res, acc);
  }
}

// Full RLC batch verification; entries prevalidated by the caller except
// for the point decodes and s < L checks done here. Returns 1 (batch
// equation holds), 0 (reject — caller falls back per-sig for blame), or
// -1 on malformed input.
static int batch_verify_rlc(const uint8_t *pubs, const uint8_t *sigs,
                            const std::vector<std::pair<const uint8_t *, size_t>> &msgs) {
  size_t n = msgs.size();
  std::vector<point> pts;
  std::vector<uint8_t> scalars;
  pts.reserve(2 * n);
  scalars.reserve(64 * n);
  uint8_t s_sum[32] = {0};
  ossl_sha512_fn fast = ossl_sha512();
  std::vector<uint8_t> cat;
  // one bulk getrandom for every z coefficient (vs n syscalls in-loop)
  std::vector<uint8_t> zs_rand(16 * n);
  {
    size_t got = 0;
    while (got < zs_rand.size()) {
      ssize_t r = getrandom(zs_rand.data() + got, zs_rand.size() - got, 0);
      if (r <= 0) return -1;
      got += (size_t)r;
    }
  }
  static const uint8_t L_BYTES[32] = {
      0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58, 0xd6, 0x9c, 0xf7,
      0xa2, 0xde, 0xf9, 0xde, 0x14, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};
  for (size_t i = 0; i < n; i++) {
    const uint8_t *pub = pubs + 32 * i;
    const uint8_t *sig = sigs + 64 * i;
    // s < L (RFC 8032)
    bool lt = false;
    for (int j = 31; j >= 0; j--) {
      if (sig[32 + j] < L_BYTES[j]) { lt = true; break; }
      if (sig[32 + j] > L_BYTES[j]) return 0;
    }
    if (!lt) return 0;
    point A, R;
    if (!ge_frombytes_zip215(A, pub)) return 0;
    if (!ge_frombytes_zip215(R, sig)) return 0;
    // k = SHA512(R || A || M) mod L
    uint8_t digest[64], k[32];
    if (fast) {
      cat.resize(64 + msgs[i].second);
      memcpy(cat.data(), sig, 32);
      memcpy(cat.data() + 32, pub, 32);
      if (msgs[i].second) memcpy(cat.data() + 64, msgs[i].first, msgs[i].second);
      fast(cat.data(), cat.size(), digest);
    } else {
      sha512::Ctx c;
      sha512::init(&c);
      sha512::update(&c, sig, 32);
      sha512::update(&c, pub, 32);
      sha512::update(&c, msgs[i].first, msgs[i].second);
      sha512::final(&c, digest);
    }
    sha512::mod_l(digest, k);
    // random 128-bit z from the bulk fill
    uint8_t z[32] = {0};
    memcpy(z, zs_rand.data() + 16 * i, 16);
    uint8_t zs[32], zk[32];
    sc_mul(zs, z, sig + 32);
    sc_add(s_sum, s_sum, zs);
    sc_mul(zk, z, k);
    pts.push_back(R);
    scalars.insert(scalars.end(), z, z + 32);
    pts.push_back(A);
    scalars.insert(scalars.end(), zk, zk + 32);
  }
  point msm, sb, check;
  pippenger_msm(msm, scalars, pts);
  point base;
  fe_copy(base.x, BASE_X_FE);
  fe_copy(base.y, BASE_Y_FE);
  fe_one(base.z);
  fe_copy(base.t, BASE_T_FE);
  pt_scalar_mul(sb, s_sum, base);
  point neg_sb;
  pt_neg(neg_sb, sb);
  pt_add(check, msm, neg_sb);
  for (int d = 0; d < 3; d++) pt_double(check, check);  // cofactor 8
  return (fe_is_zero(check.x) && fe_eq(check.y, check.z)) ? 1 : 0;
}

}  // namespace ed

// ed25519_batch_verify(pubs: n*32, sigs: n*64, msgs: seq[bytes]) -> bool
//   One RLC batch equation over the whole input (crypto/ed25519/ed25519.go
//   :219-227 BatchVerifier.Verify semantics: a single cofactored check;
//   on False the caller re-verifies per signature for blame assignment).
static PyObject *py_ed25519_batch_verify(PyObject *, PyObject *args) {
  Py_buffer pubs, sigs;
  PyObject *msgs;
  if (!PyArg_ParseTuple(args, "y*y*O", &pubs, &sigs, &msgs)) return nullptr;
  PyObject *seq = PySequence_Fast(msgs, "expected a sequence of messages");
  if (!seq) {
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&sigs);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  int rc = -1;
  if (pubs.len >= 32 * n && sigs.len >= 64 * n) {
    std::vector<std::pair<const uint8_t *, size_t>> mv;
    mv.reserve((size_t)n);
    bool ok = true;
    for (Py_ssize_t i = 0; i < n; i++) {
      char *m;
      Py_ssize_t mlen;
      if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(seq, i), &m,
                                  &mlen) < 0) {
        ok = false;
        break;
      }
      mv.emplace_back((const uint8_t *)m, (size_t)mlen);
    }
    if (ok) {
      if (n == 0) {
        rc = 0;  // Verify() on an empty batch is false (batch.go:29)
      } else {
        Py_BEGIN_ALLOW_THREADS
        rc = ed::batch_verify_rlc((const uint8_t *)pubs.buf,
                                  (const uint8_t *)sigs.buf, mv);
        Py_END_ALLOW_THREADS
      }
    } else {
      Py_DECREF(seq);
      PyBuffer_Release(&pubs);
      PyBuffer_Release(&sigs);
      return nullptr;
    }
  } else {
    PyErr_SetString(PyExc_ValueError, "pubs/sigs shorter than n entries");
  }
  Py_DECREF(seq);
  PyBuffer_Release(&pubs);
  PyBuffer_Release(&sigs);
  if (rc < 0) {
    if (!PyErr_Occurred())
      PyErr_SetString(PyExc_RuntimeError, "batch verification failed to run");
    return nullptr;
  }
  return PyBool_FromLong(rc);
}

// vote_sign_bytes_batch(prefix, suffix, times: n*16B LE int64 pairs
// (seconds, nanos)) -> list[bytes]. Composes the canonical vote sign
// bytes for every signature of a commit in one call: delimited(prefix +
// Timestamp-field(5) + suffix), mirroring wire/canonical.py
// compose_vote_sign_bytes byte for byte (proto3 default-skip varints,
// 64-bit two's complement negatives). The per-signature Python composer
// measured ~27us/sig — the host bottleneck of pipelined header sync.
static size_t put_uvarint(uint8_t *dst, uint64_t v) {
  size_t i = 0;
  while (v >= 0x80) {
    dst[i++] = (uint8_t)(v | 0x80);
    v >>= 7;
  }
  dst[i++] = (uint8_t)v;
  return i;
}

static PyObject *py_vote_sign_bytes_batch(PyObject *, PyObject *args) {
  Py_buffer prefix, suffix, times;
  if (!PyArg_ParseTuple(args, "y*y*y*", &prefix, &suffix, &times))
    return nullptr;
  if (times.len % 16) {
    PyBuffer_Release(&prefix);
    PyBuffer_Release(&suffix);
    PyBuffer_Release(&times);
    PyErr_SetString(PyExc_ValueError,
                    "times must be n*16 bytes of (seconds, nanos) pairs");
    return nullptr;
  }
  Py_ssize_t n = times.len / 16;
  PyObject *out = PyList_New(n);
  if (!out) {
    PyBuffer_Release(&prefix);
    PyBuffer_Release(&suffix);
    PyBuffer_Release(&times);
    return nullptr;
  }
  const uint8_t *tp = (const uint8_t *)times.buf;
  std::vector<uint8_t> buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t secs, nanos;
    memcpy(&secs, tp + 16 * i, 8);
    memcpy(&nanos, tp + 16 * i + 8, 8);
    uint8_t ts_body[22];
    size_t tn = 0;
    if (secs != 0) {
      ts_body[tn++] = 0x08;  // field 1, varint
      tn += put_uvarint(ts_body + tn, (uint64_t)secs);
    }
    if (nanos != 0) {
      ts_body[tn++] = 0x10;  // field 2, varint
      tn += put_uvarint(ts_body + tn, (uint64_t)nanos);
    }
    uint8_t mid[32];
    size_t mn = 0;
    mid[mn++] = 0x2a;  // field 5, length-delimited
    mn += put_uvarint(mid + mn, tn);
    memcpy(mid + mn, ts_body, tn);
    mn += tn;
    size_t body_len = (size_t)prefix.len + mn + (size_t)suffix.len;
    uint8_t hdr[10];
    size_t hn = put_uvarint(hdr, body_len);
    buf.resize(hn + body_len);
    memcpy(buf.data(), hdr, hn);
    memcpy(buf.data() + hn, prefix.buf, prefix.len);
    memcpy(buf.data() + hn + prefix.len, mid, mn);
    memcpy(buf.data() + hn + prefix.len + mn, suffix.buf, suffix.len);
    PyObject *b =
        PyBytes_FromStringAndSize((const char *)buf.data(), (Py_ssize_t)buf.size());
    if (!b) {
      Py_DECREF(out);
      PyBuffer_Release(&prefix);
      PyBuffer_Release(&suffix);
      PyBuffer_Release(&times);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, b);
  }
  PyBuffer_Release(&prefix);
  PyBuffer_Release(&suffix);
  PyBuffer_Release(&times);
  return out;
}

// ed25519_rlc_scalars(s: n*32, k: n*32, z: n*32, m: int)
//   -> bytes ((n/m)*32 S-scalars || n*32 u-scalars)
//
// Host scalar prep for the DEVICE per-lane RLC fast-accept kernel
// (ops/pallas_rlc.py): lane g covers sigs j = g*m .. g*m+m-1 with
// coefficients c_0 = 1, c_j = z_j (random 128-bit, caller-supplied;
// slot-0 z entries are ignored). Per lane:
//   S   = (s_0 + sum_{j>=1} z_j * s_j) mod L
//   u_0 = k_0;  u_j = (z_j * k_j) mod L
// Same RLC construction as batch_verify_rlc above (crypto/ed25519/
// ed25519.go:192-227 semantics); the k inputs are already mod L, the s
// inputs may be >= L for invalid signatures (reduced here — the lane's
// s<L flag rejects them independently, this just keeps the math total).
static PyObject *py_ed25519_rlc_scalars(PyObject *, PyObject *args) {
  Py_buffer sb, kb, zb;
  Py_ssize_t m;
  if (!PyArg_ParseTuple(args, "y*y*y*n", &sb, &kb, &zb, &m)) return nullptr;
  Py_ssize_t n = sb.len / 32;
  if (m <= 0 || n % m || kb.len < 32 * n || zb.len < 32 * n) {
    PyBuffer_Release(&sb);
    PyBuffer_Release(&kb);
    PyBuffer_Release(&zb);
    PyErr_SetString(PyExc_ValueError, "bad rlc scalar input lengths");
    return nullptr;
  }
  Py_ssize_t g = n / m;
  PyObject *out = PyBytes_FromStringAndSize(nullptr, 32 * (g + n));
  if (!out) {
    PyBuffer_Release(&sb);
    PyBuffer_Release(&kb);
    PyBuffer_Release(&zb);
    return nullptr;
  }
  uint8_t *S = (uint8_t *)PyBytes_AS_STRING(out);
  uint8_t *U = S + 32 * g;
  const uint8_t *s = (const uint8_t *)sb.buf;
  const uint8_t *k = (const uint8_t *)kb.buf;
  const uint8_t *z = (const uint8_t *)zb.buf;
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t lane = 0; lane < g; lane++) {
    Py_ssize_t base = lane * m;
    // S init = s_0 mod L (s may be non-canonical; widen and reduce)
    uint8_t wide[64] = {0};
    memcpy(wide, s + 32 * base, 32);
    sha512::mod_l(wide, S + 32 * lane);
    memcpy(U + 32 * base, k + 32 * base, 32);
    for (Py_ssize_t j = 1; j < m; j++) {
      uint8_t zs[32];
      ed::sc_mul(zs, z + 32 * (base + j), s + 32 * (base + j));
      ed::sc_add(S + 32 * lane, S + 32 * lane, zs);
      ed::sc_mul(U + 32 * (base + j), z + 32 * (base + j), k + 32 * (base + j));
    }
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&sb);
  PyBuffer_Release(&kb);
  PyBuffer_Release(&zb);
  return out;
}

// --------------------------------------------------------------------------
// Columnar (EntryBlock) prep — the zero-copy commit path. All entry points
// below consume contiguous buffers (pubs n*32, sigs n*64, one concatenated
// sign-bytes buffer + an (n+1) int64 offset table) and run with the GIL
// RELEASED end to end: no per-signature Python objects are touched between
// commit selection and the kernel argument arrays (ops/entry_block.py).

// Shared per-range worker pool sizing (same policy as sr25519_verify_batch:
// affinity-mask CPU count, TM_NATIVE_THREADS override).
static unsigned native_pool_width() {
  unsigned hw = 0;
  cpu_set_t setmask;
  if (sched_getaffinity(0, sizeof(setmask), &setmask) == 0)
    hw = (unsigned)CPU_COUNT(&setmask);
  if (!hw) hw = std::thread::hardware_concurrency();
  const char *env = getenv("TM_NATIVE_THREADS");
  if (env && *env) {
    long v = strtol(env, nullptr, 10);
    if (v > 0 && v < 1024) hw = (unsigned)v;
  }
  return hw ? hw : 1;
}

template <typename Fn>
static void parallel_ranges(Py_ssize_t n, Py_ssize_t min_serial, Fn fn) {
  Py_ssize_t nthreads = (Py_ssize_t)native_pool_width();
  if (nthreads > n) nthreads = n > 0 ? n : 1;
  if (nthreads <= 1 || n < min_serial) {
    fn((Py_ssize_t)0, n);
    return;
  }
  std::vector<std::thread> pool;
  Py_ssize_t chunk = (n + nthreads - 1) / nthreads;
  for (Py_ssize_t t = 0; t < nthreads; t++) {
    Py_ssize_t lo = t * chunk;
    Py_ssize_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back(fn, lo, hi);
  }
  for (auto &th : pool) th.join();
}

// Offset-table validation shared by the columnar entry points. Runs
// before any GIL-released work: a non-monotonic table would make
// offs[i+1]-offs[i] wrap to a huge size_t inside the threaded hash loop.
static bool offsets_valid(const int64_t *op, Py_ssize_t n,
                          Py_ssize_t msgs_len) {
  if (n < 0) return false;
  if (n == 0) return true;
  if (op[0] != 0 || op[n] > msgs_len) return false;
  for (Py_ssize_t i = 0; i < n; i++)
    if (op[i + 1] < op[i]) return false;
  return true;
}

// k_i = SHA512(R_i || A_i || M_i) mod L over columnar buffers.
static void challenges_range(const uint8_t *rs, const uint8_t *pubs,
                             const uint8_t *msgs, const int64_t *offs,
                             Py_ssize_t lo, Py_ssize_t hi, uint8_t *dst,
                             ossl_sha512_fn fast) {
  std::vector<uint8_t> cat;
  for (Py_ssize_t i = lo; i < hi; i++) {
    size_t mlen = (size_t)(offs[i + 1] - offs[i]);
    const uint8_t *m = msgs + offs[i];
    uint8_t digest[64];
    if (fast) {
      cat.resize(64 + mlen);
      memcpy(cat.data(), rs + 32 * i, 32);
      memcpy(cat.data() + 32, pubs + 32 * i, 32);
      if (mlen) memcpy(cat.data() + 64, m, mlen);
      fast(cat.data(), cat.size(), digest);
    } else {
      sha512::Ctx c;
      sha512::init(&c);
      sha512::update(&c, rs + 32 * i, 32);
      sha512::update(&c, pubs + 32 * i, 32);
      sha512::update(&c, m, mlen);
      sha512::final(&c, digest);
    }
    sha512::mod_l(digest, dst + 32 * i);
  }
}

// 32B LE encoding -> 20 radix-2^13 limbs of the low 255 bits.
static inline void pack_limbs_row(const uint8_t enc[32], int32_t out[20]) {
  uint64_t w[4];
  for (int j = 0; j < 4; j++) {
    w[j] = 0;
    for (int b = 0; b < 8; b++) w[j] |= uint64_t(enc[8 * j + b]) << (8 * b);
  }
  w[3] &= 0x7fffffffffffffffULL;
  for (int limb = 0; limb < 20; limb++) {
    int bit = limb * 13;
    int word = bit >> 6, off = bit & 63;
    uint64_t v = w[word] >> off;
    if (off > 64 - 13 && word < 3) v |= w[word + 1] << (64 - off);
    out[limb] = int32_t(v & 0x1fff);
  }
}

static inline bool scalar_below_l(const uint8_t s[32]) {
  static const uint8_t L_BYTES[32] = {
      0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58, 0xd6, 0x9c, 0xf7,
      0xa2, 0xde, 0xf9, 0xde, 0x14, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
      0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10};
  for (int j = 31; j >= 0; j--) {
    if (s[j] < L_BYTES[j]) return true;
    if (s[j] > L_BYTES[j]) return false;
  }
  return false;  // s == L
}

// ed25519_challenges_buf(rs: n*32, pubs: n*32, msgs: buffer,
//                        offsets: (n+1)*int64) -> bytes (n*32)
// Columnar variant of ed25519_challenges: the whole batch hashes in one
// GIL-released call with no PySequence walk (message i is
// msgs[offsets[i]:offsets[i+1]]).
static PyObject *py_ed25519_challenges_buf(PyObject *, PyObject *args) {
  Py_buffer rs, pubs, msgs, offs;
  int no_ossl = 0;  // tests force the scalar fallback path
  if (!PyArg_ParseTuple(args, "y*y*y*y*|p", &rs, &pubs, &msgs, &offs,
                        &no_ossl))
    return nullptr;
  Py_ssize_t n = offs.len / 8 - 1;
  const int64_t *op = (const int64_t *)offs.buf;
  bool ok = n >= 0 && offs.len % 8 == 0 && rs.len >= 32 * n &&
            pubs.len >= 32 * n && offsets_valid(op, n, msgs.len);
  PyObject *out = ok ? PyBytes_FromStringAndSize(nullptr, n * 32) : nullptr;
  if (!out) {
    PyBuffer_Release(&rs);
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&msgs);
    PyBuffer_Release(&offs);
    if (ok) return nullptr;
    PyErr_SetString(PyExc_ValueError, "bad columnar challenge inputs");
    return nullptr;
  }
  uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(out);
  const uint8_t *rp = (const uint8_t *)rs.buf;
  const uint8_t *pp = (const uint8_t *)pubs.buf;
  const uint8_t *mp = (const uint8_t *)msgs.buf;
  ossl_sha512_fn fast = no_ossl ? nullptr : ossl_sha512();
  Py_BEGIN_ALLOW_THREADS
  parallel_ranges(n, 2048, [&](Py_ssize_t lo, Py_ssize_t hi) {
    challenges_range(rp, pp, mp, op, lo, hi, dst, fast);
  });
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&rs);
  PyBuffer_Release(&pubs);
  PyBuffer_Release(&msgs);
  PyBuffer_Release(&offs);
  return out;
}

// ed25519_prep_fused(pubs: n*32, sigs: n*64, msgs: buffer,
//                    offsets: (n+1)*int64, bucket) ->
//   (pub_limbs (bucket*20 i32), a_sign (bucket i32),
//    r_limbs (bucket*20 i32), r_sign (bucket i32),
//    s_bits (253*bucket i32, transposed), k_bits (253*bucket i32),
//    s_ok (bucket u8))
// The ENTIRE host prep of the XLA per-signature kernel (ops/backend.py
// prepare_batch: row pack + SHA-512 challenges + limb/bit pack + s<L) in
// one GIL-released native call. Padding lanes carry the identity layout
// (A = R = identity encoding, s = k = 0, s_ok = 1) like _pack_rows.
static PyObject *py_ed25519_prep_fused(PyObject *, PyObject *args) {
  Py_buffer pubs, sigs, msgs, offs;
  Py_ssize_t bucket;
  int no_ossl = 0;
  if (!PyArg_ParseTuple(args, "y*y*y*y*n|p", &pubs, &sigs, &msgs, &offs,
                        &bucket, &no_ossl))
    return nullptr;
  Py_ssize_t n = offs.len / 8 - 1;
  const int64_t *op = (const int64_t *)offs.buf;
  bool ok = n >= 0 && offs.len % 8 == 0 && bucket >= n && bucket > 0 &&
            pubs.len >= 32 * n && sigs.len >= 64 * n &&
            offsets_valid(op, n, msgs.len);
  if (!ok) {
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&sigs);
    PyBuffer_Release(&msgs);
    PyBuffer_Release(&offs);
    PyErr_SetString(PyExc_ValueError, "bad fused prep inputs");
    return nullptr;
  }
  PyObject *pub_limbs = PyBytes_FromStringAndSize(nullptr, bucket * 20 * 4);
  PyObject *a_sign = PyBytes_FromStringAndSize(nullptr, bucket * 4);
  PyObject *r_limbs = PyBytes_FromStringAndSize(nullptr, bucket * 20 * 4);
  PyObject *r_sign = PyBytes_FromStringAndSize(nullptr, bucket * 4);
  PyObject *s_bits = PyBytes_FromStringAndSize(nullptr, 253 * bucket * 4);
  PyObject *k_bits = PyBytes_FromStringAndSize(nullptr, 253 * bucket * 4);
  PyObject *s_okb = PyBytes_FromStringAndSize(nullptr, bucket);
  if (!pub_limbs || !a_sign || !r_limbs || !r_sign || !s_bits || !k_bits ||
      !s_okb) {
    Py_XDECREF(pub_limbs); Py_XDECREF(a_sign); Py_XDECREF(r_limbs);
    Py_XDECREF(r_sign); Py_XDECREF(s_bits); Py_XDECREF(k_bits);
    Py_XDECREF(s_okb);
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&sigs);
    PyBuffer_Release(&msgs);
    PyBuffer_Release(&offs);
    return nullptr;
  }
  int32_t *pl = (int32_t *)PyBytes_AS_STRING(pub_limbs);
  int32_t *as_ = (int32_t *)PyBytes_AS_STRING(a_sign);
  int32_t *rl = (int32_t *)PyBytes_AS_STRING(r_limbs);
  int32_t *rsn = (int32_t *)PyBytes_AS_STRING(r_sign);
  int32_t *sb = (int32_t *)PyBytes_AS_STRING(s_bits);
  int32_t *kb = (int32_t *)PyBytes_AS_STRING(k_bits);
  uint8_t *sok = (uint8_t *)PyBytes_AS_STRING(s_okb);
  const uint8_t *pp = (const uint8_t *)pubs.buf;
  const uint8_t *gp = (const uint8_t *)sigs.buf;
  const uint8_t *mp = (const uint8_t *)msgs.buf;
  ossl_sha512_fn fast = no_ossl ? nullptr : ossl_sha512();
  Py_BEGIN_ALLOW_THREADS
  // padding lanes first (bulk): zero bits/limbs, identity encodings
  memset(sb, 0, 253 * (size_t)bucket * 4);
  memset(kb, 0, 253 * (size_t)bucket * 4);
  memset(pl, 0, (size_t)bucket * 80);
  memset(rl, 0, (size_t)bucket * 80);
  memset(as_, 0, (size_t)bucket * 4);
  memset(rsn, 0, (size_t)bucket * 4);
  for (Py_ssize_t i = n; i < bucket; i++) {
    pl[20 * i] = 1;  // identity encoding y=1 -> limb0 = 1
    rl[20 * i] = 1;
    sok[i] = 1;
  }
  // per-row work is row-disjoint (the transposed bit arrays write column
  // i only), so the whole pack+hash pass fans out across the pool
  parallel_ranges(n, 1024, [&](Py_ssize_t lo, Py_ssize_t hi) {
    std::vector<uint8_t> cat;
    for (Py_ssize_t i = lo; i < hi; i++) {
      const uint8_t *pub = pp + 32 * i;
      const uint8_t *r = gp + 64 * i;
      const uint8_t *s = gp + 64 * i + 32;
      pack_limbs_row(pub, pl + 20 * i);
      pack_limbs_row(r, rl + 20 * i);
      as_[i] = pub[31] >> 7;
      rsn[i] = r[31] >> 7;
      sok[i] = scalar_below_l(s) ? 1 : 0;
      uint8_t digest[64], k[32];
      size_t mlen = (size_t)(op[i + 1] - op[i]);
      const uint8_t *m = mp + op[i];
      if (fast) {
        cat.resize(64 + mlen);
        memcpy(cat.data(), r, 32);
        memcpy(cat.data() + 32, pub, 32);
        if (mlen) memcpy(cat.data() + 64, m, mlen);
        fast(cat.data(), cat.size(), digest);
      } else {
        sha512::Ctx c;
        sha512::init(&c);
        sha512::update(&c, r, 32);
        sha512::update(&c, pub, 32);
        sha512::update(&c, m, mlen);
        sha512::final(&c, digest);
      }
      sha512::mod_l(digest, k);
      for (int b = 0; b < 253; b++) {
        sb[(Py_ssize_t)b * bucket + i] = (s[b >> 3] >> (b & 7)) & 1;
        kb[(Py_ssize_t)b * bucket + i] = (k[b >> 3] >> (b & 7)) & 1;
      }
    }
  });
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&pubs);
  PyBuffer_Release(&sigs);
  PyBuffer_Release(&msgs);
  PyBuffer_Release(&offs);
  PyObject *tup = PyTuple_Pack(7, pub_limbs, a_sign, r_limbs, r_sign, s_bits,
                               k_bits, s_okb);
  Py_DECREF(pub_limbs); Py_DECREF(a_sign); Py_DECREF(r_limbs);
  Py_DECREF(r_sign); Py_DECREF(s_bits); Py_DECREF(k_bits); Py_DECREF(s_okb);
  return tup;
}

// ed25519_rlc_prep(pubs: n*32, sigs: n*64, msgs: buffer,
//                  offsets: (n+1)*int64, z: total*32, m, total) ->
//   (k_enc (n*32), S||U ((total/m + total)*32), s_ok (total u8))
// Fused host prep of the device RLC fast-accept kernel: SHA-512
// challenges + the per-lane 128x256-bit mod-L scalar mul-adds + s<L flags
// in one GIL-released call (ops/pallas_rlc.py prepare_rlc). total (a
// multiple of m, >= n) is the padded live-lane signature count; rows
// n..total-1 are padding lanes (s = k = 0, s_ok = 1, U = 0).
static PyObject *py_ed25519_rlc_prep(PyObject *, PyObject *args) {
  Py_buffer pubs, sigs, msgs, offs, zb;
  Py_ssize_t m, total;
  int no_ossl = 0;
  if (!PyArg_ParseTuple(args, "y*y*y*y*y*nn|p", &pubs, &sigs, &msgs, &offs,
                        &zb, &m, &total, &no_ossl))
    return nullptr;
  Py_ssize_t n = offs.len / 8 - 1;
  const int64_t *op = (const int64_t *)offs.buf;
  bool ok = n >= 0 && offs.len % 8 == 0 && m > 0 && total >= n &&
            total % m == 0 && pubs.len >= 32 * n && sigs.len >= 64 * n &&
            zb.len >= 32 * total && offsets_valid(op, n, msgs.len);
  PyObject *k_out = nullptr, *su_out = nullptr, *sok_out = nullptr;
  Py_ssize_t g = ok ? total / m : 0;
  if (ok) {
    k_out = PyBytes_FromStringAndSize(nullptr, n * 32);
    su_out = PyBytes_FromStringAndSize(nullptr, 32 * (g + total));
    sok_out = PyBytes_FromStringAndSize(nullptr, total);
  }
  if (!k_out || !su_out || !sok_out) {
    Py_XDECREF(k_out); Py_XDECREF(su_out); Py_XDECREF(sok_out);
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&sigs);
    PyBuffer_Release(&msgs);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&zb);
    if (!ok) PyErr_SetString(PyExc_ValueError, "bad rlc fused prep inputs");
    return nullptr;
  }
  uint8_t *kd = (uint8_t *)PyBytes_AS_STRING(k_out);
  uint8_t *S = (uint8_t *)PyBytes_AS_STRING(su_out);
  uint8_t *U = S + 32 * g;
  uint8_t *sok = (uint8_t *)PyBytes_AS_STRING(sok_out);
  const uint8_t *pp = (const uint8_t *)pubs.buf;
  const uint8_t *gp = (const uint8_t *)sigs.buf;
  const uint8_t *mp = (const uint8_t *)msgs.buf;
  const uint8_t *zp = (const uint8_t *)zb.buf;
  ossl_sha512_fn fast = no_ossl ? nullptr : ossl_sha512();
  Py_BEGIN_ALLOW_THREADS
  // lane-disjoint: each lane reads rows base..base+m-1 and writes only
  // its own S/U/k/s_ok slots
  parallel_ranges(g, 256, [&](Py_ssize_t lane_lo, Py_ssize_t lane_hi) {
    std::vector<uint8_t> cat;
    for (Py_ssize_t lane = lane_lo; lane < lane_hi; lane++) {
      Py_ssize_t base = lane * m;
      for (Py_ssize_t i = base; i < base + m && i < n; i++) {
        const uint8_t *pub = pp + 32 * i;
        const uint8_t *r = gp + 64 * i;
        sok[i] = scalar_below_l(gp + 64 * i + 32) ? 1 : 0;
        uint8_t digest[64];
        size_t mlen = (size_t)(op[i + 1] - op[i]);
        const uint8_t *msg = mp + op[i];
        if (fast) {
          cat.resize(64 + mlen);
          memcpy(cat.data(), r, 32);
          memcpy(cat.data() + 32, pub, 32);
          if (mlen) memcpy(cat.data() + 64, msg, mlen);
          fast(cat.data(), cat.size(), digest);
        } else {
          sha512::Ctx c;
          sha512::init(&c);
          sha512::update(&c, r, 32);
          sha512::update(&c, pub, 32);
          sha512::update(&c, msg, mlen);
          sha512::final(&c, digest);
        }
        sha512::mod_l(digest, kd + 32 * i);
      }
      for (Py_ssize_t i = base < n ? (base + m < n ? base + m : n) : base;
           i < base + m; i++)
        sok[i] = 1;  // padding rows: s = 0 < L
      // per-lane scalar mul-adds (ed25519_rlc_scalars semantics);
      // padding rows contribute s = k = 0 -> U = 0, no S term
      uint8_t wide[64] = {0};
      if (base < n) memcpy(wide, gp + 64 * base + 32, 32);
      sha512::mod_l(wide, S + 32 * lane);
      if (base < n)
        memcpy(U + 32 * base, kd + 32 * base, 32);
      else
        memset(U + 32 * base, 0, 32);
      for (Py_ssize_t j = 1; j < m; j++) {
        Py_ssize_t i = base + j;
        if (i >= n) {
          memset(U + 32 * i, 0, 32);
          continue;
        }
        uint8_t zs[32];
        ed::sc_mul(zs, zp + 32 * i, gp + 64 * i + 32);
        ed::sc_add(S + 32 * lane, S + 32 * lane, zs);
        ed::sc_mul(U + 32 * i, zp + 32 * i, kd + 32 * i);
      }
    }
  });
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&pubs);
  PyBuffer_Release(&sigs);
  PyBuffer_Release(&msgs);
  PyBuffer_Release(&offs);
  PyBuffer_Release(&zb);
  PyObject *tup = PyTuple_Pack(3, k_out, su_out, sok_out);
  Py_DECREF(k_out); Py_DECREF(su_out); Py_DECREF(sok_out);
  return tup;
}

// vote_sign_bytes_batch_buf(prefix, suffix, times: n*16B LE int64 pairs)
//   -> (bytes buffer, bytes offsets ((n+1) int64 LE))
// Buffer-writing variant of vote_sign_bytes_batch: composes every
// signature's canonical sign bytes into ONE contiguous buffer + offset
// table (the EntryBlock msgs form) with the GIL released — no per-lane
// PyBytes objects or list handling.
static PyObject *py_vote_sign_bytes_batch_buf(PyObject *, PyObject *args) {
  Py_buffer prefix, suffix, times;
  if (!PyArg_ParseTuple(args, "y*y*y*", &prefix, &suffix, &times))
    return nullptr;
  if (times.len % 16) {
    PyBuffer_Release(&prefix);
    PyBuffer_Release(&suffix);
    PyBuffer_Release(&times);
    PyErr_SetString(PyExc_ValueError,
                    "times must be n*16 bytes of (seconds, nanos) pairs");
    return nullptr;
  }
  Py_ssize_t n = times.len / 16;
  const uint8_t *tp = (const uint8_t *)times.buf;
  PyObject *offs_out = PyBytes_FromStringAndSize(nullptr, (n + 1) * 8);
  if (!offs_out) {
    PyBuffer_Release(&prefix);
    PyBuffer_Release(&suffix);
    PyBuffer_Release(&times);
    return nullptr;
  }
  int64_t *offs = (int64_t *)PyBytes_AS_STRING(offs_out);
  // pass 1: exact per-record lengths -> offsets (GIL released; raw bufs)
  Py_BEGIN_ALLOW_THREADS
  offs[0] = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t secs, nanos;
    memcpy(&secs, tp + 16 * i, 8);
    memcpy(&nanos, tp + 16 * i + 8, 8);
    uint8_t scratch[10];
    size_t tn = 0;
    if (secs != 0) tn += 1 + put_uvarint(scratch, (uint64_t)secs);
    if (nanos != 0) tn += 1 + put_uvarint(scratch, (uint64_t)nanos);
    uint8_t tscratch[10];
    size_t mn = 1 + put_uvarint(tscratch, tn) + tn;
    size_t body = (size_t)prefix.len + mn + (size_t)suffix.len;
    size_t hn = put_uvarint(tscratch, body);
    offs[i + 1] = offs[i] + (int64_t)(hn + body);
  }
  Py_END_ALLOW_THREADS
  PyObject *buf_out = PyBytes_FromStringAndSize(nullptr, offs[n]);
  if (!buf_out) {
    Py_DECREF(offs_out);
    PyBuffer_Release(&prefix);
    PyBuffer_Release(&suffix);
    PyBuffer_Release(&times);
    return nullptr;
  }
  uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(buf_out);
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t i = 0; i < n; i++) {
    int64_t secs, nanos;
    memcpy(&secs, tp + 16 * i, 8);
    memcpy(&nanos, tp + 16 * i + 8, 8);
    uint8_t ts_body[22];
    size_t tn = 0;
    if (secs != 0) {
      ts_body[tn++] = 0x08;
      tn += put_uvarint(ts_body + tn, (uint64_t)secs);
    }
    if (nanos != 0) {
      ts_body[tn++] = 0x10;
      tn += put_uvarint(ts_body + tn, (uint64_t)nanos);
    }
    uint8_t mid[32];
    size_t mn = 0;
    mid[mn++] = 0x2a;
    mn += put_uvarint(mid + mn, tn);
    memcpy(mid + mn, ts_body, tn);
    mn += tn;
    size_t body = (size_t)prefix.len + mn + (size_t)suffix.len;
    uint8_t *p = dst + offs[i];
    p += put_uvarint(p, body);
    memcpy(p, prefix.buf, prefix.len);
    p += prefix.len;
    memcpy(p, mid, mn);
    p += mn;
    memcpy(p, suffix.buf, suffix.len);
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&prefix);
  PyBuffer_Release(&suffix);
  PyBuffer_Release(&times);
  PyObject *tup = PyTuple_Pack(2, buf_out, offs_out);
  Py_DECREF(buf_out);
  Py_DECREF(offs_out);
  return tup;
}

// commit_prep_fused(flags: n u8, sigs: n*64, ts_secs: n*8 LE i64,
//                   ts_nanos: n*4 LE i32, pubs: n*32, power: n*8 LE i64,
//                   prefix_commit, prefix_nil, suffix,
//                   threshold, mode, ram_max_len)
//   -> (sel (m*8 LE i64), tallied)                       when tally fails
//   -> (sel, tallied, pub (m*32), sig (m*64), msgs, offs ((m+1)*8),
//       ram_hi|None, ram_lo|None, counts|None)           otherwise
//
// The ENTIRE commit-side host prep of types.verify_commit in one
// GIL-released call over CommitBlock + ValidatorSet columns
// (ops/commit_prep.py): flag selection, voting-power tally vs the 2/3
// threshold (validation.go:152 loop semantics, incl. early-stop keeping
// the crossing lane), canonical sign-bytes composition into ONE
// contiguous buffer (vote_sign_bytes_batch_buf layout, prefix chosen per
// lane flag), pub/sig row gather, and — when ram_max_len > 0 and every
// message fits — the device-hash kernel's padded R||A||M SHA blocks
// word-packed per row (ops/sha512.pad_ram_block layout).
//
// mode bits: 1 = select COMMIT lanes only (else all non-ABSENT),
//            2 = tally only COMMIT lanes, 4 = early-stop past threshold.
static size_t uvarint_len(uint64_t v) {
  size_t i = 1;
  while (v >= 0x80) {
    v >>= 7;
    i++;
  }
  return i;
}

static PyObject *py_commit_prep_fused(PyObject *, PyObject *args) {
  Py_buffer flags, sigs, tsec, tnan, pubs, power, pfxc, pfxn, sfx;
  Py_ssize_t threshold, mode, ram_max_len;
  if (!PyArg_ParseTuple(args, "y*y*y*y*y*y*y*y*y*nnn", &flags, &sigs, &tsec,
                        &tnan, &pubs, &power, &pfxc, &pfxn, &sfx, &threshold,
                        &mode, &ram_max_len))
    return nullptr;
  Py_ssize_t n = flags.len;
  auto release_all = [&]() {
    PyBuffer_Release(&flags);
    PyBuffer_Release(&sigs);
    PyBuffer_Release(&tsec);
    PyBuffer_Release(&tnan);
    PyBuffer_Release(&pubs);
    PyBuffer_Release(&power);
    PyBuffer_Release(&pfxc);
    PyBuffer_Release(&pfxn);
    PyBuffer_Release(&sfx);
  };
  if (sigs.len < 64 * n || tsec.len < 8 * n || tnan.len < 4 * n ||
      pubs.len < 32 * n || power.len < 8 * n || ram_max_len < 0) {
    release_all();
    PyErr_SetString(PyExc_ValueError, "bad commit prep inputs");
    return nullptr;
  }
  const uint8_t *fp = (const uint8_t *)flags.buf;
  const uint8_t *gp = (const uint8_t *)sigs.buf;
  const uint8_t *pp = (const uint8_t *)pubs.buf;
  const int64_t *sp = (const int64_t *)tsec.buf;
  const int32_t *np_ = (const int32_t *)tnan.buf;
  const int64_t *pw = (const int64_t *)power.buf;
  const bool sel_commit = mode & 1, count_fb = mode & 2, early = mode & 4;
  std::vector<int64_t> sel;
  int64_t tallied = 0;
  Py_BEGIN_ALLOW_THREADS
  sel.reserve((size_t)n);
  for (Py_ssize_t i = 0; i < n; i++) {
    uint8_t f = fp[i];
    if (sel_commit ? (f != 2) : (f == 1)) continue;
    sel.push_back((int64_t)i);
    if (!count_fb || f == 2) tallied += pw[i];
    if (early && tallied > (int64_t)threshold) break;
  }
  Py_END_ALLOW_THREADS
  Py_ssize_t m = (Py_ssize_t)sel.size();
  PyObject *sel_out = PyBytes_FromStringAndSize(
      (const char *)sel.data(), m * 8);
  if (!sel_out) {
    release_all();
    return nullptr;
  }
  if (tallied <= (int64_t)threshold) {
    release_all();
    PyObject *t = PyLong_FromLongLong((long long)tallied);
    PyObject *tup = t ? PyTuple_Pack(2, sel_out, t) : nullptr;
    Py_XDECREF(t);
    Py_DECREF(sel_out);
    return tup;
  }
  // pass 2: per-record sign-bytes lengths -> offsets (+ ram feasibility)
  PyObject *offs_out = PyBytes_FromStringAndSize(nullptr, (m + 1) * 8);
  if (!offs_out) {
    Py_DECREF(sel_out);
    release_all();
    return nullptr;
  }
  int64_t *offs = (int64_t *)PyBytes_AS_STRING(offs_out);
  int64_t max_msg = 0;
  Py_BEGIN_ALLOW_THREADS
  offs[0] = 0;
  for (Py_ssize_t j = 0; j < m; j++) {
    Py_ssize_t i = (Py_ssize_t)sel[(size_t)j];
    uint64_t secs = (uint64_t)sp[i];
    uint64_t nanos = (uint64_t)(int64_t)np_[i];
    size_t tn = (secs ? 1 + uvarint_len(secs) : 0) +
                (nanos ? 1 + uvarint_len(nanos) : 0);
    size_t plen = fp[i] == 3 ? (size_t)pfxn.len : (size_t)pfxc.len;
    size_t body = plen + 1 + uvarint_len(tn) + tn + (size_t)sfx.len;
    int64_t rec = (int64_t)(uvarint_len(body) + body);
    if (rec > max_msg) max_msg = rec;
    offs[j + 1] = offs[j] + rec;
  }
  Py_END_ALLOW_THREADS
  bool want_ram = ram_max_len > 0 && 64 + max_msg <= (int64_t)ram_max_len;
  Py_ssize_t nblock = want_ram ? (ram_max_len + 17 + 127) / 128 : 0;
  PyObject *pub_out = PyBytes_FromStringAndSize(nullptr, m * 32);
  PyObject *sig_out = PyBytes_FromStringAndSize(nullptr, m * 64);
  PyObject *msgs_out = PyBytes_FromStringAndSize(nullptr, offs[m]);
  PyObject *hi_out = nullptr, *lo_out = nullptr, *cnt_out = nullptr;
  if (want_ram) {
    hi_out = PyBytes_FromStringAndSize(nullptr, m * nblock * 16 * 4);
    lo_out = PyBytes_FromStringAndSize(nullptr, m * nblock * 16 * 4);
    cnt_out = PyBytes_FromStringAndSize(nullptr, m * 4);
  }
  if (!pub_out || !sig_out || !msgs_out ||
      (want_ram && (!hi_out || !lo_out || !cnt_out))) {
    Py_XDECREF(pub_out); Py_XDECREF(sig_out); Py_XDECREF(msgs_out);
    Py_XDECREF(hi_out); Py_XDECREF(lo_out); Py_XDECREF(cnt_out);
    Py_DECREF(sel_out); Py_DECREF(offs_out);
    release_all();
    return nullptr;
  }
  uint8_t *pub_d = (uint8_t *)PyBytes_AS_STRING(pub_out);
  uint8_t *sig_d = (uint8_t *)PyBytes_AS_STRING(sig_out);
  uint8_t *msg_d = (uint8_t *)PyBytes_AS_STRING(msgs_out);
  uint32_t *hi_d = want_ram ? (uint32_t *)PyBytes_AS_STRING(hi_out) : nullptr;
  uint32_t *lo_d = want_ram ? (uint32_t *)PyBytes_AS_STRING(lo_out) : nullptr;
  int32_t *cnt_d = want_ram ? (int32_t *)PyBytes_AS_STRING(cnt_out) : nullptr;
  Py_BEGIN_ALLOW_THREADS
  parallel_ranges(m, 1024, [&](Py_ssize_t lo_j, Py_ssize_t hi_j) {
    std::vector<uint8_t> ram_row;
    if (want_ram) ram_row.resize((size_t)nblock * 128);
    for (Py_ssize_t j = lo_j; j < hi_j; j++) {
      Py_ssize_t i = (Py_ssize_t)sel[(size_t)j];
      memcpy(pub_d + 32 * j, pp + 32 * i, 32);
      memcpy(sig_d + 64 * j, gp + 64 * i, 64);
      // compose the canonical vote sign bytes (vote_sign_bytes_batch_buf
      // layout: delimited(prefix + Timestamp-field(5) + suffix))
      uint64_t secs = (uint64_t)sp[i];
      uint64_t nanos = (uint64_t)(int64_t)np_[i];
      uint8_t ts_body[22];
      size_t tn = 0;
      if (secs) {
        ts_body[tn++] = 0x08;
        tn += put_uvarint(ts_body + tn, secs);
      }
      if (nanos) {
        ts_body[tn++] = 0x10;
        tn += put_uvarint(ts_body + tn, nanos);
      }
      const uint8_t *pfx =
          fp[i] == 3 ? (const uint8_t *)pfxn.buf : (const uint8_t *)pfxc.buf;
      size_t plen = fp[i] == 3 ? (size_t)pfxn.len : (size_t)pfxc.len;
      uint8_t mid[32];
      size_t mn = 0;
      mid[mn++] = 0x2a;
      mn += put_uvarint(mid + mn, tn);
      memcpy(mid + mn, ts_body, tn);
      mn += tn;
      size_t body = plen + mn + (size_t)sfx.len;
      uint8_t *p = msg_d + offs[j];
      p += put_uvarint(p, body);
      memcpy(p, pfx, plen);
      p += plen;
      memcpy(p, mid, mn);
      p += mn;
      memcpy(p, sfx.buf, sfx.len);
      if (want_ram) {
        size_t mlen = (size_t)(offs[j + 1] - offs[j]);
        size_t tot = 64 + mlen;
        memset(ram_row.data(), 0, ram_row.size());
        memcpy(ram_row.data(), gp + 64 * i, 32);       // R
        memcpy(ram_row.data() + 32, pp + 32 * i, 32);  // A
        memcpy(ram_row.data() + 64, msg_d + offs[j], mlen);
        ram_row[tot] = 0x80;
        size_t blocks = (tot + 17 + 127) / 128;
        uint64_t bitlen = (uint64_t)tot * 8;
        uint8_t *tail = ram_row.data() + blocks * 128 - 8;
        for (int b = 0; b < 8; b++)
          tail[b] = (uint8_t)(bitlen >> (8 * (7 - b)));
        cnt_d[j] = (int32_t)blocks;
        uint32_t *hi_row = hi_d + (size_t)j * nblock * 16;
        uint32_t *lo_row = lo_d + (size_t)j * nblock * 16;
        for (Py_ssize_t w = 0; w < nblock * 16; w++) {
          const uint8_t *q = ram_row.data() + 8 * w;
          hi_row[w] = ((uint32_t)q[0] << 24) | ((uint32_t)q[1] << 16) |
                      ((uint32_t)q[2] << 8) | (uint32_t)q[3];
          lo_row[w] = ((uint32_t)q[4] << 24) | ((uint32_t)q[5] << 16) |
                      ((uint32_t)q[6] << 8) | (uint32_t)q[7];
        }
      }
    }
  });
  Py_END_ALLOW_THREADS
  release_all();
  PyObject *t = PyLong_FromLongLong((long long)tallied);
  PyObject *none = Py_None;
  PyObject *tup =
      t ? PyTuple_Pack(9, sel_out, t, pub_out, sig_out, msgs_out, offs_out,
                       want_ram ? hi_out : none, want_ram ? lo_out : none,
                       want_ram ? cnt_out : none)
        : nullptr;
  Py_XDECREF(t);
  Py_DECREF(sel_out); Py_DECREF(pub_out); Py_DECREF(sig_out);
  Py_DECREF(msgs_out); Py_DECREF(offs_out);
  Py_XDECREF(hi_out); Py_XDECREF(lo_out); Py_XDECREF(cnt_out);
  return tup;
}

static PyMethodDef Methods[] = {
    {"commit_prep_fused", py_commit_prep_fused, METH_VARARGS,
     "Fused columnar commit prep: selection + tally + sign-bytes + "
     "pub/sig gather + device-hash RAM blocks, one GIL-released call"},
    {"ed25519_batch_verify", py_ed25519_batch_verify, METH_VARARGS,
     "Host RLC batch ed25519 verification (Pippenger MSM); returns bool"},
    {"ed25519_rlc_scalars", py_ed25519_rlc_scalars, METH_VARARGS,
     "Per-lane RLC scalar prep for the device fast-accept kernel"},
    {"vote_sign_bytes_batch", py_vote_sign_bytes_batch, METH_VARARGS,
     "Batch canonical vote sign-bytes composition from a template"},
    {"ed25519_challenges", py_ed25519_challenges, METH_VARARGS,
     "Batch k = SHA512(R||A||M) mod L challenge scalars (32B LE each)"},
    {"ed25519_challenges_buf", py_ed25519_challenges_buf, METH_VARARGS,
     "Columnar challenge scalars from a concatenated msgs buffer + offsets"},
    {"ed25519_prep_fused", py_ed25519_prep_fused, METH_VARARGS,
     "Fused columnar host prep for the XLA per-sig kernel (one GIL-released call)"},
    {"ed25519_rlc_prep", py_ed25519_rlc_prep, METH_VARARGS,
     "Fused columnar challenges + per-lane RLC scalar prep + s<L flags"},
    {"vote_sign_bytes_batch_buf", py_vote_sign_bytes_batch_buf, METH_VARARGS,
     "Batch sign-bytes composed into one contiguous buffer + offset table"},
    {"sr25519_verify_batch", py_sr25519_verify_batch, METH_VARARGS,
     "Batch schnorrkel sr25519 verification (R == [s]B - [k]A)"},
    {"merkle_root", py_merkle_root, METH_VARARGS,
     "RFC-6962 merkle root of a list of byte strings"},
    {"sha256_many", py_sha256_many, METH_VARARGS,
     "SHA-256 of each item, concatenated"},
    {"pack_le_limbs", py_pack_le_limbs, METH_VARARGS,
     "pack 32B LE encodings into 13-bit limb arrays"},
    {"sr25519_challenges", py_sr25519_challenges, METH_VARARGS,
     "Batch merlin signing-transcript challenges for sr25519 verification"},
    {"pack_bits_le", py_pack_bits_le, METH_VARARGS,
     "pack 32B LE scalars into transposed bit arrays"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "tm_native",
                                       nullptr, -1, Methods};

PyMODINIT_FUNC PyInit_tm_native(void) { return PyModule_Create(&moduledef); }
