// tm_native — native host-side hot paths for the TPU verification engine.
//
// The framework's compute path is JAX/XLA on the device; this module is the
// native runtime seam around it (SURVEY.md §2: the batch verification
// engine's host half): the per-batch packing that turns 10k signature
// triples into kernel input arrays, and RFC-6962 merkle hashing for part
// sets / block data. CPython C API (no pybind11 in this image), built by
// native/build.py via setuptools.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <cstdint>
#include <cstring>
#include <vector>

// --------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), self-contained.

namespace sha256 {

static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

struct Ctx {
  uint32_t h[8];
  uint64_t len;
  uint8_t buf[64];
  size_t buflen;
};

static void init(Ctx *c) {
  static const uint32_t iv[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                 0xa54ff53a, 0x510e527f, 0x9b05688c,
                                 0x1f83d9ab, 0x5be0cd19};
  memcpy(c->h, iv, sizeof(iv));
  c->len = 0;
  c->buflen = 0;
}

static void compress(Ctx *c, const uint8_t *p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void update(Ctx *c, const uint8_t *data, size_t n) {
  c->len += n;
  if (c->buflen) {
    size_t take = 64 - c->buflen;
    if (take > n) take = n;
    memcpy(c->buf + c->buflen, data, take);
    c->buflen += take;
    data += take;
    n -= take;
    if (c->buflen == 64) {
      compress(c, c->buf);
      c->buflen = 0;
    }
  }
  while (n >= 64) {
    compress(c, data);
    data += 64;
    n -= 64;
  }
  if (n) {
    memcpy(c->buf, data, n);
    c->buflen = n;
  }
}

static void final(Ctx *c, uint8_t out[32]) {
  uint64_t bitlen = c->len * 8;
  uint8_t pad = 0x80;
  update(c, &pad, 1);
  uint8_t z = 0;
  while (c->buflen != 56) update(c, &z, 1);
  uint8_t lenb[8];
  for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bitlen >> (56 - 8 * i));
  update(c, lenb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = uint8_t(c->h[i] >> 24);
    out[4 * i + 1] = uint8_t(c->h[i] >> 16);
    out[4 * i + 2] = uint8_t(c->h[i] >> 8);
    out[4 * i + 3] = uint8_t(c->h[i]);
  }
}

static void digest(const uint8_t *data, size_t n, uint8_t out[32]) {
  Ctx c;
  init(&c);
  update(&c, data, n);
  final(&c, out);
}

}  // namespace sha256

// --------------------------------------------------------------------------
// SHA-512 (FIPS 180-4) + reduction mod the ed25519 group order L — the
// host half of the batch challenge k = SHA512(R||A||M) mod L
// (crypto/ed25519/ed25519.go verification; ops/pallas_verify.py
// prepare_compact). One C call replaces a per-signature Python loop that
// measured ~50% of end-to-end batch time on a loaded host.

namespace sha512 {

static const uint64_t K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

struct Ctx {
  uint64_t h[8];
  uint8_t buf[128];
  size_t buflen;
  uint64_t total;  // bytes
};

static void init(Ctx *c) {
  static const uint64_t H0[8] = {
      0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
      0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
      0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  memcpy(c->h, H0, sizeof H0);
  c->buflen = 0;
  c->total = 0;
}

static void compress(Ctx *c, const uint8_t *p) {
  uint64_t w[80];
  for (int i = 0; i < 16; i++) {
    w[i] = 0;
    for (int b = 0; b < 8; b++) w[i] = (w[i] << 8) | p[8 * i + b];
  }
  for (int i = 16; i < 80; i++) {
    uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3], e = c->h[4],
           f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 80; i++) {
    uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = h + S1 + ch + K[i] + w[i];
    uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
    uint64_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint64_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

static void update(Ctx *c, const uint8_t *data, size_t n) {
  c->total += n;
  if (c->buflen) {
    size_t take = 128 - c->buflen;
    if (take > n) take = n;
    memcpy(c->buf + c->buflen, data, take);
    c->buflen += take;
    data += take;
    n -= take;
    if (c->buflen == 128) {
      compress(c, c->buf);
      c->buflen = 0;
    }
  }
  while (n >= 128) {
    compress(c, data);
    data += 128;
    n -= 128;
  }
  if (n) {
    memcpy(c->buf, data, n);
    c->buflen = n;
  }
}

static void final(Ctx *c, uint8_t out[64]) {
  uint64_t bits = c->total * 8;
  uint8_t pad = 0x80;
  update(c, &pad, 1);
  uint8_t z = 0;
  while (c->buflen != 112) update(c, &z, 1);
  uint8_t len[16] = {0};
  for (int i = 0; i < 8; i++) len[15 - i] = uint8_t(bits >> (8 * i));
  // counter only tracks real input; neutralize padding's contribution
  c->total = 0;
  update(c, len, 16);
  for (int i = 0; i < 8; i++)
    for (int b = 0; b < 8; b++) out[8 * i + b] = uint8_t(c->h[i] >> (56 - 8 * b));
}

// k = digest (64B little-endian integer) mod L, L = 2^252 + C,
// C = 27742317777372353535851937790883648493. Since 2^252 ≡ -C (mod L),
// each fold rewrites x = hi*2^252 + lo as lo + K_r - hi*C where K_r is a
// precomputed multiple of L large enough to keep the result positive
// (K1 = L<<133, K2 = L<<7, K3 = L; sizes 512 -> 386 -> 260 -> 254 bits),
// then conditionally subtracts L (at most 3 times; x3 < 2^254 < 4L).
static const uint64_t C_LO = 0x5812631a5cf5d3edULL;
static const uint64_t C_HI = 0x14def9dea2f79cd6ULL;  // C = C_HI<<64 | C_LO
static const uint64_t L_LIMBS[4] = {C_LO, C_HI, 0, 0x1000000000000000ULL};
static const uint64_t FOLD_K[3][7] = {
    {0x0000000000000000ULL, 0x0000000000000000ULL, 0x024c634b9eba7da0ULL,
     0x9bdf3bd45ef39acbULL, 0x0000000000000002ULL, 0x0000000000000000ULL,
     0x0000000000000002ULL},
    {0x09318d2e7ae9f680ULL, 0x6f7cef517bce6b2cULL, 0x000000000000000aULL,
     0x0000000000000000ULL, 0x0000000000000008ULL, 0x0000000000000000ULL,
     0x0000000000000000ULL},
    {0x5812631a5cf5d3edULL, 0x14def9dea2f79cd6ULL, 0x0000000000000000ULL,
     0x1000000000000000ULL, 0x0000000000000000ULL, 0x0000000000000000ULL,
     0x0000000000000000ULL}};

static void mod_l(const uint8_t digest[64], uint8_t out[32]) {
  // x: 8 limbs LE; every intermediate fits in 7 limbs after round 1
  uint64_t x[8] = {0};
  for (int i = 0; i < 8; i++)
    for (int b = 0; b < 8; b++) x[i] |= uint64_t(digest[8 * i + b]) << (8 * b);
  for (int round = 0; round < 3; round++) {
    // hi = x >> 252 (up to 5 limbs), lo = x & (2^252 - 1)
    uint64_t hi[5];
    for (int i = 0; i < 5; i++) {
      uint64_t v = (i + 3 < 8) ? (x[i + 3] >> 60) : 0;
      if (i + 4 < 8) v |= x[i + 4] << 4;
      hi[i] = v;
    }
    uint64_t lo[4] = {x[0], x[1], x[2], x[3] & 0x0fffffffffffffffULL};
    // t = hi * C (7 limbs)
    uint64_t t[7];
    unsigned __int128 carry = 0;
    for (int i = 0; i < 7; i++) {
      unsigned __int128 acc = carry;
      if (i < 5) acc += (unsigned __int128)hi[i] * C_LO;
      if (i >= 1 && i <= 5) acc += (unsigned __int128)hi[i - 1] * C_HI;
      t[i] = uint64_t(acc);
      carry = acc >> 64;
    }
    // x = lo + K_round - t  (guaranteed non-negative)
    memset(x, 0, sizeof x);
    unsigned __int128 acc2 = 0;
    uint64_t borrow = 0;
    for (int i = 0; i < 7; i++) {
      acc2 += (i < 4 ? lo[i] : 0);
      acc2 += FOLD_K[round][i];
      uint64_t add = uint64_t(acc2);
      unsigned __int128 d = (unsigned __int128)add - t[i] - borrow;
      x[i] = uint64_t(d);
      borrow = (uint64_t)(d >> 64) ? 1 : 0;
      acc2 >>= 64;
    }
  }
  // now x < 2^254 < 4L: subtract L while x >= L
  for (int rep = 0; rep < 3; rep++) {
    bool ge = true;
    for (int i = 3; i >= 0; i--) {
      if (x[i] > L_LIMBS[i]) break;
      if (x[i] < L_LIMBS[i]) { ge = false; break; }
    }
    if (!ge) break;
    uint64_t borrow = 0;
    for (int i = 0; i < 4; i++) {
      unsigned __int128 d = (unsigned __int128)x[i] - L_LIMBS[i] - borrow;
      x[i] = uint64_t(d);
      borrow = (uint64_t)(d >> 64) ? 1 : 0;
    }
  }
  for (int i = 0; i < 4; i++)
    for (int b = 0; b < 8; b++) out[8 * i + b] = uint8_t(x[i] >> (8 * b));
}

}  // namespace sha512

// --------------------------------------------------------------------------
// RFC-6962 merkle (crypto/merkle/tree.go semantics)

static void leaf_hash(const uint8_t *data, size_t n, uint8_t out[32]) {
  sha256::Ctx c;
  sha256::init(&c);
  uint8_t prefix = 0x00;
  sha256::update(&c, &prefix, 1);
  sha256::update(&c, data, n);
  sha256::final(&c, out);
}

static void inner_hash(const uint8_t *l, const uint8_t *r, uint8_t out[32]) {
  sha256::Ctx c;
  sha256::init(&c);
  uint8_t prefix = 0x01;
  sha256::update(&c, &prefix, 1);
  sha256::update(&c, l, 32);
  sha256::update(&c, r, 32);
  sha256::final(&c, out);
}

static size_t split_point(size_t n) {
  size_t k = 1;
  while (k * 2 < n) k *= 2;
  return k;
}

static void merkle_root_hashes(std::vector<uint8_t> &hashes, size_t lo,
                               size_t hi, uint8_t out[32]) {
  size_t n = hi - lo;
  if (n == 1) {
    memcpy(out, &hashes[32 * lo], 32);
    return;
  }
  size_t k = split_point(n);
  uint8_t left[32], right[32];
  merkle_root_hashes(hashes, lo, lo + k, left);
  merkle_root_hashes(hashes, lo + k, hi, right);
  inner_hash(left, right, out);
}

// merkle_root(items: list[bytes]) -> bytes
static PyObject *py_merkle_root(PyObject *, PyObject *args) {
  PyObject *items;
  if (!PyArg_ParseTuple(args, "O", &items)) return nullptr;
  PyObject *seq = PySequence_Fast(items, "expected a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  uint8_t out[32];
  if (n == 0) {
    sha256::digest(nullptr, 0, out);
    Py_DECREF(seq);
    return PyBytes_FromStringAndSize((const char *)out, 32);
  }
  std::vector<uint8_t> hashes(size_t(n) * 32);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(item, &buf, &len) < 0) {
      Py_DECREF(seq);
      return nullptr;
    }
    leaf_hash((const uint8_t *)buf, size_t(len), &hashes[32 * size_t(i)]);
  }
  Py_DECREF(seq);
  merkle_root_hashes(hashes, 0, size_t(n), out);
  return PyBytes_FromStringAndSize((const char *)out, 32);
}

// sha256_many(items: list[bytes]) -> bytes (concatenated 32B digests)
static PyObject *py_sha256_many(PyObject *, PyObject *args) {
  PyObject *items;
  if (!PyArg_ParseTuple(args, "O", &items)) return nullptr;
  PyObject *seq = PySequence_Fast(items, "expected a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 32);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t *op = (uint8_t *)PyBytes_AS_STRING(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *buf;
    Py_ssize_t len;
    if (PyBytes_AsStringAndSize(item, &buf, &len) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    sha256::digest((const uint8_t *)buf, size_t(len), op + 32 * i);
  }
  Py_DECREF(seq);
  return out;
}

// pack_le_limbs(encodings: bytes (n*32), n: int) -> bytes (n*20 int32 LE)
// Low 255 bits of each 32-byte little-endian encoding into 20 radix-2^13
// limbs — the fe.py input format (ops/backend.py _pack_le_limbs).
static PyObject *py_pack_le_limbs(PyObject *, PyObject *args) {
  Py_buffer view;
  Py_ssize_t n;
  if (!PyArg_ParseTuple(args, "y*n", &view, &n)) return nullptr;
  if (view.len < n * 32) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "buffer too small");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 20 * 4);
  if (!out) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  int32_t *op = (int32_t *)PyBytes_AS_STRING(out);
  const uint8_t *ip = (const uint8_t *)view.buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    const uint8_t *enc = ip + 32 * i;
    // 255-bit value as four 64-bit words (top bit cleared)
    uint64_t w[4];
    for (int j = 0; j < 4; j++) {
      w[j] = 0;
      for (int b = 0; b < 8; b++) w[j] |= uint64_t(enc[8 * j + b]) << (8 * b);
    }
    w[3] &= 0x7fffffffffffffffULL;
    for (int limb = 0; limb < 20; limb++) {
      int bit = limb * 13;
      int word = bit >> 6, off = bit & 63;
      uint64_t v = w[word] >> off;
      if (off > 64 - 13 && word < 3) v |= w[word + 1] << (64 - off);
      op[20 * i + limb] = int32_t(v & 0x1fff);
    }
  }
  PyBuffer_Release(&view);
  return out;
}

// pack_bits_le(scalars: bytes (n*32), n: int, nbits: int)
//   -> bytes (nbits * n int32 LE), transposed for the ladder.
static PyObject *py_pack_bits_le(PyObject *, PyObject *args) {
  Py_buffer view;
  Py_ssize_t n;
  int nbits;
  if (!PyArg_ParseTuple(args, "y*ni", &view, &n, &nbits)) return nullptr;
  if (view.len < n * 32 || nbits > 256) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "bad buffer/nbits");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)nbits * n * 4);
  if (!out) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  int32_t *op = (int32_t *)PyBytes_AS_STRING(out);
  const uint8_t *ip = (const uint8_t *)view.buf;
  for (Py_ssize_t i = 0; i < n; i++) {
    const uint8_t *s = ip + 32 * i;
    for (int b = 0; b < nbits; b++) {
      op[(Py_ssize_t)b * n + i] = (s[b >> 3] >> (b & 7)) & 1;
    }
  }
  PyBuffer_Release(&view);
  return out;
}


// --------------------------------------------------------------------------
// Merlin transcripts on STROBE-128 / Keccak-f[1600] — the sr25519
// (schnorrkel) challenge computation, which dominates host-side cost of
// the device sr25519 lane (pure-Python merlin is ~3 ms/signature; this is
// ~2 us). Mirrors crypto/_merlin.py bit-for-bit (differentially tested).

namespace merlin {

static const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

static inline uint64_t rotl64(uint64_t v, int n) {
  return n ? (v << n) | (v >> (64 - n)) : v;
}

static const int ROTC[5][5] = {{0, 36, 3, 41, 18},
                               {1, 44, 10, 45, 2},
                               {62, 6, 43, 15, 61},
                               {28, 55, 25, 21, 56},
                               {27, 20, 39, 8, 14}};

static void keccak_f1600(uint8_t state[200]) {
  uint64_t lanes[5][5];
  for (int x = 0; x < 5; x++)
    for (int y = 0; y < 5; y++)
      memcpy(&lanes[x][y], state + 8 * (x + 5 * y), 8);
  for (int r = 0; r < 24; r++) {
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; x++)
      c[x] = lanes[x][0] ^ lanes[x][1] ^ lanes[x][2] ^ lanes[x][3] ^ lanes[x][4];
    for (int x = 0; x < 5; x++)
      d[x] = c[(x + 4) % 5] ^ rotl64(c[(x + 1) % 5], 1);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++) lanes[x][y] ^= d[x];
    uint64_t b[5][5];
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        b[y][(2 * x + 3 * y) % 5] = rotl64(lanes[x][y], ROTC[x][y]);
    for (int x = 0; x < 5; x++)
      for (int y = 0; y < 5; y++)
        lanes[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y]);
    lanes[0][0] ^= RC[r];
  }
  for (int x = 0; x < 5; x++)
    for (int y = 0; y < 5; y++)
      memcpy(state + 8 * (x + 5 * y), &lanes[x][y], 8);
}

static const int STROBE_R = 166;
static const uint8_t F_I = 1, F_A = 1 << 1, F_C = 1 << 2, F_M = 1 << 4,
                     F_K = 1 << 5;

struct Strobe {
  uint8_t state[200];
  int pos, pos_begin;

  void run_f() {
    state[pos] ^= (uint8_t)pos_begin;
    state[pos + 1] ^= 0x04;
    state[STROBE_R + 1] ^= 0x80;
    keccak_f1600(state);
    pos = 0;
    pos_begin = 0;
  }

  void absorb(const uint8_t *d, size_t n) {
    for (size_t i = 0; i < n; i++) {
      state[pos] ^= d[i];
      if (++pos == STROBE_R) run_f();
    }
  }

  void squeeze(uint8_t *out, size_t n) {
    for (size_t i = 0; i < n; i++) {
      out[i] = state[pos];
      state[pos] = 0;
      if (++pos == STROBE_R) run_f();
    }
  }

  void begin_op(uint8_t flags) {
    uint8_t old_begin = (uint8_t)pos_begin;
    pos_begin = pos + 1;
    uint8_t hdr[2] = {old_begin, flags};
    absorb(hdr, 2);
    if ((flags & (F_C | F_K)) && pos != 0) run_f();
  }

  void meta_ad(const uint8_t *d, size_t n, bool more) {
    if (!more) begin_op(F_M | F_A);
    absorb(d, n);
  }

  void ad(const uint8_t *d, size_t n) {
    begin_op(F_A);
    absorb(d, n);
  }

  void prf(uint8_t *out, size_t n) {
    begin_op(F_I | F_A | F_C);
    squeeze(out, n);
  }

  void init(const uint8_t *label, size_t n) {
    memset(state, 0, 200);
    const uint8_t hdr[6] = {1, STROBE_R + 2, 1, 0, 1, 12 * 8};
    memcpy(state, hdr, 6);
    memcpy(state + 6, "STROBEv1.0.2", 12);
    keccak_f1600(state);
    pos = 0;
    pos_begin = 0;
    meta_ad(label, n, false);
  }
};

static void append_message(Strobe &s, const uint8_t *label, size_t ln,
                           const uint8_t *msg, size_t mn) {
  uint8_t le[4] = {(uint8_t)(mn & 0xff), (uint8_t)((mn >> 8) & 0xff),
                   (uint8_t)((mn >> 16) & 0xff), (uint8_t)((mn >> 24) & 0xff)};
  s.meta_ad(label, ln, false);
  s.meta_ad(le, 4, true);
  s.ad(msg, mn);
}

}  // namespace merlin

// sr25519_challenges(ctx, pubs, rs, msgs) -> n x 64-byte challenge bytes.
static PyObject *py_sr25519_challenges(PyObject *, PyObject *args) {
  const char *ctx_buf, *pubs, *rs;
  Py_ssize_t ctx_len, pubs_len, rs_len;
  PyObject *msgs;
  if (!PyArg_ParseTuple(args, "y#y#y#O", &ctx_buf, &ctx_len, &pubs, &pubs_len,
                        &rs, &rs_len, &msgs))
    return nullptr;
  PyObject *seq = PySequence_Fast(msgs, "expected a sequence of messages");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (pubs_len != 32 * n || rs_len != 32 * n) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "pubs/rs must be n*32 bytes");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 64);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *m;
    Py_ssize_t mlen;
    if (PyBytes_AsStringAndSize(item, &m, &mlen) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    merlin::Strobe s;
    s.init((const uint8_t *)"Merlin v1.0", 11);
    merlin::append_message(s, (const uint8_t *)"dom-sep", 7,
                           (const uint8_t *)"SigningContext", 14);
    merlin::append_message(s, (const uint8_t *)"", 0, (const uint8_t *)ctx_buf,
                           (size_t)ctx_len);
    merlin::append_message(s, (const uint8_t *)"sign-bytes", 10,
                           (const uint8_t *)m, (size_t)mlen);
    merlin::append_message(s, (const uint8_t *)"proto-name", 10,
                           (const uint8_t *)"Schnorr-sig", 11);
    merlin::append_message(s, (const uint8_t *)"sign:pk", 7,
                           (const uint8_t *)(pubs + 32 * i), 32);
    merlin::append_message(s, (const uint8_t *)"sign:R", 6,
                           (const uint8_t *)(rs + 32 * i), 32);
    uint8_t le[4] = {64, 0, 0, 0};
    s.meta_ad((const uint8_t *)"sign:c", 6, false);
    s.meta_ad(le, 4, true);
    s.prf(dst + 64 * i, 64);
  }
  Py_DECREF(seq);
  return out;
}

// OpenSSL's asm SHA-512 when libcrypto is present (no dev headers in the
// image, so resolve the one-shot SHA512() via dlopen; the scalar
// implementation above is the fallback and the differential-test oracle).
#include <dlfcn.h>
typedef unsigned char *(*ossl_sha512_fn)(const unsigned char *, size_t,
                                         unsigned char *);
static ossl_sha512_fn ossl_sha512() {
  static ossl_sha512_fn fn = []() -> ossl_sha512_fn {
    void *h = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_LOCAL);
    if (!h) h = dlopen("libcrypto.so", RTLD_NOW | RTLD_LOCAL);
    if (!h) return nullptr;
    return (ossl_sha512_fn)dlsym(h, "SHA512");
  }();
  return fn;
}

// ed25519_challenges(rs: n*32 bytes, pubs: n*32 bytes, msgs: seq[bytes])
//   -> bytes (n*32): k_i = SHA512(R_i || A_i || M_i) mod L, little-endian.
static PyObject *py_ed25519_challenges(PyObject *, PyObject *args) {
  Py_buffer rs, pubs;
  PyObject *msgs;
  int no_ossl = 0;  // tests force the scalar fallback path
  if (!PyArg_ParseTuple(args, "y*y*O|p", &rs, &pubs, &msgs, &no_ossl))
    return nullptr;
  PyObject *seq = PySequence_Fast(msgs, "expected a sequence of messages");
  if (!seq) {
    PyBuffer_Release(&rs);
    PyBuffer_Release(&pubs);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (rs.len < 32 * n || pubs.len < 32 * n) {
    Py_DECREF(seq);
    PyBuffer_Release(&rs);
    PyBuffer_Release(&pubs);
    PyErr_SetString(PyExc_ValueError, "rs/pubs must be at least n*32 bytes");
    return nullptr;
  }
  PyObject *out = PyBytes_FromStringAndSize(nullptr, n * 32);
  if (!out) {
    Py_DECREF(seq);
    PyBuffer_Release(&rs);
    PyBuffer_Release(&pubs);
    return nullptr;
  }
  uint8_t *dst = (uint8_t *)PyBytes_AS_STRING(out);
  const uint8_t *rp = (const uint8_t *)rs.buf;
  const uint8_t *pp = (const uint8_t *)pubs.buf;
  ossl_sha512_fn fast = no_ossl ? nullptr : ossl_sha512();
  std::vector<uint8_t> cat;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *item = PySequence_Fast_GET_ITEM(seq, i);
    char *m;
    Py_ssize_t mlen;
    if (PyBytes_AsStringAndSize(item, &m, &mlen) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      PyBuffer_Release(&rs);
      PyBuffer_Release(&pubs);
      return nullptr;
    }
    uint8_t digest[64];
    if (fast) {
      cat.resize(64 + size_t(mlen));
      memcpy(cat.data(), rp + 32 * i, 32);
      memcpy(cat.data() + 32, pp + 32 * i, 32);
      if (mlen) memcpy(cat.data() + 64, m, size_t(mlen));
      fast(cat.data(), cat.size(), digest);
    } else {
      sha512::Ctx c;
      sha512::init(&c);
      sha512::update(&c, rp + 32 * i, 32);
      sha512::update(&c, pp + 32 * i, 32);
      sha512::update(&c, (const uint8_t *)m, size_t(mlen));
      sha512::final(&c, digest);
    }
    sha512::mod_l(digest, dst + 32 * i);
  }
  Py_DECREF(seq);
  PyBuffer_Release(&rs);
  PyBuffer_Release(&pubs);
  return out;
}

static PyMethodDef Methods[] = {
    {"ed25519_challenges", py_ed25519_challenges, METH_VARARGS,
     "Batch k = SHA512(R||A||M) mod L challenge scalars (32B LE each)"},
    {"merkle_root", py_merkle_root, METH_VARARGS,
     "RFC-6962 merkle root of a list of byte strings"},
    {"sha256_many", py_sha256_many, METH_VARARGS,
     "SHA-256 of each item, concatenated"},
    {"pack_le_limbs", py_pack_le_limbs, METH_VARARGS,
     "pack 32B LE encodings into 13-bit limb arrays"},
    {"sr25519_challenges", py_sr25519_challenges, METH_VARARGS,
     "Batch merlin signing-transcript challenges for sr25519 verification"},
    {"pack_bits_le", py_pack_bits_le, METH_VARARGS,
     "pack 32B LE scalars into transposed bit arrays"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "tm_native",
                                       nullptr, -1, Methods};

PyMODINIT_FUNC PyInit_tm_native(void) { return PyModule_Create(&moduledef); }
