"""Benchmark: VerifyCommit hot path — 10k-validator ed25519 commit.

BASELINE.md north star: device batch verification vs the host per-signature
path (OpenSSL via `cryptography`, the fastest CPU verifier available here;
the reference's Go crypto/batch cannot run in this image — no Go toolchain).

Prints ONE JSON line:
  {"metric": "verify_commit_10k", "value": <device sigs/s>,
   "unit": "sigs/s", "vs_baseline": <device/host speedup>}

Timing is end-to-end per batch (host prep: SHA-512 challenge scalars +
limb packing + transfer, then the device ladder) — what VerifyCommit
actually pays per commit.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    import jax

    backend_kind = jax.default_backend()
    on_accel = backend_kind not in ("cpu",)
    n_sigs = int(os.environ.get("TM_TPU_BENCH_SIGS", "10000" if on_accel else "512"))

    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.ops import backend

    # Build a synthetic 10k-validator commit: unique keys, ~120B canonical
    # vote-sized messages (types/vote.go:93 sign bytes scale).
    entries = []
    msg_pad = b"\x08\x02\x10\x01" + b"p" * 100
    for i in range(n_sigs):
        sk = ed25519.gen_priv_key(i.to_bytes(32, "little"))
        msg = i.to_bytes(8, "big") + msg_pad
        entries.append((sk.pub_key().bytes(), msg, sk.sign(msg)))

    # Host baseline: per-signature OpenSSL verify (ZIP-215 fast path).
    n_base = min(n_sigs, 2000)
    t0 = time.perf_counter()
    ok = all(
        ed25519.verify_zip215_fast(p, m, s) for p, m, s in entries[:n_base]
    )
    host_s = (time.perf_counter() - t0) / n_base
    assert ok

    # Device path: warm up (compile), then steady-state.
    bucket = backend._bucket_for(n_sigs)
    t0 = time.perf_counter()
    res = backend.verify_batch(entries)
    warm = time.perf_counter() - t0
    assert bool(res.all()), "all benchmark signatures must verify"

    reps = 3 if on_accel else 1
    t0 = time.perf_counter()
    for _ in range(reps):
        backend.verify_batch(entries)
    dev_s = (time.perf_counter() - t0) / reps / n_sigs

    out = {
        "metric": f"verify_commit_{n_sigs}",
        "value": round(1.0 / dev_s, 1),
        "unit": "sigs/s",
        "vs_baseline": round(host_s / dev_s, 3),
    }
    print(json.dumps(out))
    print(
        f"# backend={backend_kind} bucket={bucket} warmup={warm:.1f}s "
        f"host={1.0/host_s:.0f} sigs/s device={1.0/dev_s:.0f} sigs/s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
